"""Pallas TPU kernels.

Each kernel fuses one estimator hot loop into a single VMEM-resident pass
over row tiles (grid over the instance-block rows, accumulating into revisited
output blocks — the standard Pallas reduction pattern):

Measured on a v5e chip (131072×512 f32 blocks, 50-eval jit chain):
the XLA-fused aggregator path and these kernels land within ~1.5× of each
other (XLA slightly ahead), confirming SURVEY §2.6's call that jit fusion
already covers the netlib-BLAS boundary for gemv-shaped MLlib workloads.
The estimators therefore default to the jnp aggregators; these kernels are
the escape hatch for shapes XLA schedules poorly and the foundation for
genuinely fusion-resistant ops, and their parity is pinned by tests in both
interpret mode (CPU) and native Mosaic lowering (bench/verify on hardware).

- ``fused_binary_logistic``: the north-star hot loop (ref:
  BinaryLogisticBlockAggregator.scala:41 — forward gemv :97, multiplier :112,
  transpose gemv :130) as margin→softplus-loss→multiplier→grad in one kernel.
- ``fused_kmeans_assign``: the KMeans distance+argmin inner loop (ref:
  DistanceMeasure.findClosest:123) as ‖x‖²−2x·c+‖c‖² with a fused argmin.
- ``fused_gramian``: XᵀX accumulation (ref: RowMatrix.computeGramianMatrix:130
  — the treeAggregate of spr:147 rank-1 updates, batched onto the MXU).

All wrappers pad rows to the tile size and features to the 128-lane boundary,
and run anywhere via ``interpret=True`` (the CPU test path; on TPU the same
code lowers to Mosaic).
"""

from __future__ import annotations

import functools
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl

ROW_TILE = 256
LANE = 128


def pallas_available() -> bool:
    """True when the default backend lowers Pallas natively (TPU)."""
    try:
        return jax.default_backend() == "tpu"
    except Exception:
        return False


def use_fused_kernels(ctx) -> bool:
    """Whether the eligible dense sweeps route through the fused Pallas
    kernels: ``cyclone.ml.usePallasKernels`` 'auto' (default) says yes on
    natively-lowered backends (TPU) — the fused kernels ARE the default
    sweep there — and no elsewhere (the interpreter exists for tests, not
    speed); 'true'/'false' force one path everywhere."""
    try:
        from cycloneml_tpu.conf import USE_PALLAS_KERNELS
        conf = getattr(ctx, "conf", None)
        mode = (str(conf.get(USE_PALLAS_KERNELS)).lower()
                if conf is not None else "auto")
    except Exception:
        mode = "auto"
    if mode == "true":
        return True
    if mode == "false":
        return False
    return pallas_available()


def _storage_width(x):
    """Keep narrow (bf16/f16/fp8) DATA-tier blocks at storage width — the
    whole point of the tier is that HBM sees 1-2 bytes per element — and
    cast full-width inputs to the kernels' f32 accumulator dtype. The
    kernels upcast narrow tiles to f32 INSIDE VMEM (a vector convert per
    tile, never an HBM materialization); fp8 tiles additionally apply
    their per-column dequantization scale per VMEM block (the ``x_scale``
    operand — one VPU multiply on a resident tile)."""
    from cycloneml_tpu.dataset.instance import is_narrow_dtype
    x = jnp.asarray(x)
    if is_narrow_dtype(x.dtype):
        return x
    return x.astype(jnp.float32)


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def _auto_row_tile(n: int, row_tile: int) -> int:
    """A tile that DIVIDES n when one exists: row padding copies the whole
    X operand (at bench scale that is a second ~10 GB HBM allocation — an
    OOM, not a slowdown), so dividing beats the default tile size. Falls
    back to the requested tile (with padding) for ns with no small
    divisor — loudly, when the operand is big enough for the copy to
    matter."""
    if n % row_tile == 0:
        return row_tile
    for t in (1024, 512, 256, 128, 64, 32, 16, 8):
        if n >= t and n % t == 0:
            return t
    if n > 1 << 20:
        import warnings
        warnings.warn(
            f"pallas kernel: no row tile divides n={n}; padding will COPY "
            "the full operand in HBM — pad the input to a multiple of 8 "
            "rows upstream to avoid it")
    return row_tile


def _pad_scale(scale, d: int, d_pad: int):
    """Per-column fp8 dequant scales as a (1, d_pad) f32 block (padding
    columns carry 1.0 — their x entries are zero anyway)."""
    s = jnp.asarray(scale, jnp.float32).reshape(-1)
    if s.shape[0] != d:
        raise ValueError(f"x_scale has {s.shape[0]} entries, expected {d}")
    return jnp.pad(s, (0, d_pad - d), constant_values=1.0).reshape(1, d_pad)


def _pad_rows_cols(x, y, w, row_tile: int):
    """Zero-pad rows to the tile multiple and features to the lane multiple;
    padding rows carry w=0 so they contribute nothing to any sum. The row
    tile is re-chosen to DIVIDE n when possible (see _auto_row_tile) and
    returned — row padding copies the whole X operand otherwise."""
    n, d = x.shape
    row_tile = _auto_row_tile(n, row_tile)
    n_pad, d_pad = _pad_to(max(n, row_tile), row_tile), _pad_to(d, LANE)
    if n_pad != n or d_pad != d:
        x = jnp.pad(x, ((0, n_pad - n), (0, d_pad - d)))
        y = jnp.pad(y, (0, n_pad - n))
        w = jnp.pad(w, (0, n_pad - n))
    return x, y, w, n_pad, d_pad, row_tile


# -- fused binary logistic loss + gradient -------------------------------------

def fused_binary_logistic(x, y, w, coef, d: int, fit_intercept: bool = True,
                          interpret: Optional[bool] = None,
                          row_tile: int = ROW_TILE,
                          x_scale=None) -> Dict[str, jnp.ndarray]:
    """Drop-in for the ``aggregators.binary_logistic`` block math: one pass
    over HBM computing {loss, grad, count} sums for the shard. Narrow
    (bf16/fp8) data-tier blocks are read at storage width and upcast to
    the f32 accumulator per VMEM tile — half (bf16) or a quarter (fp8) of
    the HBM traffic of an f32 sweep, no wide X copy anywhere. ``x_scale``
    is the fp8 tier's per-column dequantization vector, applied in-kernel
    per VMEM block."""
    if interpret is None:
        interpret = not pallas_available()
    dtype = jnp.float32
    x = _storage_width(x)
    y = jnp.asarray(y, dtype)
    w = jnp.asarray(w, dtype)
    coef = jnp.asarray(coef, dtype)
    beta = coef[:d] if fit_intercept else coef
    b0 = coef[d] if fit_intercept else jnp.zeros((), dtype)

    x, y, w, n_pad, d_pad, row_tile = _pad_rows_cols(x, y, w, row_tile)
    beta_p = jnp.pad(beta, (0, d_pad - d)).reshape(1, d_pad)
    grid = (n_pad // row_tile,)

    kernel = functools.partial(
        _run_glm, kind="logistic", row_tile=row_tile, d_pad=d_pad,
        grid=grid, interpret=interpret,
        scale=None if x_scale is None else _pad_scale(x_scale, d, d_pad))
    loss, grad_row, aux = kernel(x, y.reshape(-1, 1), w.reshape(-1, 1),
                                 beta_p, b0, jnp.zeros((), dtype))
    g = grad_row[0, :d]
    if fit_intercept:
        grad = jnp.concatenate([g, aux[0, 0][None]])
    else:
        grad = g
    return {"loss": loss[0, 0], "grad": grad, "count": aux[0, 1]}


def fused_binary_logistic_scaled(x, y, w, inv_std, scaled_mean, coef,
                                 d: int, fit_intercept: bool = True,
                                 interpret: Optional[bool] = None,
                                 row_tile: int = ROW_TILE,
                                 x_scale=None) -> Dict[str, jnp.ndarray]:
    """Folded-standardization twin of :func:`fused_binary_logistic`: the
    kernel reads RAW feature rows — no standardized copy — because the
    scaling is algebra OUTSIDE the row pass:

      margin = x·(inv_std∘β) + (β₀ − scaled_mean·β)   (scaled vector +
                                                       offset fold into the
                                                       kernel's β/β₀ slots)
      grad_β̂ = inv_std∘(Σ mult·x) − scaled_mean·Σmult (O(d) correction on
                                                       the kernel's raw sums)

    Same contract as ``aggregators.binary_logistic_scaled``; the kernel
    itself is byte-identical to the unscaled one, so the A/B numbers carry.
    """
    if interpret is None:
        interpret = not pallas_available()
    dtype = jnp.float32
    x = _storage_width(x)
    y = jnp.asarray(y, dtype)
    w = jnp.asarray(w, dtype)
    coef = jnp.asarray(coef, dtype)
    inv_std = jnp.asarray(inv_std, dtype)
    scaled_mean = jnp.asarray(scaled_mean, dtype)
    beta = coef[:d] if fit_intercept else coef
    b0 = coef[d] if fit_intercept else jnp.zeros((), dtype)
    sb = inv_std * beta
    off = b0 - jnp.dot(scaled_mean, beta)

    x, y, w, n_pad, d_pad, row_tile = _pad_rows_cols(x, y, w, row_tile)
    beta_p = jnp.pad(sb, (0, d_pad - d)).reshape(1, d_pad)
    grid = (n_pad // row_tile,)
    kernel = functools.partial(
        _run_glm, kind="logistic", row_tile=row_tile, d_pad=d_pad,
        grid=grid, interpret=interpret,
        scale=None if x_scale is None else _pad_scale(x_scale, d, d_pad))
    loss, grad_row, aux = kernel(x, y.reshape(-1, 1), w.reshape(-1, 1),
                                 beta_p, off, jnp.zeros((), dtype))
    msum = aux[0, 0]
    g = inv_std * grad_row[0, :d] - scaled_mean * msum
    if fit_intercept:
        grad = jnp.concatenate([g, msum[None]])
    else:
        grad = g
    return {"loss": loss[0, 0], "grad": grad, "count": aux[0, 1]}


def fused_least_squares_scaled(x, y, w, inv_std, scaled_mean, y_pars, coef,
                               d: int, interpret: Optional[bool] = None,
                               row_tile: int = ROW_TILE,
                               x_scale=None) -> Dict[str, jnp.ndarray]:
    """Fused least-squares loss/grad sweep — the kernel twin of
    ``aggregators.least_squares_scaled`` (the LinearRegression l-bfgs
    objective). The kernel reads RAW data-tier rows once (margin → residual
    → loss/multiplier/grad in one VMEM-resident pass); the doubly-
    standardized objective is algebra OUTSIDE the row pass:

      margin = x·(inv_std∘β) − (scaled_mean·β − ȳ̂)   (β/offset slots)
      err    = margin − y·(1/σ_y)                      (ys scalar slot)
      grad_β̂ = inv_std∘(Σ mult·x) − scaled_mean·Σmult

    ``y_pars = [1/σ_y, ȳ̂]``; no intercept coordinate exists (recovered in
    closed form by the caller). Same Kahan-compensated grid accumulation
    as the logistic kernel."""
    if interpret is None:
        interpret = not pallas_available()
    dtype = jnp.float32
    x = _storage_width(x)
    y = jnp.asarray(y, dtype)
    w = jnp.asarray(w, dtype)
    coef = jnp.asarray(coef, dtype)
    inv_std = jnp.asarray(inv_std, dtype)
    scaled_mean = jnp.asarray(scaled_mean, dtype)
    y_pars = jnp.asarray(y_pars, dtype)
    sb = inv_std * coef
    off = y_pars[1] - jnp.dot(scaled_mean, coef)  # rides the b0 slot

    x, y, w, n_pad, d_pad, row_tile = _pad_rows_cols(x, y, w, row_tile)
    beta_p = jnp.pad(sb, (0, d_pad - d)).reshape(1, d_pad)
    grid = (n_pad // row_tile,)
    kernel = functools.partial(
        _run_glm, kind="squared", row_tile=row_tile, d_pad=d_pad,
        grid=grid, interpret=interpret,
        scale=None if x_scale is None else _pad_scale(x_scale, d, d_pad))
    loss, grad_row, aux = kernel(x, y.reshape(-1, 1), w.reshape(-1, 1),
                                 beta_p, off, y_pars[0])
    msum = aux[0, 0]
    g = inv_std * grad_row[0, :d] - scaled_mean * msum
    return {"loss": loss[0, 0], "grad": g, "count": aux[0, 1]}


def _run_glm(x, y, w, beta_p, b0, ys, *, kind, row_tile, d_pad, grid,
             interpret, scale=None):
    """Shared one-pass GLM row sweep: margin → per-row loss/multiplier →
    grad, with ``kind`` selecting the link ("logistic" softplus/sigmoid,
    "squared" residual). ``ys`` is the label scale (squared only; the
    logistic path carries a zero). X tiles arrive at STORAGE width (bf16
    or fp8 when the data tier is narrow) and upcast to the f32
    accumulator in VMEM — the bytes HBM sees per sweep are exactly the
    tier's. ``scale`` (optional, (1, d_pad)) is the fp8 tier's per-column
    dequantization vector, applied to every upcast VMEM block (one VPU
    broadcast-multiply per tile); ``scale=None`` compiles the pre-fp8
    kernel byte-for-byte."""
    has_scale = scale is not None

    def kern(*refs):
        if has_scale:
            (b0_ref, ys_ref, x_ref, y_ref, w_ref, beta_ref, s_ref,
             loss_ref, grad_ref, aux_ref,
             closs_ref, cgrad_ref, caux_ref) = refs
        else:
            (b0_ref, ys_ref, x_ref, y_ref, w_ref, beta_ref,
             loss_ref, grad_ref, aux_ref,
             closs_ref, cgrad_ref, caux_ref) = refs
            s_ref = None
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            # full-block stores only: Mosaic rejects scalar VMEM stores
            loss_ref[:] = jnp.zeros_like(loss_ref)
            aux_ref[:] = jnp.zeros_like(aux_ref)
            grad_ref[:] = jnp.zeros_like(grad_ref)
            closs_ref[:] = jnp.zeros_like(closs_ref)
            cgrad_ref[:] = jnp.zeros_like(cgrad_ref)
            caux_ref[:] = jnp.zeros_like(caux_ref)

        # fp32 accumulator tier from here on: the convert is a VPU op on a
        # VMEM-resident tile, not an HBM materialization
        xv = x_ref[:].astype(jnp.float32)
        if s_ref is not None:
            # fp8 dequant per VMEM block: codes * per-column scale
            xv = xv * s_ref[:]
        yv = y_ref[:]          # (T, 1) — Mosaic rejects 1-D blocks that
        wv = w_ref[:]          # don't align to the T(1024) XLA layout
        # matvecs with a width-1 output don't lower to the MXU (Mosaic:
        # non-constant reduction accumulator); broadcast-multiply + reduce on
        # the VPU instead — the pass is HBM-bound, not FLOP-bound
        margin = jnp.sum(xv * beta_ref[:], axis=1,
                         keepdims=True) + b0_ref[0, 0]       # (T, 1)
        if kind == "logistic":
            mult = wv * (jax.nn.sigmoid(margin) - yv)
            v_loss = jnp.sum(wv * (jax.nn.softplus(margin)
                                   - yv * margin)).reshape(1, 1)
        else:  # squared (least-squares residual)
            err = margin - ys_ref[0, 0] * yv
            mult = wv * err
            v_loss = (0.5 * jnp.sum(wv * err * err)).reshape(1, 1)
        v_aux = jnp.concatenate(
            [jnp.sum(mult)[None], jnp.sum(wv)[None]]).reshape(1, 2)
        v_grad = jnp.sum(mult * xv, axis=0, keepdims=True)
        # Kahan-compensated accumulation across the (sequential) grid: a
        # plain f32 `+=` over thousands of row tiles drifts ~n_tiles ulps,
        # which is enough to break the strong-Wolfe first-try acceptance
        # when this kernel feeds the chunked device L-BFGS (measured: 46
        # line-search evals vs 10 for the tree-reducing XLA path at
        # n=2M×d=1280). The running compensation keeps the total at ~1 ulp
        # — cheaper than the XLA tree and exact enough for the Wolfe tests.
        for acc, comp, v in ((loss_ref, closs_ref, v_loss),
                             (grad_ref, cgrad_ref, v_grad),
                             (aux_ref, caux_ref, v_aux)):
            yk = v - comp[:]
            t = acc[:] + yk
            comp[:] = (t - acc[:]) - yk
            acc[:] = t

    in_specs = [
        pl.BlockSpec((1, 1), lambda i: (0, 0)),          # b0 / -offset
        pl.BlockSpec((1, 1), lambda i: (0, 0)),          # label scale
        pl.BlockSpec((row_tile, d_pad), lambda i: (i, 0)),
        pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
        pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
        pl.BlockSpec((1, d_pad), lambda i: (0, 0)),      # beta
    ]
    args = [b0.reshape(1, 1), ys.reshape(1, 1), x, y, w, beta_p]
    if has_scale:
        in_specs.append(pl.BlockSpec((1, d_pad), lambda i: (0, 0)))
        args.append(scale)
    outs = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, 2), lambda i: (0, 0)),
            pl.BlockSpec((1, 1), lambda i: (0, 0)),
            pl.BlockSpec((1, d_pad), lambda i: (0, 0)),
            pl.BlockSpec((1, 2), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, d_pad), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return outs[:3]


# -- fused KMeans assignment ----------------------------------------------------

def fused_kmeans_assign(x, centers, interpret: Optional[bool] = None,
                        row_tile: int = ROW_TILE, x_scale=None):
    """Nearest-center assignment: returns (best_idx (n,), min_dist² (n,)).
    Fuses ‖x‖² − 2x·cᵀ + ‖c‖² with the argmin so the (T, k) distance tile
    never leaves VMEM (ref: DistanceMeasure.findClosest:123). bf16 point
    blocks stay at storage width in HBM — the tile upcasts to f32 in VMEM
    for the distance accumulation, so narrowing the tier no longer costs a
    full-X fp32 materialization per Lloyd step. fp8 point blocks pass
    their per-column dequant vector as ``x_scale``, applied to every
    upcast VMEM block before the distance math (centers stay f32 in
    original space)."""
    if interpret is None:
        interpret = not pallas_available()
    x = _storage_width(x)
    centers = jnp.asarray(centers, jnp.float32)
    n, d = x.shape
    k = centers.shape[0]
    row_tile = _auto_row_tile(n, row_tile)
    n_pad = _pad_to(max(n, row_tile), row_tile)
    d_pad = _pad_to(d, LANE)
    k_pad = _pad_to(k, 8)
    x_p = jnp.pad(x, ((0, n_pad - n), (0, d_pad - d)))
    c_p = jnp.pad(centers, ((0, k_pad - k), (0, d_pad - d)))
    # padded centers must never win the argmin
    c_norm = jnp.concatenate(
        [jnp.sum(c_p[:k] * c_p[:k], axis=1),
         jnp.full((k_pad - k,), jnp.inf, jnp.float32)]).reshape(1, k_pad)
    has_scale = x_scale is not None
    s_p = _pad_scale(x_scale, d, d_pad) if has_scale else None

    def kern(*refs):
        if has_scale:
            x_ref, c_ref, cn_ref, s_ref, best_ref, dist_ref = refs
        else:
            x_ref, c_ref, cn_ref, best_ref, dist_ref = refs
            s_ref = None
        xv = x_ref[:].astype(jnp.float32)                      # (T, d_pad)
        if s_ref is not None:
            xv = xv * s_ref[:]          # fp8 dequant per VMEM block
        # HIGHEST = multi-pass f32 on the MXU; default bf16 multiplies lose
        # near-tie argmins at ~1e-4 relative distance (ref computes in f64)
        prod = jnp.dot(xv, c_ref[:].T,
                       preferred_element_type=jnp.float32,
                       precision=jax.lax.Precision.HIGHEST)    # (T, k_pad)
        x2 = jnp.sum(xv * xv, axis=1, keepdims=True)           # (T, 1)
        d2 = x2 - 2.0 * prod + cn_ref[:]                       # (T, k_pad)
        best_ref[:] = jnp.argmin(d2, axis=1).astype(jnp.int32).reshape(-1, 1)
        dist_ref[:] = jnp.min(d2, axis=1).reshape(-1, 1)

    in_specs = [
        pl.BlockSpec((row_tile, d_pad), lambda i: (i, 0)),
        pl.BlockSpec((k_pad, d_pad), lambda i: (0, 0)),
        pl.BlockSpec((1, k_pad), lambda i: (0, 0)),
    ]
    args = [x_p, c_p, c_norm]
    if has_scale:
        in_specs.append(pl.BlockSpec((1, d_pad), lambda i: (0, 0)))
        args.append(s_p)
    best, dist = pl.pallas_call(
        kern,
        grid=(n_pad // row_tile,),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
            pl.BlockSpec((row_tile, 1), lambda i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n_pad, 1), jnp.int32),
            jax.ShapeDtypeStruct((n_pad, 1), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return best[:n, 0], jnp.maximum(dist[:n, 0], 0.0)


# -- fused Gramian --------------------------------------------------------------

def fused_gramian(x, w=None, interpret: Optional[bool] = None,
                  row_tile: int = ROW_TILE, x_scale=None):
    """XᵀX over row tiles, accumulated in a revisited VMEM block (ref:
    RowMatrix.computeGramianMatrix:130 — spr rank-1 updates become one MXU
    matmul per tile). bf16 blocks are read at storage width and upcast per
    VMEM tile into the f32 accumulator; fp8 blocks additionally apply
    their per-column ``x_scale`` to each upcast VMEM block, so the
    accumulated Gramian is already in value space. ``w`` (optional
    per-row weights) masks padding/invalid rows by presence (w > 0)
    INSIDE the kernel — the jnp path's ``x * (w > 0)`` row mask without
    the masked X copy."""
    if interpret is None:
        interpret = not pallas_available()
    x = _storage_width(x)
    n, d = x.shape
    if w is None:
        w = jnp.ones((n,), jnp.float32)
    w = jnp.asarray(w, jnp.float32)
    row_tile = _auto_row_tile(n, row_tile)
    n_pad = _pad_to(max(n, row_tile), row_tile)
    d_pad = _pad_to(d, LANE)
    x_p = jnp.pad(x, ((0, n_pad - n), (0, d_pad - d)))
    w_p = jnp.pad(w, (0, n_pad - n)).reshape(-1, 1)
    has_scale = x_scale is not None
    s_p = _pad_scale(x_scale, d, d_pad) if has_scale else None

    def kern(*refs):
        if has_scale:
            x_ref, w_ref, s_ref, out_ref = refs
        else:
            x_ref, w_ref, out_ref = refs
            s_ref = None
        i = pl.program_id(0)

        @pl.when(i == 0)
        def _():
            out_ref[:] = jnp.zeros_like(out_ref)

        xv = x_ref[:].astype(jnp.float32)
        if s_ref is not None:
            xv = xv * s_ref[:]          # fp8 dequant per VMEM block
        xv = xv * (w_ref[:] > 0).astype(jnp.float32)
        out_ref[:] += jnp.dot(xv.T, xv, preferred_element_type=jnp.float32,
                              precision=jax.lax.Precision.HIGHEST)

    in_specs = [pl.BlockSpec((row_tile, d_pad), lambda i: (i, 0)),
                pl.BlockSpec((row_tile, 1), lambda i: (i, 0))]
    args = [x_p, w_p]
    if has_scale:
        in_specs.append(pl.BlockSpec((1, d_pad), lambda i: (0, 0)))
        args.append(s_p)
    g = pl.pallas_call(
        kern,
        grid=(n_pad // row_tile,),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((d_pad, d_pad), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((d_pad, d_pad), jnp.float32),
        interpret=interpret,
    )(*args)
    return g[:d, :d]
