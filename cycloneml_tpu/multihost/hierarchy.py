"""Hierarchical DCN×ICI mesh construction.

A multi-process mesh has two interconnect tiers: devices inside one
process/host talk over ICI (fast, contended by nothing else), processes
talk over DCN (slower, the cross-slice hop). The grid this module builds
makes the tier boundary a MESH AXIS boundary:

- ``replica`` (axis 0) strides across PROCESS boundaries — each replica
  row is one process's device set, so a psum over ``replica`` is exactly
  the DCN hop;
- ``data`` and ``model`` (axes 1/2) stay inside one process's local
  devices — psums over them ride ICI only.

On the CPU smoke the process boundary stands in for DCN and the virtual
local devices for ICI; on a TPU pod the same construction puts slices on
rows. ``collectives.psum_over_mesh`` reduces ``data`` before ``replica``
so XLA schedules the ICI reduction before the slower DCN combine — the
two-level realization of the reference's ``treeAggregate`` depth
parameter (ref: RDD.scala:1223), and GSPMD sharding propagation composes
over the hierarchy without per-level rewrites (PAPERS.md, Xu et al.).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import numpy as np

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


def process_groups(devices) -> "OrderedDict[int, list]":
    """Devices grouped by owning process, insertion-ordered by process
    index — the DCN partition of the device set (one group per host on a
    pod; one group total in-process)."""
    groups: "OrderedDict[int, list]" = OrderedDict()
    for d in sorted(devices,
                    key=lambda d: (d.process_index, getattr(d, "id", 0))):
        groups.setdefault(d.process_index, []).append(d)
    return groups


def build_device_grid(devices, n_replicas: Optional[int] = None,
                      model_parallelism: int = 1
                      ) -> Tuple[np.ndarray, int]:
    """(replica, data, model) device grid with DCN-aligned replica rows.

    ``n_replicas=None`` (auto) gives one replica row per process — the
    layout where every cross-process collective is confined to the
    ``replica`` axis. An explicit ``n_replicas`` is honoured (slice
    stand-ins on a single process; aggregated rows on a pod) with a
    warning when rows would straddle a process boundary, since psums
    over the ICI axes then cross DCN.
    """
    groups = process_groups(devices)
    ordered = [d for g in groups.values() for d in g]
    n = len(ordered)
    n_procs = len(groups)
    if n_replicas is None or n_replicas <= 0:
        n_replicas = n_procs
    if n % (n_replicas * model_parallelism) != 0:
        raise ValueError(
            f"{n} devices not divisible by replicas({n_replicas}) x "
            f"model({model_parallelism})")
    data = n // (n_replicas * model_parallelism)
    grid = np.array(ordered).reshape(n_replicas, data, model_parallelism)
    if not dcn_aligned(grid):
        logger.warning(
            "mesh replica rows straddle process boundaries "
            "(%d replicas over %d processes): intra-row (ICI-axis) "
            "collectives will cross DCN — prefer n_replicas=%d",
            n_replicas, n_procs, n_procs)
    return grid, n_replicas


def dcn_aligned(grid: np.ndarray) -> bool:
    """True when no replica row mixes devices of two processes — every
    ICI-axis collective then stays inside one host. Trivially true on a
    single process (there is no DCN)."""
    for row in grid.reshape(grid.shape[0], -1):
        if len({d.process_index for d in row}) > 1:
            return False
    return True


def describe(grid: np.ndarray) -> Dict[str, object]:
    """Topology summary for logs / MeshUp events."""
    procs = sorted({d.process_index for d in grid.ravel()})
    return {
        "n_processes": len(procs),
        "dcn_aligned": dcn_aligned(grid),
        "replicas": int(grid.shape[0]),
        "data": int(grid.shape[1]),
        "model": int(grid.shape[2]) if grid.ndim > 2 else 1,
    }


def local_replica_rows(grid: np.ndarray, process_index: int) -> List[int]:
    """Replica-row indices whose devices this process owns (any overlap)
    — which DCN slices this host participates in."""
    rows = []
    for i, row in enumerate(grid.reshape(grid.shape[0], -1)):
        if any(d.process_index == process_index for d in row):
            rows.append(i)
    return rows
