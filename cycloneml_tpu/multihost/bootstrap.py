"""``jax.distributed`` lifecycle for multi-process meshes.

The control-plane leg of the multihost runtime (ref: the reference's
driver↔executor registration, SURVEY §3.1, collapsed into coordinator
rendezvous): every process of a multihost application calls
:func:`initialize` with the same coordinator address and its own process
index, after which ``jax.devices()`` is the GLOBAL device set and
cross-process collectives ride the backend fabric (DCN on a pod; gloo
over TCP on the CPU smoke).

Contracts this module owns:

- **Single-process no-op**: nothing here touches ``jax.distributed``
  unless a ``multihost[...]`` master (or an explicit call) asks for it —
  every in-core fit runs exactly as before.
- **Version compat**: ``jax.distributed.is_initialized`` does not exist
  on every supported jax (0.4.x has only ``initialize``/``shutdown``);
  :func:`is_initialized` reads the distributed global state instead.
  This was the root cause of the standing deploy-harness failures.
- **CPU-smoke collectives**: the XLA:CPU backend refuses multi-process
  programs unless a CPU collectives implementation is configured;
  :func:`initialize` selects gloo (``cyclone.multihost.cpuCollectives``)
  BEFORE the backend comes up, so 2-process CPU meshes are real meshes.
- **Coordinator preflight**: process 0 probes the coordinator port with
  a plain bind before handing it to the gRPC server — a taken port
  surfaces as a clean ``RuntimeError`` (the deploy master's relaunch
  machinery retries with a fresh port) instead of a native crash.
- **Barriered teardown**: :func:`shutdown` syncs every process at a
  coordination-service barrier before disconnecting, so no process
  tears down the backend while a peer is mid-collective.
  :func:`abandon` is the FAILURE-path teardown — no barrier (the peer
  is dead), bounded wait — used by MeshSupervisor's host-loss recovery.
"""

from __future__ import annotations

import os
import socket
import threading
from typing import List, Optional, Tuple

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: default CPU cross-process collectives implementation ("none" disables —
#: multi-process CPU programs then fail at dispatch, as stock XLA does)
DEFAULT_CPU_COLLECTIVES = "gloo"

#: default teardown-barrier timeout (ms); a dead peer bounds the graceful
#: path at this instead of hanging exit
DEFAULT_BARRIER_TIMEOUT_MS = 10_000

_lock = threading.Lock()
_barrier_seq = 0
_cpu_collectives = DEFAULT_CPU_COLLECTIVES
_barrier_timeout_ms = DEFAULT_BARRIER_TIMEOUT_MS


def configure(cpu_collectives: Optional[str] = None,
              barrier_timeout_ms: Optional[int] = None) -> None:
    """Install conf-driven defaults (CycloneContext calls this from
    ``cyclone.multihost.*`` before the mesh comes up; standalone callers
    that build the mesh first get the module defaults)."""
    global _cpu_collectives, _barrier_timeout_ms
    with _lock:
        if cpu_collectives is not None:
            _cpu_collectives = cpu_collectives
        if barrier_timeout_ms is not None:
            _barrier_timeout_ms = int(barrier_timeout_ms)


def is_initialized() -> bool:
    """True when this process is part of an initialized
    ``jax.distributed`` runtime. Compat shim: prefers the real API where
    it exists, else reads the distributed global state (jax 0.4.x)."""
    import jax
    probe = getattr(jax.distributed, "is_initialized", None)
    if probe is not None:
        try:
            return bool(probe())
        except Exception:  # pragma: no cover - defensive: fall through
            pass
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None) is not None
    except Exception:  # pragma: no cover - jax internals moved
        return False


def _client():
    """The distributed-runtime client, or None."""
    try:
        from jax._src import distributed as _dist
        return getattr(_dist.global_state, "client", None)
    except Exception:  # pragma: no cover
        return None


def _platform_hint() -> str:
    """The configured primary platform WITHOUT initializing backends
    (``jax.default_backend()`` would bring XLA up before the collectives
    implementation is chosen)."""
    import jax
    try:
        plats = jax.config.values.get("jax_platforms")
    except Exception:
        plats = None
    plats = plats or os.environ.get("JAX_PLATFORMS", "")
    return plats.split(",")[0].strip().lower() if plats else ""


def _enable_cpu_collectives() -> None:
    """Select the CPU cross-process collectives implementation BEFORE the
    backend exists — XLA:CPU otherwise rejects multi-process programs
    ('Multiprocess computations aren't implemented on the CPU backend')."""
    impl = _cpu_collectives
    if not impl or impl == "none":
        return
    import jax
    try:
        jax.config.update("jax_cpu_collectives_implementation", impl)
    except Exception:
        try:  # older spelling: a bare gloo switch
            jax.config.update("jax_cpu_enable_gloo_collectives", True)
        except Exception:
            logger.warning("no CPU collectives config in this jax; "
                           "cross-process CPU programs will fail")


def _preflight_coordinator_port(address: str) -> None:
    """Process 0 binds the coordinator port for a moment before gRPC
    does: a taken port becomes a clean, classifiable RuntimeError (the
    deploy layer relaunches with a fresh port) instead of a native
    server crash. The probe-to-bind window is the same one the deploy
    port pool already accepts."""
    host, sep, port = address.rpartition(":")
    if not sep or not port.isdigit():
        raise RuntimeError(
            f"multihost coordinator address {address!r} must be "
            f"<host>:<port>")
    try:
        with socket.socket() as s:
            s.bind((host or "127.0.0.1", int(port)))
    except OSError as e:
        raise RuntimeError(
            f"multihost coordinator port unavailable at {address}: {e}; "
            f"resubmit with a fresh port (the deploy master's relaunch "
            f"does this automatically)") from e


def probe_free_ports(n: int) -> List[int]:
    """``n`` DISTINCT free ports on this machine, all held open while
    collecting so the kernel cannot hand the same ephemeral port twice
    (briefly unreserved after close — the window every launcher that
    assigns ports ahead of bind accepts). The deploy Worker keeps its
    coordinator-port pool stocked through this."""
    socks = []
    try:
        for _ in range(n):
            s = socket.socket()
            s.bind(("", 0))
            socks.append(s)
        return [s.getsockname()[1] for s in socks]
    finally:
        for s in socks:
            s.close()


def initialize(coordinator_address: Optional[str] = None,
               num_processes: Optional[int] = None,
               process_id: Optional[int] = None) -> bool:
    """Join (or form) the distributed runtime. Returns True when THIS
    call initialized it, False when it already was. With no arguments,
    defers to jax's env/cloud auto-detection (TPU pod metadata)."""
    import jax
    with _lock:
        if is_initialized():
            return False
        # CPU collectives must be selected whenever the runtime MAY span
        # processes: an explicit count > 1, or the no-arg auto-detect
        # path, where the process count is unknown until after init (and
        # the config is harmless for a single process)
        if _platform_hint() == "cpu" and \
                (num_processes is None or num_processes > 1):
            _enable_cpu_collectives()
        if coordinator_address and (process_id or 0) == 0:
            _preflight_coordinator_port(coordinator_address)
        kw = {}
        if coordinator_address is not None:
            kw = dict(coordinator_address=coordinator_address,
                      num_processes=int(num_processes or 1),
                      process_id=int(process_id or 0))
        jax.distributed.initialize(**kw)
        logger.info("jax.distributed up: process %s of %s (coordinator %s)",
                    int(process_id or 0), int(num_processes or 1),
                    coordinator_address or "<auto>")
        return True


def from_env(environ=None) -> Optional[Tuple[str, int, int]]:
    """(coordinator, num_processes, process_id) parsed from the deploy
    launch environment (``CYCLONE_MASTER_URL`` or the conf channel's
    ``CYCLONE_CONF_cyclone__master``, both seeded by the Worker), or
    None when this process was not deploy-launched with a multihost
    master — the single-process no-op path."""
    import re
    env = os.environ if environ is None else environ
    for key in ("CYCLONE_MASTER_URL", "CYCLONE_CONF_cyclone__master"):
        m = re.fullmatch(r"multihost\[([^,\]]+),(\d+),(\d+)\]",
                         env.get(key, ""))
        if m is not None:
            return m.group(1), int(m.group(2)), int(m.group(3))
    return None


def ensure_from_env() -> bool:
    """Initialize from the deploy environment when it names a multihost
    master; False (no-op) otherwise."""
    spec = from_env()
    if spec is None:
        return False
    return initialize(*spec)


def global_devices() -> list:
    """Every device of the global runtime, ordered so that process
    (host/DCN) boundaries are contiguous — the order
    :func:`hierarchy.build_device_grid` relies on."""
    import jax
    return sorted(jax.devices(),
                  key=lambda d: (d.process_index, getattr(d, "id", 0)))


def process_count() -> int:
    import jax
    return int(jax.process_count()) if is_initialized() else 1


def process_index() -> int:
    import jax
    return int(jax.process_index()) if is_initialized() else 0


def barrier(name: str = "cyclone-multihost",
            timeout_ms: Optional[int] = None) -> bool:
    """Block until every process reaches the same barrier (coordination-
    service backed). Per-process sequence numbers keep repeated barriers
    distinct; every process must therefore call barrier() the same
    number of times, which the symmetric call sites (context teardown)
    guarantee. Returns False (no-op) when not distributed."""
    global _barrier_seq
    client = _client()
    if client is None:
        return False
    with _lock:
        _barrier_seq += 1
        seq = _barrier_seq
    client.wait_at_barrier(f"{name}.{seq}",
                           int(timeout_ms or _barrier_timeout_ms))
    return True


def shutdown(barrier_first: bool = True) -> bool:
    """Graceful, barriered teardown: sync every process, then disconnect.
    A dead peer bounds the barrier at the configured timeout and the
    teardown proceeds — exit must never hang forever. Idempotent."""
    if not is_initialized():
        return False
    if barrier_first:
        try:
            barrier("cyclone-teardown")
        except Exception as e:
            logger.warning("teardown barrier failed (%s); continuing", e)
    import jax
    try:
        jax.distributed.shutdown()
    except Exception as e:
        logger.warning("jax.distributed.shutdown failed: %s", e)
        return False
    logger.info("jax.distributed shut down")
    return True


def install_preemption_handler(fn, signals: Optional[Tuple[int, ...]] = None
                               ) -> bool:
    """Route the platform's decommission signal into ``fn()``.

    On real pods a slice preemption arrives as SIGTERM (the ``tpu``
    master's advance notice); this installs a handler that calls ``fn``
    — typically ``lambda: channel.announce(CapacityEvent(...))`` or a
    supervisor's drain trigger — and then CHAINS to any previously
    installed handler, so the process's own shutdown hooks still run.
    Returns False (and installs nothing) off the main thread — Python
    only allows signal handlers there — or when no usable signal exists;
    the CPU smoke models the notice with the ``multihost.preempt_notice``
    fault point instead, which is also the deterministic test surface.
    """
    import signal as _signal
    if threading.current_thread() is not threading.main_thread():
        logger.warning("preemption handler not installed: signal handlers "
                       "require the main thread")
        return False
    sigs = signals if signals is not None else (_signal.SIGTERM,)
    installed = False
    for sig in sigs:
        try:
            prev = _signal.getsignal(sig)

            def _handler(signum, frame, _prev=prev):
                logger.warning("preemption signal %s received: draining",
                               signum)
                try:
                    fn()
                finally:
                    if callable(_prev):
                        _prev(signum, frame)

            _signal.signal(sig, _handler)
            installed = True
        except (ValueError, OSError) as e:
            logger.warning("cannot install preemption handler for signal "
                           "%s: %s", sig, e)
    return installed


def abandon(timeout_s: float = 5.0) -> bool:
    """Failure-path teardown after a HOST died: no barrier (the peer
    cannot arrive), and the disconnect itself runs on a daemon thread
    with a bounded join — a coordinator that died mid-handshake must not
    wedge the survivor's recovery. Returns True when the disconnect
    completed within the bound."""
    if not is_initialized():
        return False

    def _tear():
        import jax
        try:
            jax.distributed.shutdown()
        except Exception as e:  # expected: the coordinator may be gone
            logger.info("abandoning distributed runtime: %s", e)

    t = threading.Thread(target=_tear, daemon=True,
                         name="cyclone-multihost-abandon")
    t.start()
    t.join(timeout=timeout_s)
    if t.is_alive():
        logger.warning("distributed teardown still blocked after %.1fs; "
                       "abandoned to its daemon thread", timeout_s)
        return False
    return True
