"""Multi-host runtime: process bootstrap + hierarchical DCN×ICI meshes.

The scale-out limb of the mesh layer (ROADMAP item 2; PAPER.md layers 1-3
— the transport/RPC/deploy capabilities being matched). Two modules:

- :mod:`~cycloneml_tpu.multihost.bootstrap` — ``jax.distributed``
  lifecycle: initialization driven by the deploy environment the Worker
  injects (coordinator address, process count/index), CPU-smoke
  cross-process collectives (gloo), barriered teardown, and the
  failure-path teardown MeshSupervisor uses after a host dies. A
  single-process run never touches ``jax.distributed`` — every in-core
  fit is untouched.
- :mod:`~cycloneml_tpu.multihost.hierarchy` — hierarchical mesh
  construction: the ``replica`` (DCN) axis strides across PROCESS
  boundaries, the ``data``/``model`` (ICI) axes stay inside one
  process's local devices. On the CPU smoke the process boundary stands
  in for DCN and local virtual devices for ICI; on a TPU pod the same
  grid maps replica→DCN slices and data/model→ICI (GSPMD sharding
  propagation composes over the hierarchy without per-level rewrites,
  PAPERS.md Xu et al.).

``mesh.MeshRuntime`` consumes both; ``parallel/collectives.py`` realizes
the reference's ``RDD.treeAggregate`` depth parameter over the resulting
two-level topology (psum inside a slice over ICI, then the cross-slice
combine over DCN). See docs/multihost.md.
"""

from cycloneml_tpu.multihost import bootstrap, hierarchy  # noqa: F401
