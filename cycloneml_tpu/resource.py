"""Resource profiles — accelerator-aware job requirements.

Analog of the reference's stage-level scheduling surface (ref:
resource/ResourceProfile.scala:48 with its defaults object :252,
TaskResourceRequests / ExecutorResourceRequests, ResourceProfileManager.scala:39,
``RDD.withResources`` rdd/RDD.scala:1806). On TPU "the mesh IS the resource"
(SURVEY §2.7): a profile names the slice topology a job wants — device
count, data/model parallel split, replica (DCN) groups — instead of
per-executor GPU counts and discovery scripts. ``CycloneContext.with_resources``
checks the active mesh against the profile and rebuilds it when allowed,
which is the stage-level-scheduling decision this platform actually has.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Dict, Optional


@dataclass(frozen=True)
class ResourceProfile:
    """What a job needs from the mesh.

    ``min_devices``: devices the SPMD program requires (0 = any).
    ``model_parallelism``: feature-dim shards (the ``model`` mesh axis).
    ``replicas``: DCN replica groups (the ``replica`` axis).
    ``memory_per_device_mb``: advisory HBM need, validated against the
    platform when known.
    """

    min_devices: int = 0
    model_parallelism: int = 1
    replicas: int = 1
    memory_per_device_mb: int = 0
    id: int = field(default=0, compare=False)

    def satisfied_by(self, mesh_runtime) -> bool:
        shape = dict(zip(mesh_runtime.mesh.axis_names,
                         mesh_runtime.mesh.devices.shape))
        if self.min_devices and mesh_runtime.n_devices < self.min_devices:
            return False
        if shape.get("model", 1) != self.model_parallelism:
            return False
        if shape.get("replica", 1) != self.replicas:
            return False
        return True

    def mesh_kwargs(self) -> Dict[str, int]:
        return {"n_replicas": self.replicas,
                "model_parallelism": self.model_parallelism}


class ResourceProfileBuilder:
    """Fluent builder (ref: TaskResourceRequests/ExecutorResourceRequests
    feeding ResourceProfileBuilder)."""

    def __init__(self):
        self._kw = {}

    def devices(self, n: int) -> "ResourceProfileBuilder":
        self._kw["min_devices"] = n
        return self

    def model_parallel(self, n: int) -> "ResourceProfileBuilder":
        self._kw["model_parallelism"] = n
        return self

    def replicas(self, n: int) -> "ResourceProfileBuilder":
        self._kw["replicas"] = n
        return self

    def memory_per_device_mb(self, mb: int) -> "ResourceProfileBuilder":
        self._kw["memory_per_device_mb"] = mb
        return self

    def build(self) -> ResourceProfile:
        return ResourceProfileManager.instance().register(
            ResourceProfile(**self._kw))


class ResourceProfileManager:
    """Registry with sequential ids (ref: ResourceProfileManager.scala:39);
    id 0 is the default profile (ref: defaults object :252)."""

    _instance: Optional["ResourceProfileManager"] = None
    _lock = threading.Lock()

    def __init__(self):
        self._next_id = 1
        self._profiles: Dict[int, ResourceProfile] = {0: ResourceProfile()}

    @classmethod
    def instance(cls) -> "ResourceProfileManager":
        with cls._lock:
            if cls._instance is None:
                cls._instance = cls()
            return cls._instance

    def register(self, profile: ResourceProfile) -> ResourceProfile:
        import dataclasses
        with self._lock:
            pid = self._next_id
            self._next_id += 1
            registered = dataclasses.replace(profile, id=pid)
            self._profiles[pid] = registered
            return registered

    def get(self, pid: int) -> ResourceProfile:
        with self._lock:   # register() rewrites the map concurrently
            return self._profiles[pid]

    @staticmethod
    def default_profile() -> ResourceProfile:
        return ResourceProfileManager.instance().get(0)
