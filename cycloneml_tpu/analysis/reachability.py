"""Jit-reachability: which functions run under a JAX trace.

The rule pack must fire inside traced code and stay quiet in host driver
code (a ``float()`` in a result-postprocessing loop is correct; the same
``float()`` inside a jitted aggregator is a silent per-step device sync).
This is a deliberately simple call-graph pass, not a type system:

Seeds
-----
1. ``@jax.jit`` / ``@jit`` / ``@pjit`` / ``@pmap`` decorated functions
   (including ``functools.partial(jax.jit, ...)`` decorator forms).
2. Functions passed by name to a tracing entry point anywhere in their
   module: ``jax.jit(f)``, ``shard_map(f, ...)``, ``lax.while_loop(c, b,
   ...)``, ``dataset.tree_aggregate_fn(f)``, ``jax.grad(f)``, ...
3. Functions whose own body (not nested defs) calls ``jax.lax.*`` —
   collectives and control-flow primitives only run traced.
4. Returned kernel closures: a nested function that its enclosing factory
   returns and whose body does jnp/jax math. This is how every block
   aggregator in ``ml/optim/aggregators.py`` reaches ``tree_aggregate``
   (the factory's *caller* passes the closure in, which a name-based
   graph cannot see).

Propagation
-----------
``f -> g`` edges when ``f``'s body calls ``g`` resolved through (in
order): the lexical scope chain (nested siblings / enclosing function
locals), same-class methods via ``self.m()`` / ``cls.m()``, module-level
functions, explicit ``from mod import name`` imports across the analyzed
file set, and constructor-typed receivers — ``x.m()`` where ``x`` is a
function local (or ``self.f.m()`` where ``f`` is an instance field)
observed being bound to ``ClassName(...)`` for a class in the analyzed
set resolves to ``ClassName.m``. There is NO global match-any-same-name
fallback — a false edge would spray host-only rules across driver code.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterable, List, Optional, Set, Tuple

from cycloneml_tpu.analysis.astutil import (FunctionInfo, call_name,
                                            dotted_name, iter_own_statements,
                                            last_component)

JIT_DECORATORS = {"jax.jit", "jit", "pjit", "jax.pmap", "pmap",
                  "jax.experimental.pjit.pjit", "partial_jit"}

# call targets whose function-valued arguments are traced
TRACING_ENTRYPOINTS = {
    "jit", "pjit", "pmap", "vmap", "grad", "value_and_grad", "jacfwd",
    "jacrev", "hessian", "shard_map", "shard_map_compat", "scan", "cond",
    "while_loop", "fori_loop", "switch", "remat", "checkpoint",
    "custom_vjp", "custom_jvp", "named_call", "tree_aggregate",
    "tree_aggregate_fn", "tree_aggregate_with_state", "all_gather_hosts",
}


class ModuleFunctions(ast.NodeVisitor):
    """Collect FunctionInfo for every def in one module, with lexical
    nesting, per-function call lists, and tracer-argument sightings."""

    def __init__(self, module_path: str, tree: ast.Module):
        self.module_path = module_path
        self.functions: List[FunctionInfo] = []
        # names seen as fn-valued args to tracing entry points, scoped to
        # the enclosing function ("" = module level)
        self.traced_args: Set[tuple] = set()
        self._fn_stack: List[FunctionInfo] = []
        self._class_stack: List[str] = []
        self.imports: Dict[str, str] = {}   # local name -> source module
        self.visit(tree)
        # module-level `go = jax.jit(fn)` style wrapping
        for stmt in tree.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for sub in ast.walk(stmt):
                if not isinstance(sub, ast.Call):
                    continue
                name = call_name(sub)
                if name and last_component(name) in TRACING_ENTRYPOINTS:
                    for arg in (list(sub.args)
                                + [kw.value for kw in sub.keywords]):
                        if isinstance(arg, ast.Name):
                            self.traced_args.add(("", arg.id))

    # -- scope bookkeeping ---------------------------------------------------
    def _qualname(self, name: str) -> str:
        parts = [f.qualname for f in self._fn_stack[-1:]]
        if parts:
            return f"{parts[0]}.{name}"
        if self._class_stack:
            return ".".join(self._class_stack + [name])
        return name

    def visit_ClassDef(self, node: ast.ClassDef):
        self._class_stack.append(node.name)
        self.generic_visit(node)
        self._class_stack.pop()

    def visit_ImportFrom(self, node: ast.ImportFrom):
        for alias in node.names:
            self.imports[alias.asname or alias.name] = (
                f"{node.module}.{alias.name}" if node.module else alias.name)

    def visit_Import(self, node: ast.Import):
        # `import pkg.mod as m` binds m -> pkg.mod, giving m.f() an edge;
        # un-aliased `import pkg.mod` binds only the top package — skip
        for alias in node.names:
            if alias.asname:
                self.imports[alias.asname] = alias.name

    def _visit_function(self, node):
        parent = self._fn_stack[-1] if self._fn_stack else None
        info = FunctionInfo(
            qualname=self._qualname(node.name), node=node,
            module_path=self.module_path, parent=parent,
            class_name=self._class_stack[-1] if self._class_stack else None)
        args = node.args
        for a in (list(args.posonlyargs) + list(args.args)
                  + list(args.kwonlyargs)):
            info.params.add(a.arg)
        info.is_jit_decorated = any(
            self._decorator_is_jit(d) for d in node.decorator_list)
        self._scan_body(info)
        self.functions.append(info)
        self._fn_stack.append(info)
        self.generic_visit(node)
        self._fn_stack.pop()

    visit_FunctionDef = _visit_function
    visit_AsyncFunctionDef = _visit_function

    @staticmethod
    def _decorator_is_jit(dec: ast.AST) -> bool:
        name = dotted_name(dec)
        if name in JIT_DECORATORS:
            return True
        if isinstance(dec, ast.Call):
            name = call_name(dec)
            if name in JIT_DECORATORS:       # @jax.jit(static_argnums=...)
                return True
            if name and last_component(name) == "partial" and dec.args:
                return dotted_name(dec.args[0]) in JIT_DECORATORS
        return False

    def _scan_body(self, info: FunctionInfo) -> None:
        scope = info.parent.qualname if info.parent else ""
        has_jnp_math = False
        for sub in iter_own_statements(info.node):
            if not isinstance(sub, ast.Call):
                continue
            name = call_name(sub)
            if not name:
                continue
            info.calls.add(name)
            if name.startswith(("jax.lax.", "lax.")):
                info.has_lax_call = True
            if name.startswith(("jnp.", "jax.numpy.", "jax.nn.",
                                "jax.scipy.", "jax.random.")):
                has_jnp_math = True
            if last_component(name) in TRACING_ENTRYPOINTS:
                for arg in list(sub.args) + [kw.value for kw in sub.keywords]:
                    if isinstance(arg, ast.Name):
                        self.traced_args.add((scope_key(info), arg.id))
                        self.traced_args.add((scope, arg.id))
        # returned kernel closure: nested + returned + jnp math
        if info.parent is not None and has_jnp_math:
            parent_returns = _names_in_returns(info.parent.node)
            fname = getattr(info.node, "name", None)
            if fname and fname in parent_returns:
                info.is_returned_kernel = True


def scope_key(info: FunctionInfo) -> str:
    return info.qualname


def _names_in_returns(fn_node: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for stmt in iter_own_statements(fn_node):
        if isinstance(stmt, ast.Return) and stmt.value is not None:
            for n in ast.walk(stmt.value):
                if isinstance(n, ast.Name):
                    out.add(n.id)
    return out


class CallResolver:
    """Name -> FunctionInfo resolution across the analyzed file set.

    One instance serves both the reachability pass and the
    interprocedural dataflow engine (:mod:`.dataflow`): the resolution
    tables (same-module top-level names, class methods, nested-scope
    chains, ``from mod import name`` edges) are built once per analysis.
    Resolution is deliberately conservative — no match-any-same-name
    fallback; an unresolvable callee returns ``[]``.
    """

    def __init__(self, modules: Dict[str, "object"]):
        self.modules = modules
        self.by_module_toplevel: Dict[str, Dict[str, FunctionInfo]] = {}
        self.by_module_class: Dict[str, Dict[str, FunctionInfo]] = {}
        for path, mod in modules.items():
            top: Dict[str, FunctionInfo] = {}
            meth: Dict[str, FunctionInfo] = {}
            for fn in mod.functions:
                simple = fn.qualname.rsplit(".", 1)[-1]
                if fn.parent is None and fn.class_name is None:
                    top[simple] = fn
                if fn.class_name is not None and fn.parent is None:
                    meth[f"{fn.class_name}.{simple}"] = fn
                    meth.setdefault(simple, fn)
            self.by_module_toplevel[path] = top
            self.by_module_class[path] = meth

        # module-name index for `from pkg.mod import f` resolution
        self.modname_to_path: Dict[str, str] = {}
        for path in modules:
            dotted = (path[:-3].replace("/", ".") if path.endswith(".py")
                      else path)
            self.modname_to_path[dotted] = path
            if dotted.endswith(".__init__"):
                self.modname_to_path[dotted[: -len(".__init__")]] = path

        # parent qualname -> nested children, built once per module
        # (resolve() runs once per call edge — rebuilding there is O(F*E))
        self.children_by_module: Dict[str, Dict[str, List[FunctionInfo]]] = {}
        for path, mod in modules.items():
            children: Dict[str, List[FunctionInfo]] = {}
            for fn in mod.functions:
                if fn.parent is not None:
                    children.setdefault(fn.parent.qualname, []).append(fn)
            self.children_by_module[path] = children

        # class name -> {method simple name -> [FunctionInfo]} across the
        # whole set (same-named classes in different modules merge; the
        # resolver returns every candidate and lets rules join)
        self.by_class: Dict[str, Dict[str, List[FunctionInfo]]] = {}
        for path, mod in modules.items():
            for fn in mod.functions:
                if fn.class_name is not None and fn.parent is None:
                    simple = fn.qualname.rsplit(".", 1)[-1]
                    self.by_class.setdefault(fn.class_name, {}) \
                        .setdefault(simple, []).append(fn)

        # constructor-typed receivers, built lazily on first x.m() miss:
        # per-class instance-field types (`self.f = ClassName(...)`) and
        # per-function local types (`x = ClassName(...)`)
        self._field_types: Optional[Dict[str, Dict[str, str]]] = None
        self._local_types: Dict[int, Dict[str, str]] = {}

        # resolution is a pure function of the tables above, and both the
        # reachability worklist and CallGraph construction resolve the
        # same (caller, name) edges — memoize so the second pass is a
        # dict hit instead of a repeated scope-chain walk
        self._memo: Dict[Tuple[int, str], List[FunctionInfo]] = {}

    def resolve(self, caller: FunctionInfo, callee: str) -> List[FunctionInfo]:
        key = (id(caller), callee)
        got = self._memo.get(key)
        if got is None:
            got = self._resolve(caller, callee)
            self._memo[key] = got
        return got

    def _resolve(self, caller: FunctionInfo,
                 callee: str) -> List[FunctionInfo]:
        simple = last_component(callee)
        # scope chain: nested siblings and enclosing functions' children
        scope = caller
        children = self.children_by_module[caller.module_path]
        while scope is not None:
            for child in children.get(scope.qualname, []):
                if child.qualname.rsplit(".", 1)[-1] == simple:
                    return [child]
            scope = scope.parent
        # self.method() / cls.method() — exactly two components: a deeper
        # chain (`self._spans.clear()`) is a call on a FIELD, and
        # resolving it by its last component would hand `list.clear` to
        # `Tracer.clear` (false self-edges in every lock/reachability
        # analysis); field chains resolve below, by constructor type
        if callee.count(".") == 1 \
                and callee.startswith(("self.", "cls.")) \
                and caller.class_name:
            hit = self.by_module_class[caller.module_path].get(
                f"{caller.class_name}.{simple}")
            if hit is not None:
                return [hit]
        # module-level function, same module
        hit = self.by_module_toplevel[caller.module_path].get(simple)
        if hit is not None and "." not in callee:
            return [hit]
        # explicit from-import
        mod = self.modules[caller.module_path]
        src = mod.mf.imports.get(simple if "." not in callee
                                 else callee.split(".", 1)[0])
        if src is not None:
            if "." in callee:  # `import pkg.mod as m; m.f()`
                target_mod, target_fn = src, simple
            else:
                target_mod, _, target_fn = src.rpartition(".")
            tpath = self.modname_to_path.get(target_mod)
            if tpath is not None:
                hit = self.by_module_toplevel[tpath].get(target_fn)
                if hit is not None:
                    return [hit]
        # constructor-typed receiver: `x.m()` / `self.f.m()` where the
        # receiver was observed bound to `ClassName(...)`
        parts = callee.split(".")
        cls: Optional[str] = None
        if len(parts) == 2 and parts[0] not in ("self", "cls"):
            cls = self._locals_of(caller).get(parts[0])
        elif len(parts) == 3 and parts[0] in ("self", "cls") \
                and caller.class_name:
            cls = self._fields_of().get(caller.class_name, {}).get(parts[1])
        if cls is not None:
            return list(self.by_class.get(cls, {}).get(simple, []))
        return []

    def _constructed_class(self, value: ast.AST) -> Optional[str]:
        """ClassName when ``value`` is `ClassName(...)` (possibly dotted)
        for a class defined in the analyzed set, else None."""
        if not isinstance(value, ast.Call):
            return None
        name = last_component(call_name(value))
        return name if name in self.by_class else None

    def _locals_of(self, caller: FunctionInfo) -> Dict[str, str]:
        got = self._local_types.get(id(caller))
        if got is None:
            got = {}
            for stmt in iter_own_statements(caller.node):
                if not isinstance(stmt, ast.Assign):
                    continue
                cls = self._constructed_class(stmt.value)
                for tgt in stmt.targets:
                    if isinstance(tgt, ast.Name):
                        if cls is None or (tgt.id in got
                                           and got[tgt.id] != cls):
                            got.pop(tgt.id, None)   # rebound/ambiguous
                        else:
                            got[tgt.id] = cls
            self._local_types[id(caller)] = got
        return got

    def _fields_of(self) -> Dict[str, Dict[str, str]]:
        if self._field_types is None:
            types: Dict[str, Dict[str, str]] = {}
            dropped: Set[Tuple[str, str]] = set()
            for mod in self.modules.values():
                for fn in mod.functions:
                    if fn.class_name is None:
                        continue
                    for stmt in iter_own_statements(fn.node):
                        if not isinstance(stmt, ast.Assign):
                            continue
                        cls = self._constructed_class(stmt.value)
                        if cls is None:
                            continue
                        for tgt in stmt.targets:
                            tn = dotted_name(tgt)
                            if tn is None or not tn.startswith("self.") \
                                    or tn.count(".") != 1:
                                continue
                            fld = tn.split(".", 1)[1]
                            key = (fn.class_name, fld)
                            fields = types.setdefault(fn.class_name, {})
                            if key in dropped:
                                continue
                            if fields.get(fld, cls) != cls:
                                # two constructors for one field:
                                # ambiguous, resolve neither
                                fields.pop(fld, None)
                                dropped.add(key)
                            else:
                                fields[fld] = cls
            self._field_types = types
        return self._field_types


def compute_reachability(modules: Dict[str, "object"],
                         resolver: Optional[CallResolver] = None) -> None:
    """Mark ``jit_reachable`` on every FunctionInfo across the file set.

    ``modules`` maps path -> ModuleInfo (engine.ModuleInfo: needs
    ``.functions`` (List[FunctionInfo]), ``.mf`` (ModuleFunctions)).
    """
    if resolver is None:
        resolver = CallResolver(modules)

    # seeds
    worklist: List[FunctionInfo] = []
    for path, mod in modules.items():
        for fn in mod.functions:
            simple = fn.qualname.rsplit(".", 1)[-1]
            scope = fn.parent.qualname if fn.parent else ""
            if (scope, simple) in mod.mf.traced_args:
                fn.passed_to_tracer = True
            if (fn.is_jit_decorated or fn.passed_to_tracer
                    or fn.has_lax_call or fn.is_returned_kernel):
                fn.jit_reachable = True
                worklist.append(fn)

    # propagate: call edges + nesting (a function nested inside traced
    # code is itself traced when called — closures are near-always called
    # by their creator's trace). Interleaved to a fixpoint: a closure
    # reached only through the nesting rule must still propagate to ITS
    # callees.
    while True:
        while worklist:
            fn = worklist.pop()
            for callee in fn.calls:
                for target in resolver.resolve(fn, callee):
                    if not target.jit_reachable:
                        target.jit_reachable = True
                        worklist.append(target)
        for mod in modules.values():
            for fn in mod.functions:
                if fn.jit_reachable:
                    continue
                p = fn.parent
                while p is not None:
                    if p.jit_reachable:
                        fn.jit_reachable = True
                        worklist.append(fn)
                        break
                    p = p.parent
        if not worklist:
            break
