"""graftlint — AST-based JAX/TPU hazard analyzer.

The framework hangs off one narrow dispatch boundary (MLlib -> BLAS ->
``tree_aggregate`` -> ``jax.lax.psum``), so a single silent host-device
sync, tracer leak, or mismatched collective axis name inside a jitted hot
path wrecks the perf story without any functional test failing. This
package encodes those failure modes as enforced lint rules:

- **JX001** implicit host sync in jit-reachable code (``float()`` /
  ``int()`` / ``bool()`` / ``.item()`` / ``np.asarray`` on a traced value)
  and piecemeal device->host pulls from an aggregate program's output
  where one ``jax.device_get`` would do.
- **JX002** Python ``if`` / ``while`` branching on a traced value where
  ``lax.cond`` / ``lax.while_loop`` is required.
- **JX003** PRNG key reuse — the same key consumed by two ``jax.random.*``
  draws without an intervening ``split`` / ``fold_in``.
- **JX004** fp64 literal/dtype drift in device code without a
  ``jax_enable_x64`` guard.
- **JX005** collective axis names validated against the axes declared in
  ``cycloneml_tpu/mesh.py``.
- **JX006** jitted function mutating ``self`` / ``global`` / ``nonlocal``
  state (the side effect runs once at trace time, then silently freezes).
- **JX007–JX010** interprocedural dataflow rules (thread-dispatched SPMD
  entry points, recompile hazards, use-after-donate, collectives under
  host-divergent branches), **JX011–JX014** the compositional
  concurrency pack (lockset races, lock-order cycles, obligation leaks,
  blocking under locks), **JX015–JX018** the abstract shape & sharding
  pack (:mod:`.shapes`: shard_map spec consistency, provable
  shape/padding hazards, cross-mesh program reuse, O(n) host
  materialization on fit paths), and **JX019** conf-key typo checking
  against the ``conf.py`` registry.

Rules fire only where they matter: a call-graph pass
(:mod:`.reachability`) computes which functions are jit-reachable, seeded
from ``@jax.jit`` / ``pjit`` decorations, functions handed to tracing
entry points (``jit``, ``shard_map``, ``tree_aggregate_fn``,
``lax.while_loop``, ...), ``jax.lax`` call sites, and returned jnp-kernel
closures.

Usage::

    python -m cycloneml_tpu.analysis <paths> [--json] [--baseline FILE]

``tests/test_graftlint.py`` runs the analyzer over ``cycloneml_tpu/`` as
part of tier-1 and fails on any finding not grandfathered in
``cycloneml_tpu/analysis/baseline.json``. See ``docs/graftlint.md``.
"""

from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, analyze_paths
from cycloneml_tpu.analysis.report import render_json, render_text

__all__ = ["AnalysisContext", "Finding", "analyze_paths", "render_json",
           "render_text"]
