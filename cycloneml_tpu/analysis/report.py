"""Finding reporters: human text, machine JSON, and SARIF 2.1.0.

SARIF is the interchange format CI/code-review tooling renders inline
(GitHub code scanning, VS Code SARIF viewer): one ``run`` per invocation,
rule metadata in ``tool.driver.rules``, one ``result`` per finding with a
physical location region. The stable ``(rule, path, function)``
fingerprint rides along in ``partialFingerprints`` so baselining on the
consumer side matches graftlint's own."""

from __future__ import annotations

import collections
import json
import sys
from typing import List, Optional

from cycloneml_tpu.analysis.engine import Finding

SARIF_SCHEMA = ("https://raw.githubusercontent.com/oasis-tcs/sarif-spec/"
                "master/Schemata/sarif-schema-2.1.0.json")
SARIF_VERSION = "2.1.0"


def _sorted(findings: List[Finding]) -> List[Finding]:
    """Deterministic report order: (path, line, rule) first — pinned so
    CI diffs and SARIF fingerprint ordering never churn on unrelated
    edits — with col/function/message breaking any remaining ties."""
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.col,
                                           f.function, f.message))


def render_text(findings: List[Finding], grandfathered: int = 0,
                total_files: Optional[int] = None,
                timings: Optional[dict] = None) -> str:
    lines = []
    for f in _sorted(findings):
        where = f"  [{f.function}]" if f.function else ""
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}{where}")
    by_rule = collections.Counter(f.rule for f in findings)
    summary = ", ".join(f"{r}×{n}" for r, n in sorted(by_rule.items()))
    tail = f"{len(findings)} finding(s)"
    if summary:
        tail += f" ({summary})"
    if grandfathered:
        tail += f"; {grandfathered} baselined"
    if total_files is not None:
        tail += f"; {total_files} file(s) scanned"
    lines.append(tail)
    if timings:
        top = sorted(timings.items(), key=lambda kv: -kv[1])[:3]
        lines.append("slowest rules: " + " · ".join(
            f"{rid} {secs:.2f}s" for rid, secs in top))
    return "\n".join(lines)


def render_json(findings: List[Finding], grandfathered: int = 0,
                timings: Optional[dict] = None) -> str:
    payload = {"findings": [f.to_dict() for f in _sorted(findings)],
               "grandfathered": grandfathered,
               "count": len(findings)}
    if timings is not None:
        # per-rule wall time (seconds): check() over every module plus
        # the rule's dataflow fixpoint; shared analyses (JXSHAPE) get
        # their own entry
        payload["timings"] = dict(timings)
    return json.dumps(payload, indent=2, sort_keys=True) + "\n"


def _rule_descriptions() -> List[dict]:
    """SARIF rule metadata from the registry's module docstrings (the
    first line is the one-sentence rule summary)."""
    from cycloneml_tpu.analysis.rules import ALL_RULES
    out = []
    for cls in ALL_RULES:
        doc = (sys.modules[cls.__module__].__doc__ or "").strip()
        first = doc.splitlines()[0] if doc else cls.rule_id
        out.append({
            "id": cls.rule_id,
            "name": cls.__name__,
            "shortDescription": {"text": first},
            "helpUri": "docs/graftlint.md",
        })
    return out


def render_sarif(findings: List[Finding], grandfathered: int = 0,
                 timings: Optional[dict] = None) -> str:
    properties: dict = {"grandfathered": grandfathered}
    if timings:
        # CI's budget gate reads these straight off the artifact — no
        # second analysis run just to name the slow rules on a breach
        properties["timings"] = dict(timings)
    results = []
    for f in _sorted(findings):
        results.append({
            "ruleId": f.rule,
            "level": "error",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.path,
                                         "uriBaseId": "SRCROOT"},
                    "region": {
                        "startLine": f.line,
                        "startColumn": f.col + 1,   # SARIF is 1-based
                        "endLine": max(f.end_line, f.line),
                    },
                },
            }],
            "partialFingerprints": {"graftlint/v1": f.fingerprint},
        })
    doc = {
        "$schema": SARIF_SCHEMA,
        "version": SARIF_VERSION,
        "runs": [{
            "tool": {"driver": {
                "name": "graftlint",
                "informationUri": "docs/graftlint.md",
                "rules": _rule_descriptions(),
            }},
            "results": results,
            "properties": properties,
        }],
    }
    return json.dumps(doc, indent=2, sort_keys=True) + "\n"
