"""Finding reporters: human-readable text and machine-readable JSON."""

from __future__ import annotations

import collections
import json
from typing import List, Optional

from cycloneml_tpu.analysis.engine import Finding


def render_text(findings: List[Finding], grandfathered: int = 0,
                total_files: Optional[int] = None) -> str:
    lines = []
    for f in findings:
        where = f"  [{f.function}]" if f.function else ""
        lines.append(f"{f.path}:{f.line}:{f.col}: {f.rule} {f.message}{where}")
    by_rule = collections.Counter(f.rule for f in findings)
    summary = ", ".join(f"{r}×{n}" for r, n in sorted(by_rule.items()))
    tail = f"{len(findings)} finding(s)"
    if summary:
        tail += f" ({summary})"
    if grandfathered:
        tail += f"; {grandfathered} baselined"
    if total_files is not None:
        tail += f"; {total_files} file(s) scanned"
    lines.append(tail)
    return "\n".join(lines)


def render_json(findings: List[Finding], grandfathered: int = 0) -> str:
    return json.dumps(
        {"findings": [f.to_dict() for f in findings],
         "grandfathered": grandfathered,
         "count": len(findings)},
        indent=2, sort_keys=True) + "\n"
