"""JX002 — Python control flow on a traced value.

Inside jit-reachable code, a Python ``if``/``while`` whose condition
depends on a traced value raises ``TracerBoolConversionError`` at trace
time — or, when the function happens to run eagerly first, silently
specializes the trace to one branch. ``jax.lax.cond`` /
``jax.lax.while_loop`` / ``jnp.where`` are the staged equivalents.

Deliberately NOT flagged (static under tracing):
- conditions over closure variables / constants (``if fit_intercept:``),
- ``x is None`` / ``x is not None`` (a tracer is never None),
- shape/dtype/ndim reads (``if x.ndim == 2:``) — static metadata,
- ``isinstance`` / ``hasattr`` / ``len`` guards.
"""

from __future__ import annotations

import ast
from typing import Iterator

from cycloneml_tpu.analysis.astutil import TaintTracker, iter_own_statements
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule


class TracedControlFlowRule(Rule):
    rule_id = "JX002"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        for fn in mod.functions:
            if not fn.jit_reachable:
                continue
            taint = TaintTracker(fn.node, seed_params=fn.params_traced)
            for node in iter_own_statements(fn.node):
                if isinstance(node, ast.If) and taint.expr_tainted(node.test):
                    yield self.finding(
                        mod, node,
                        "Python `if` on a traced value inside jit-reachable "
                        "code; use `jax.lax.cond` / `jnp.where` (or hoist "
                        "the decision to a static argument)",
                        fn.qualname)
                elif isinstance(node, ast.While) \
                        and taint.expr_tainted(node.test):
                    yield self.finding(
                        mod, node,
                        "Python `while` on a traced value inside "
                        "jit-reachable code; use `jax.lax.while_loop`",
                        fn.qualname)
                elif isinstance(node, ast.Assert) \
                        and taint.expr_tainted(node.test):
                    yield self.finding(
                        mod, node,
                        "`assert` on a traced value inside jit-reachable "
                        "code; use `checkify` or validate outside the trace",
                        fn.qualname)
