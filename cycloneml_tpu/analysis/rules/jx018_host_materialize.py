"""JX018 — unbounded host materialization of dataset-sized arrays on a
fit path.

The scale contract behind out-of-core training (ROADMAP item 2) is that
the *fit path* never materializes O(n) data on the host: the design
matrix streams/shards onto the mesh, aggregation reduces it to O(d)
stats, and only those stats cross back. One ``np.asarray(resid)`` of an
``(n,)`` residual vector in a fit driver silently reintroduces the
ceiling — it works in every test (n is small), then OOMs the host the
first time a dataset exceeds RAM, which is exactly the regime the
streaming engine exists for.

The abstract interpreter tracks the **dataset dim** ``n`` symbolically:

* dims of arrays passed as the row-sharded operands of
  ``tree_aggregate``/``tree_aggregate_with_state`` (the row-sharded dim
  *is* the dataset dim, by construction of the dispatch boundary), and
* ``.shape`` unpacks binding a conventional row-count name (``n``,
  ``n_rows``, ``num_rows``, ``n_samples``, ``n_pad``) in the leading
  position.

A **host materializer** — ``jax.device_get``, ``np.asarray`` /
``np.array`` (``jnp.asarray`` is device-side and exempt), ``.tolist()``
— whose operand's abstract shape contains a dataset dim (or is a
dataset-sharded operand itself, shape-preserved) is flagged, but only
in functions on the **fit path**: the JXSHAPE summary's transitive
``reaches_aggregate`` fact, or a ``fit``/``train`` qualname. Predict
and transform drivers returning n-sized results to the caller are the
API contract and stay silent; O(d)/O(K) pulls of coefficients and stats
stay silent (their shapes don't contain ``n``).

Interprocedural through ``materializes_params``: a helper that hands
its parameter to ``np.asarray`` convicts the fit-path caller passing an
n-sized array two hops up.
"""

from __future__ import annotations

from typing import Iterator, Set

from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.shapes import AArray, ShapeRuleBase, summary_of

FIT_NAME_TOKENS = ("fit", "train")


class HostMaterializeRule(ShapeRuleBase, DataflowRule):
    rule_id = "JX018"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        if ctx.callgraph is None:
            return
        facts = self.facts(ctx)
        for fn in mod.functions:
            summary = summary_of(facts, fn)
            lowq = fn.qualname.lower()
            on_fit_path = summary.reaches_aggregate or any(
                tok in lowq for tok in FIT_NAME_TOKENS)
            if not on_fit_path:
                continue
            state = self.state_of(ctx, fn)
            if state is None or not (state.dataset_syms
                                     or state.dataset_roots):
                continue
            reported: Set[int] = set()
            for ev in state.events:
                if ev.kind != "materialize":
                    continue
                aval = ev.aval
                if not isinstance(aval, AArray):
                    continue
                n_hit = aval.dims_contained() & state.dataset_syms
                root_hit = aval.roots & state.dataset_roots
                if not n_hit and not root_hit:
                    continue
                if id(ev.node) in reported:
                    continue
                reported.add(id(ev.node))
                what = ev.detail or "host materializer"
                dim = next(iter(sorted(
                    (s.label for s in n_hit)))) if n_hit else "n"
                yield self.finding(
                    mod, ev.node,
                    f"`{what}` materializes an array whose shape contains "
                    f"the dataset dim `{dim}` on a fit path — this is "
                    f"O(n) host memory and reintroduces the scale ceiling "
                    f"out-of-core training removes; keep the value on "
                    f"device, reduce it first, or stream it in chunks",
                    fn.qualname)
