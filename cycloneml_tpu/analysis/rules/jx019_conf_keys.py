"""JX019 — `cyclone.*` conf-key literals validated against the registry.

``CycloneConf.get`` falls back to the registered default for any key it
does not recognize — so a typo'd ``conf.set("cyclone.serving.windwMs",
5)`` / ``conf.get("cyclone.serving.windwMs")`` silently configures
nothing and silently reads the default. Every real key is registered
exactly once through ``ConfigBuilder("cyclone....")`` (conf.py's
centralized registry, plus ``with_alternative`` legacy spellings); this
rule collects that registry from the analyzed file set and validates
every key-shaped string literal against it.

A literal is key-shaped when it fullmatches ``cyclone.seg(.seg)*`` —
prose mentioning a key inside a doc/error string never fullmatches, and
f-string fragments are not literals. Two exemptions keep the rule
quiet on legitimate dynamic use:

* the registration sites themselves (``ConfigBuilder(...)`` /
  ``.with_alternative(...)`` arguments ARE the registry), and
* literals that are a strict PREFIX of a registered key
  (``key.startswith("cyclone.sql.")`` namespace checks).

When no registry is visible in the analyzed set the rule stays silent
— there is nothing to validate against.

The rule also checks **conf-default drift**: ``conf.get("cyclone.x",
<literal>)`` carries an inline fallback that ``CycloneConf.get`` only
uses when the key is *unset* — if it disagrees with the default
registered by the ``ConfigBuilder`` chain's typed terminal
(``.int_conf(64)`` etc.), the two defaults silently diverge and the
code path behaves differently depending on whether the conf was
materialized. The registered default wins; the inline literal must
match it exactly (value AND type — ``1`` is not ``True``).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, Optional, Set

from cycloneml_tpu.analysis.astutil import (call_name, iter_own_statements,
                                            last_component)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule

KEY_RE = re.compile(r"cyclone\.[A-Za-z0-9_]+(\.[A-Za-z0-9_]+)*")


class ConfKeyRule(Rule):
    rule_id = "JX019"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        keys = _registered_keys(ctx)
        if not keys:
            return
        yield from self._unknown_keys(mod, ctx, keys)
        yield from self._default_drift(mod, ctx)

    def _unknown_keys(self, mod: ModuleInfo, ctx: AnalysisContext,
                      keys: Set[str]) -> Iterator[Finding]:
        candidates = [node for node in ast.walk(mod.tree)
                      if isinstance(node, ast.Constant)
                      and isinstance(node.value, str)
                      and KEY_RE.fullmatch(node.value)
                      and node.value not in keys]
        if not candidates:
            return
        registration_args = _registration_arg_ids(mod)
        owner = _constant_owners(mod)
        for node in candidates:
            value = node.value
            if id(node) in registration_args:
                continue
            if any(k.startswith(value) for k in keys):
                # namespace-prefix use (`key.startswith("cyclone.sql.")`)
                continue
            close = _closest(value, keys)
            hint = f"; did you mean '{close}'?" if close else ""
            yield self.finding(
                mod, node,
                f"'{value}' is not a registered conf key — CycloneConf "
                f"silently takes the default for unknown keys, so a typo "
                f"configures nothing{hint} (registry: conf.py "
                f"ConfigBuilder entries)",
                owner.get(id(node), ""))

    def _default_drift(self, mod: ModuleInfo, ctx: AnalysisContext
                       ) -> Iterator[Finding]:
        defaults = _registered_defaults(ctx)
        if not defaults:
            return
        owner = None
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "get"
                    and len(node.args) == 2 and not node.keywords):
                continue
            key, inline = node.args
            if not (isinstance(key, ast.Constant)
                    and isinstance(key.value, str)
                    and key.value in defaults
                    and isinstance(inline, ast.Constant)):
                continue
            registered = defaults[key.value]
            if type(inline.value) is type(registered) \
                    and inline.value == registered:
                continue
            if owner is None:
                owner = _constant_owners(mod)
            yield self.finding(
                mod, node,
                f"inline default {inline.value!r} for '{key.value}' "
                f"disagrees with the registered default {registered!r} "
                f"(conf.py) — the inline value only applies when the "
                f"conf never materialized the key, so the two paths "
                f"silently diverge; match the registered default or "
                f"drop the fallback",
                owner.get(id(key), ""))


def _registered_keys(ctx: AnalysisContext) -> Set[str]:
    """Keys registered anywhere in the analyzed set (cached per ctx)."""
    cached = getattr(ctx, "_conf_keys", None)
    if cached is not None and getattr(ctx, "_conf_keys_ctx", None) is ctx:
        return cached
    keys: Set[str] = set()
    for mod in ctx.modules.values():
        # cheap text gate before the tree walk: registries are rare
        if not any("ConfigBuilder" in ln for ln in mod.source_lines):
            continue
        for node in ast.walk(mod.tree):
            key = _registration_key(node)
            if key is not None:
                keys.add(key)
    ctx._conf_keys = keys
    ctx._conf_keys_ctx = ctx
    return keys


def _registration_key(node: ast.AST) -> Optional[str]:
    if not isinstance(node, ast.Call):
        return None
    base = last_component(call_name(node) or "")
    if not base and isinstance(node.func, ast.Attribute):
        # `.with_alternative(...)` chained onto a ConfigBuilder CALL has
        # no resolvable dotted name — the attr is still the dispatch key
        base = node.func.attr
    if base not in ("ConfigBuilder", "with_alternative"):
        return None
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


#: ConfigBuilder chain terminals carrying a literal default
_TYPED_TERMINALS = ("int_conf", "float_conf", "bool_conf", "str_conf")


def _registered_defaults(ctx: AnalysisContext) -> Dict[str, object]:
    """key -> literal default from ``ConfigBuilder("key")....int_conf(v)``
    chains anywhere in the analyzed set (cached per ctx)."""
    cached = getattr(ctx, "_conf_defaults", None)
    if cached is not None \
            and getattr(ctx, "_conf_defaults_ctx", None) is ctx:
        return cached
    out: Dict[str, object] = {}
    for mod in ctx.modules.values():
        if not any("ConfigBuilder" in ln for ln in mod.source_lines):
            continue
        for node in ast.walk(mod.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in _TYPED_TERMINALS):
                continue
            default = None
            if len(node.args) == 1 \
                    and isinstance(node.args[0], ast.Constant):
                default = node.args[0]
            else:
                default = next(
                    (kw.value for kw in node.keywords
                     if kw.arg == "default"
                     and isinstance(kw.value, ast.Constant)), None)
            if default is None:
                continue
            key = _builder_root_key(node.func.value)
            if key is not None:
                out[key] = default.value
    ctx._conf_defaults = out
    ctx._conf_defaults_ctx = ctx
    return out


def _builder_root_key(expr: ast.AST) -> Optional[str]:
    """Walk a builder chain (`.doc(...).check_value(...)`) down to the
    ``ConfigBuilder("key")`` root and return the key."""
    while isinstance(expr, ast.Call):
        if last_component(call_name(expr) or "") == "ConfigBuilder" \
                and expr.args \
                and isinstance(expr.args[0], ast.Constant) \
                and isinstance(expr.args[0].value, str):
            return expr.args[0].value
        expr = expr.func.value \
            if isinstance(expr.func, ast.Attribute) else None
    return None


def _registration_arg_ids(mod: ModuleInfo) -> Set[int]:
    out: Set[int] = set()
    for node in ast.walk(mod.tree):
        if _registration_key(node) is not None:
            out.add(id(node.args[0]))
    return out


def _constant_owners(mod: ModuleInfo) -> Dict[int, str]:
    """id(Constant) -> enclosing function qualname for finding
    attribution."""
    out: Dict[int, str] = {}
    for fn in mod.functions:
        for node in iter_own_statements(fn.node):
            if isinstance(node, ast.Constant):
                out[id(node)] = fn.qualname
    return out


def _closest(value: str, keys: Set[str]) -> Optional[str]:
    """The registered key with the same segment count and the smallest
    per-segment mismatch — a cheap typo suggestion, no quadratic edit
    distance."""
    segs = value.split(".")
    best, best_score = None, 0.0
    for key in keys:
        ks = key.split(".")
        if len(ks) != len(segs):
            continue
        same = sum(1 for a, b in zip(segs, ks) if a == b)
        if same < len(segs) - 1:
            continue
        # one differing segment: score by shared prefix length
        diff = next((i for i, (a, b) in enumerate(zip(segs, ks))
                     if a != b), None)
        if diff is None:
            continue
        a, b = segs[diff], ks[diff]
        prefix = len([1 for x, y in zip(a, b) if x == y])
        score = same + prefix / max(len(a), len(b), 1)
        if score > best_score:
            best, best_score = key, score
    return best
