"""JX015 — sharding-spec consistency at the shard_map/pjit boundary.

The whole SPMD contract of this repo funnels through a handful of
``shard_map`` bindings (``collectives.shard_map_compat``): the in/out
``PartitionSpec``\\ s are the *declared* sharding of every operand and
result, and nothing at trace time checks them against what the body
actually computes (``check_vma``/``check_rep`` is explicitly disabled
for jax<0.5 compat). GSPMD treats sharding as a propagatable dataflow
fact; this rule propagates it statically and flags the four
inconsistency classes that turn into silent wrong numbers or
downstream reshard chaos:

* **unknown axis** — a spec naming a mesh axis that the binding mesh
  does not declare (``P("batch")`` against the ``(replica, data,
  model)`` mesh of ``mesh.py``); axis names are discovered from the
  analyzed ``mesh.py``, the same source JX005 validates collectives
  against.
* **duplicate axis** — one mesh axis bound to two different tensor
  dims in a single spec (``P("data", "data")``): each mesh axis can
  partition at most one dim.
* **rank overflow** — an in_spec with more partitioned entries than
  the operand's abstract rank (a ``P("data", None)`` spec applied to a
  1-D operand), caught when the shard_map result is applied directly
  and the operand's rank is known to the abstract interpreter.
* **out_spec claims a reduced axis** — the body ``psum``\\ s a value
  over an axis (making it replicated over that axis *by construction*)
  but the out_spec still claims the axis partitions the result. With
  replication checking off, XLA emits whatever the spec says — each
  shard keeps a full copy and downstream consumers read sharded
  garbage. The body's psummed-axes fact is the JXSHAPE ``ret_psummed``
  summary, so a body that reduces through a helper
  (``_reduce -> psum_over_mesh``) is still seen.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from cycloneml_tpu.analysis import shapes
from cycloneml_tpu.analysis.astutil import call_name, last_component
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.shapes import (AArray, ShapeRuleBase, SpecVal,
                                           TupleVal, UNKNOWN_ENTRY,
                                           resolve_spec, iter_spec_literals,
                                           summary_of)


class ShardingSpecRule(ShapeRuleBase, DataflowRule):
    rule_id = "JX015"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        graph = ctx.callgraph
        if graph is None:
            return
        consts = getattr(ctx, "axis_constants", {}) or {}
        valid = set(ctx.valid_axes)
        facts = self.facts(ctx)
        for fn in mod.functions:
            state = self.state_of(ctx, fn)
            if state is None:
                continue
            flagged_specs: Set[int] = set()

            # 1/2: internal validity of EVERY P(...) literal in the body
            for call in graph.index(fn).calls:
                base = last_component(call_name(call) or "")
                if base not in ("P", "PartitionSpec"):
                    continue
                spec = shapes.parse_spec(call, consts)
                yield from self._validate_spec(mod, fn, spec, valid,
                                               flagged_specs)

            apply_by_inner = {
                id(ev.payload["inner"]): ev
                for ev in state.events if ev.kind == "shard_apply"}
            for ev in state.events:
                if ev.kind != "shard_map":
                    continue
                in_expr = ev.payload.get("in_specs")
                out_expr = ev.payload.get("out_specs")
                # specs reachable only through bound names still get
                # internal validation
                for expr in (in_expr, out_expr):
                    for spec in iter_spec_literals(expr, state.env, consts):
                        yield from self._validate_spec(
                            mod, fn, spec, valid, flagged_specs)

                # 3: in_spec rank vs the applied operands' abstract rank
                applied = apply_by_inner.get(id(ev.node))
                if applied is not None and not applied.payload["has_star"]:
                    in_val = resolve_spec(in_expr, state.env, consts)
                    yield from self._check_ranks(
                        mod, fn, applied, in_val)

                # 4: out_spec claiming an axis the body psummed away
                yield from self._check_out_psummed(
                    mod, fn, ev, out_expr, state, consts, graph, facts)

    # -- spec internal validity ----------------------------------------------
    def _validate_spec(self, mod, fn, spec: SpecVal, valid,
                       flagged: Set[int]):
        if spec.node is None or id(spec.node) in flagged:
            return
        seen_axes: Set[str] = set()
        for entry in spec.entries:
            if not isinstance(entry, frozenset):
                continue
            for axis in sorted(entry):
                if axis not in valid:
                    flagged.add(id(spec.node))
                    yield self.finding(
                        mod, spec.node,
                        f"PartitionSpec names mesh axis '{axis}' which the "
                        f"mesh does not declare (axes: "
                        f"{', '.join(sorted(valid))}) — the spec silently "
                        f"partitions nothing (or raises at dispatch on "
                        f"newer jax); use a declared axis",
                        fn.qualname)
                elif axis in seen_axes:
                    flagged.add(id(spec.node))
                    yield self.finding(
                        mod, spec.node,
                        f"PartitionSpec binds mesh axis '{axis}' to two "
                        f"different tensor dims — one mesh axis can "
                        f"partition at most one dim; use a different axis "
                        f"or merge the dims",
                        fn.qualname)
            seen_axes |= {a for a in entry}

    # -- rank alignment -------------------------------------------------------
    def _check_ranks(self, mod, fn, applied, in_val):
        arg_avals = applied.payload["arg_avals"]
        pairs = []
        if isinstance(in_val, TupleVal):
            if len(in_val.items) == len(arg_avals):
                pairs = list(zip(in_val.items, arg_avals, range(
                    len(arg_avals))))
        elif isinstance(in_val, SpecVal):
            pairs = [(in_val, a, i) for i, a in enumerate(arg_avals)]
        for spec, aval, pos in pairs:
            if not isinstance(spec, SpecVal) \
                    or not isinstance(aval, AArray):
                continue
            rank = aval.rank()
            if not isinstance(rank, int):
                continue
            entries = [e for e in spec.entries if e is not UNKNOWN_ENTRY]
            if len(spec.entries) != len(entries):
                continue
            if len(entries) > rank:
                yield self.finding(
                    mod, applied.node,
                    f"in_spec for operand {pos} declares "
                    f"{len(entries)} partitioned dim(s) but the operand's "
                    f"abstract rank is {rank} — the spec cannot bind; "
                    f"align the spec with the operand's shape",
                    fn.qualname)

    # -- out_spec vs psummed return -------------------------------------------
    def _check_out_psummed(self, mod, fn, ev, out_expr, state, consts,
                           graph, facts):
        body = ev.payload.get("body")
        if not isinstance(body, ast.Name):
            return
        targets = graph.resolver.resolve(fn, body.id)
        if not targets:
            return
        # ambiguous body resolution (multiple candidates) counts only
        # when every candidate agrees — a conflict stays a conflict no
        # matter how many more targets follow
        psummed = None
        for t in targets:
            vec = summary_of(facts, t).ret_psummed
            if psummed is None:
                psummed = vec
            elif psummed != vec:
                return
        if psummed is None:
            return
        out_val = resolve_spec(out_expr, state.env, consts)
        if isinstance(out_val, SpecVal):
            out_vec = (out_val,)
        elif isinstance(out_val, TupleVal) and all(
                isinstance(i, SpecVal) for i in out_val.items):
            out_vec = out_val.items
        else:
            return
        if len(out_vec) != len(psummed):
            if len(out_vec) == 1 and len(psummed) > 1:
                # single spec broadcast over a tuple return
                psummed = (frozenset.intersection(*psummed),)
            else:
                return
        for i, (spec, axes) in enumerate(zip(out_vec, psummed)):
            claimed = sorted(spec.axes() & axes)
            if claimed:
                which = f" (output {i})" if len(out_vec) > 1 else ""
                yield self.finding(
                    mod, ev.node,
                    f"out_spec claims axis "
                    f"{', '.join(repr(a) for a in claimed)} partitions the "
                    f"result{which}, but the body already psum-reduced the "
                    f"value over that axis — it is replicated by "
                    f"construction, and with replication checking disabled "
                    f"the spec silently re-declares it sharded; use a "
                    f"replicated out_spec (P()) for reduced outputs",
                    fn.qualname)
