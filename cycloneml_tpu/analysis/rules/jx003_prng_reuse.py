"""JX003 — PRNG key reuse.

JAX keys are consumed, not mutated: passing the same key to two
``jax.random.*`` draws yields IDENTICAL (perfectly correlated) samples —
the classic silent-correctness bug in sampling loops (minibatch masks,
negative sampling, dropout). Keys must be threaded through
``jax.random.split`` / ``fold_in``.

Two detection shapes, both per function:

1. sequential reuse: the same key name consumed by two draw calls with no
   intervening reassignment (``split``/``fold_in`` rebinding counts);
2. loop reuse: a draw inside a ``for``/``while`` body consuming a key
   that is neither assigned inside the loop body nor derived per
   iteration — every iteration then draws the same numbers.
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from cycloneml_tpu.analysis.astutil import (assigned_names, call_name,
                                            last_component)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule

KEY_PRODUCERS = {"PRNGKey", "key", "split", "fold_in", "wrap_key_data",
                 "clone"}
# jax.random draws that CONSUME their key argument (first positional)
NON_CONSUMING = {"PRNGKey", "key", "split", "fold_in", "key_data",
                 "wrap_key_data", "key_impl", "clone"}
# JAX key-threading modules ONLY. Deliberately NOT bare `random` or a
# generic `.random` suffix: `np.random.*` / stdlib `random.*` are STATEFUL
# RNGs whose repeated calls draw fresh samples — matching them would turn
# every `np.random.choice(xs)` pair into a bogus "key reuse" finding.
# (`import jax.random as random` is a miss we accept; the repo uses
# `jax.random` / `jrandom`.)
RANDOM_MODULES = ("jax.random", "jrandom", "jr")


def _is_random_call(name: Optional[str]) -> bool:
    if not name or "." not in name:
        return False
    mod, _, fn = name.rpartition(".")
    return mod in RANDOM_MODULES or mod.endswith("jax.random")


class PRNGReuseRule(Rule):
    rule_id = "JX003"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        for fn in mod.functions:
            yield from self._check_function(mod, fn)

    def _check_function(self, mod: ModuleInfo, fn) -> Iterator[Finding]:
        body = list(getattr(fn.node, "body", []))
        yield from self._scan_block(mod, fn, body, consumed=set(),
                                    key_names=set(), flagged=set())

    def _scan_block(self, mod: ModuleInfo, fn, stmts: List[ast.stmt],
                    consumed: Set[str], key_names: Set[str],
                    flagged: Set[int]):
        """Linear scan in source order; recurses into compound statements.
        ``consumed``: key names already used by one draw. ``key_names``:
        names known to hold PRNG keys. ``flagged``: ids of call nodes
        already reported (loop check + sequential scan overlap)."""
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.For, ast.While)):
                body_assigned = self._names_assigned(stmt.body)
                if isinstance(stmt, ast.For):
                    # `for key in jax.random.split(key, n):` rebinds the
                    # key per iteration — the idiomatic fan-out
                    body_assigned |= set(assigned_names(stmt.target))
                for node in self._calls_in(stmt.body):
                    key = self._consumed_key(node)
                    if key is None or id(node) in flagged:
                        continue
                    # a name a jax.random draw consumes IS a key — so a
                    # function parameter counts even before any assignment
                    if (key in key_names or key in fn.params) \
                            and key not in body_assigned:
                        yield self.finding(
                            mod, node,
                            f"PRNG key `{key}` drawn from inside a loop "
                            f"without per-iteration `split`/`fold_in` — "
                            f"every iteration gets identical samples",
                            fn.qualname)
                        flagged.add(id(node))
                        consumed.add(key)
                # also run the sequential scan inside the body
                yield from self._scan_block(mod, fn, stmt.body, consumed,
                                            key_names, flagged)
                continue
            if isinstance(stmt, ast.If):
                yield from self._scan_calls(mod, fn, [stmt.test], consumed,
                                            key_names, flagged)
                # mutually exclusive branches: at most ONE executes, so a
                # draw per branch is not reuse — scan each against the
                # pre-branch state, then merge (may-consumed afterwards)
                snap = set(consumed)
                yield from self._scan_block(mod, fn, stmt.body, consumed,
                                            key_names, flagged)
                yield from self._scan_block(mod, fn, stmt.orelse, snap,
                                            key_names, flagged)
                consumed.update(snap)
                continue
            if isinstance(stmt, (ast.With, ast.Try)):
                inner = list(getattr(stmt, "body", []))
                for h in getattr(stmt, "handlers", []):
                    inner.extend(h.body)
                inner.extend(getattr(stmt, "orelse", []))
                inner.extend(getattr(stmt, "finalbody", []))
                yield from self._scan_block(mod, fn, inner, consumed,
                                            key_names, flagged)
                continue
            # assignments: key production / rebinding clears consumption
            if isinstance(stmt, ast.Assign):
                names = [n for t in stmt.targets for n in assigned_names(t)]
                produced = self._produces_key(stmt.value)
                for n in names:
                    consumed.discard(n)
                    if produced:
                        key_names.add(n)
            # draws anywhere in this simple statement
            yield from self._scan_calls(mod, fn, [stmt], consumed,
                                        key_names, flagged)

    def _scan_calls(self, mod: ModuleInfo, fn, nodes, consumed: Set[str],
                    key_names: Set[str], flagged: Set[int]):
        for node in self._calls_in(nodes):
            key = self._consumed_key(node)
            if key is None or id(node) in flagged:
                continue
            if key in consumed:
                yield self.finding(
                    mod, node,
                    f"PRNG key `{key}` reused by a second `jax.random` "
                    f"draw without `split`/`fold_in` — the two draws are "
                    f"perfectly correlated",
                    fn.qualname)
                flagged.add(id(node))
            consumed.add(key)
            key_names.add(key)

    @staticmethod
    def _walk_pruned(stmts: List[ast.stmt]):
        """Every node under ``stmts`` EXCLUDING subtrees of nested
        function/lambda/class defs (ast.walk's `continue` would still
        descend — the skip must happen at enqueue time)."""
        stack = list(stmts)
        while stack:
            node = stack.pop()
            yield node
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda, ast.ClassDef)):
                continue
            stack.extend(ast.iter_child_nodes(node))

    @classmethod
    def _calls_in(cls, stmts: List[ast.stmt]) -> List[ast.Call]:
        return [n for n in cls._walk_pruned(stmts)
                if isinstance(n, ast.Call)]

    @classmethod
    def _names_assigned(cls, stmts: List[ast.stmt]) -> Set[str]:
        out: Set[str] = set()
        for node in cls._walk_pruned(stmts):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    out.update(assigned_names(t))
            elif isinstance(node, (ast.AugAssign, ast.AnnAssign)):
                out.update(assigned_names(node.target))
        return out

    @staticmethod
    def _consumed_key(call: ast.Call) -> Optional[str]:
        """Name of the key consumed by this jax.random draw, if any."""
        name = call_name(call)
        if not _is_random_call(name):
            return None
        if last_component(name) in NON_CONSUMING:
            return None
        args = list(call.args)
        key_arg = args[0] if args else None
        for kw in call.keywords:
            if kw.arg == "key":
                key_arg = kw.value
        if isinstance(key_arg, ast.Name):
            return key_arg.id
        return None

    @staticmethod
    def _produces_key(expr: ast.AST) -> bool:
        for node in ast.walk(expr):
            if isinstance(node, ast.Call):
                name = call_name(node)
                if _is_random_call(name) \
                        and last_component(name) in KEY_PRODUCERS:
                    return True
        return False
