"""JX020 — fault-point table and injection sites cross-checked both ways.

The fault-point table in ``parallel/faults.py``'s docstring is the
contract the chaos suite, the resilience docs and the runtime sites all
reference — and nothing enforced it. Three deviations convict (Engler's
cross-checking: infer the belief from N sites, flag the odd one out):

1. a **registered point no site fires** — the table promises an
   injection point that cannot inject; a chaos test scheduling it waits
   forever (reported on the table row itself);
2. an **injection site naming an unregistered point** — a typo'd
   ``faults.inject("serving.dispach", ...)`` silently never fires (the
   schedule matches on the exact string), with a closest-name suggestion
   in the JX019 style;
3. a **retry boundary without a reachable fault point** — a function
   that classifies/retries failures (``classify_failure`` /
   ``retry_step``) but cannot reach any ``faults.inject`` site holds the
   belief "this path fails transiently" while being untestable under the
   chaos harness. Higher-order wrappers that retry a callable parameter
   (``retry_step(fn)`` itself) are exempt — the injectable site lives in
   the callable they are handed.

"Reaches a fault point" is the shared bottom-up ``JXFAULT`` dataflow
fact (this rule is its fixpoint client; JX023 scopes on the same
summaries): a function's summary is True when its own body holds a
literal injection site or any resolved callee's summary is True.

When no fault-point table is in the analyzed set the rule stays silent —
there is no registry to check against.
"""

from __future__ import annotations

import ast
from typing import Iterator

from cycloneml_tpu.analysis.astutil import (FunctionInfo, call_name,
                                            last_component)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.registries import (fault_registry,
                                               injection_sites,
                                               is_injection_call)
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.rules.jx019_conf_keys import _closest

#: call names that mark a retried/classified dispatch boundary
RETRY_BOUNDARY_CALLS = {"retry_step", "classify_failure"}

FAULT_ANALYSIS = "JXFAULT"


def fault_initial(fn: FunctionInfo, graph) -> bool:
    """Does ``fn``'s own body hold a literal injection site?"""
    return any(is_injection_call(call) is not None
               for call in graph.index(fn).calls)


def fault_transfer(fn: FunctionInfo, facts, graph) -> bool:
    out = facts.get(fn, False)
    if out:
        return True
    for site in graph.sites(fn):
        if any(facts.get(t, False) is True for t in site.targets):
            return True
    return out


def _calls_own_param(fn: FunctionInfo, graph) -> bool:
    """``fn`` invokes one of its own parameters — a higher-order wrapper
    whose injectable site is the callable it was handed."""
    return any(isinstance(call.func, ast.Name) and call.func.id in fn.params
               for call in graph.index(fn).calls)


class FaultCoverageRule(DataflowRule):
    rule_id = "JX020"

    @property
    def analysis_id(self) -> str:
        return FAULT_ANALYSIS

    # -- shared JXFAULT summary: may this function reach an inject site? -----
    def initial(self, fn: FunctionInfo, graph, ctx) -> bool:
        return fault_initial(fn, graph)

    def transfer(self, fn: FunctionInfo, facts, graph, ctx) -> bool:
        return fault_transfer(fn, facts, graph)

    def top(self, fn, graph, ctx) -> bool:
        return True

    # -- the check -----------------------------------------------------------
    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        registry = fault_registry(ctx)
        if not registry.points:
            return
        sites = injection_sites(ctx)
        fired = {s.point for s in sites}

        # 1. registered points no site fires, anchored on the table row
        if mod.path in registry.table_modules:
            for point in registry.points.values():
                if point.module_path != mod.path or point.name in fired:
                    continue
                anchor = ast.Constant(value=point.name)
                anchor.lineno = anchor.end_lineno = point.line
                anchor.col_offset = anchor.end_col_offset = 0
                yield self.finding(
                    mod, anchor,
                    f"fault point '{point.name}' is registered in this "
                    f"table but NO injection site fires it — a chaos "
                    f"schedule targeting it waits forever; add a "
                    f"faults.inject('{point.name}', ...) at the boundary "
                    f"it documents, or drop the row")

        # 2. sites naming unregistered points (typos never fire)
        registered = set(registry.points)
        for site in sites:
            if site.module_path != mod.path or site.point in registered:
                continue
            close = _closest(site.point, registered)
            hint = f"; did you mean '{close}'?" if close else ""
            yield self.finding(
                mod, site.node,
                f"'{site.point}' is not in the fault-point table "
                f"(parallel/faults.py) — schedules match on the exact "
                f"string, so this site can never fire{hint}",
                site.function)

        # 3. retry boundaries that cannot reach any fault point
        graph = ctx.callgraph
        if graph is None:
            return
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        for fn in mod.functions:
            if facts.get(fn, False) is True:
                continue
            boundary = next(
                (call for call in graph.index(fn).calls
                 if last_component(call_name(call) or "")
                 in RETRY_BOUNDARY_CALLS), None)
            if boundary is None:
                continue
            if _calls_own_param(fn, graph):
                continue
            yield self.finding(
                mod, boundary,
                f"`{fn.qualname}` classifies/retries failures but cannot "
                f"reach any faults.inject site — the retry path is "
                f"untestable under the chaos harness (every other "
                f"retried boundary carries a fault point); add one at "
                f"the dispatch this retry protects",
                fn.qualname)
