"""JX014 — blocking call inside a held-lock region.

A lock held across a blocking operation turns one slow thing into a
convoy: every thread that wants the lock now waits for the sleep, the
``Future.result()``, the thread ``join()``, the compiled-program
dispatch, or — worst — a mesh collective (then the lock's critical
section is gated on a cross-process rendezvous, and a lock+rendezvous
pair in two orders is the PR-2 deadlock). The rule flags blocking calls
whose lexical lockset is non-empty, interprocedurally: a helper that
blocks taints its callers — ``with self._lock: self._drain()`` flags when
``_drain`` (transitively) sleeps, three calls deep.

Blocking primitives: ``time.sleep``, ``Future.result()``, thread-shaped
``.join()`` (receiver named ``*thread*``/``*worker*``/``*proc*`` — string
and ``os.path`` joins are not locks' business), ``block_until_ready``,
``device_get`` (host sync), blocking queue ``.get()`` on a queue-shaped
receiver, ``Event``/``Condition`` ``.wait()``, collective dispatch
(``psum``-family, ``tree_aggregate``-family), and calls to names bound to
``jax.jit`` programs (a dispatch can hide a compile).

The one sanctioned blocking-wait-under-lock is the condition-variable
loop — ``with self._cv: while not ready: self._cv.wait()`` — because
``wait`` RELEASES the lock it blocks on: waiting on the lock you hold is
exempt; waiting on anything else while holding a lock still flags. The
exemption extends to the *may-block summary*: a ``.wait()`` whose
receiver resolves to a known lock/cv does not make its function a
blocker, because a Condition wait REQUIRES holding that cv (working code
always holds it) and releases it while blocked — so the factored wait
loop (``with self._cv: self._wait_ready()``) stays clean. Known
limitation, chosen deliberately: a helper cv-wait made while the caller
holds a SECOND lock is missed (the second lock is NOT released); the
ratchet-0 gate makes the false positive the costlier error. Bare
``lock.acquire()`` is not "blocking" here — self/cyclic re-acquisition
is JX012's finding, drawn from the same acquisition model.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from cycloneml_tpu.analysis.astutil import (FunctionInfo, call_name,
                                            last_component)
from cycloneml_tpu.analysis.dataflow import ProgramBindingsCache
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.locks import LockModel, model_for
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.rules.jx010_collective_divergence import \
    COLLECTIVE_CALLS
from cycloneml_tpu.analysis.rules.jx013_obligation_leak import _queueish

BLOCKING_SIMPLE = {"sleep", "block_until_ready", "device_get"}
THREADISH = ("thread", "worker", "proc")


class BlockingUnderLockRule(DataflowRule):
    rule_id = "JX014"

    def __init__(self):
        self._bindings = ProgramBindingsCache()

    # -- summary: may this function block? (bottom-up bool) ------------------
    def initial(self, fn: FunctionInfo, graph, ctx) -> bool:
        bindings = self._bindings.bindings_for(fn, ctx, graph)
        model = model_for(ctx)
        for call in graph.index(fn).calls:
            if _is_lock_wait(call, model, fn):
                # waiting on a cv you (necessarily) hold releases it —
                # the factored wait-loop helper is not a blocker
                continue
            if _blocking_reason(call, bindings) is not None:
                return True
        return False

    def transfer(self, fn: FunctionInfo, facts, graph, ctx) -> bool:
        out = facts.get(fn, False)
        if out:
            return True
        for site in graph.sites(fn):
            if any(facts.get(t, False) is True for t in site.targets):
                return True
        return out

    def top(self, fn, graph, ctx):
        return True

    # -- the check -----------------------------------------------------------
    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        graph = ctx.callgraph
        if graph is None:
            return
        model = model_for(ctx)
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        for fn in mod.functions:
            if fn.jit_reachable:
                continue   # traced code has no host locks to convoy
            info = model.info(fn)
            if not info.call_locks:
                continue
            bindings = self._bindings.bindings_for(fn, ctx, graph)
            sites = graph.sites_map(fn)
            for call in graph.index(fn).calls:
                held = info.call_locks.get(id(call))
                if not held:
                    continue
                if _is_wait_on_held(call, held, model, fn):
                    continue   # cv-wait releases the lock it blocks on
                reason = _blocking_reason(call, bindings)
                if reason is not None:
                    yield self.finding(
                        mod, call,
                        f"{reason} while holding "
                        f"{_pretty_locks(held)} — every thread wanting "
                        f"the lock now waits out the blocking call "
                        f"(convoy; a collective here can deadlock the "
                        f"mesh); move the blocking call outside the "
                        f"critical section (snapshot under the lock, "
                        f"release, then block)",
                        fn.qualname)
                    continue
                site = sites.get(id(call))
                if site is None:
                    continue
                blocker = next((t for t in site.targets
                                if facts.get(t, False) is True), None)
                if blocker is not None:
                    yield self.finding(
                        mod, call,
                        f"`{blocker.qualname}` can block (sleep/wait/"
                        f"dispatch, transitively) and is called while "
                        f"holding {_pretty_locks(held)} — the lock is "
                        f"held across the wait (convoy / deadlock "
                        f"exposure); call it after releasing the lock",
                        fn.qualname)


def _blocking_reason(call: ast.Call,
                     bindings) -> Optional[str]:
    """A human-readable reason when ``call`` is a blocking primitive."""
    name = call_name(call)
    base = last_component(name)
    if base is None:
        return None
    if base in BLOCKING_SIMPLE:
        return f"`{name}` blocks"
    if base in COLLECTIVE_CALLS:
        return f"collective `{name}` rendezvouses the whole mesh"
    receiver = None
    if isinstance(call.func, ast.Attribute):
        from cycloneml_tpu.analysis.astutil import dotted_name
        receiver = dotted_name(call.func.value)
    if base == "result":
        return f"`{name}()` blocks until the future completes"
    if base == "join":
        low = (receiver or "").lower()
        if any(t in low for t in THREADISH):
            return f"`{name}()` blocks until the thread exits"
        return None
    if base == "wait":
        return f"`{name}()` blocks until signaled"
    if base in ("get", "popleft") and receiver is None:
        return None
    if base == "get" and _queueish(receiver) and not call.keywords \
            and len(call.args) == 0:
        return f"queue `{name}()` blocks until an item arrives"
    if isinstance(call.func, ast.Name) and call.func.id in bindings:
        return (f"compiled-program dispatch `{call.func.id}(...)` can "
                f"block (and hide a compile)")
    return None


def _is_wait_on_held(call: ast.Call, held, model: LockModel,
                     fn: FunctionInfo) -> bool:
    # NOT `acquire`: Lock.acquire releases nothing — re-acquiring a held
    # lock is the JX012 self-deadlock, and acquiring another lock under
    # one is a JX012 ordering edge, never an exemption here
    if not isinstance(call.func, ast.Attribute) \
            or call.func.attr not in ("wait", "wait_for",
                                      "notify", "notify_all"):
        return False
    if call.func.attr in ("notify", "notify_all"):
        return True   # notify never blocks
    lid = model.lock_id(call.func.value, fn)
    return lid is not None and lid in held


def _is_lock_wait(call: ast.Call, model: LockModel,
                  fn: FunctionInfo) -> bool:
    """`X.wait()` where X resolves to a known lock/cv — a Condition wait
    requires holding X and releases it while blocked."""
    return (isinstance(call.func, ast.Attribute)
            and call.func.attr in ("wait", "wait_for")
            and model.lock_id(call.func.value, fn) is not None)


def _pretty_locks(held) -> str:
    return ", ".join(f"`{h}`" for h in sorted(held))
