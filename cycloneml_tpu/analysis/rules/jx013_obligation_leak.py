"""JX013 — request/future obligation leaked on a path to function exit.

The serving batcher's whole no-hang contract is one sentence: *every
request popped from a lane queue completes its future* — ``set_result``,
``set_exception``, or a requeue — on **every** path, including the error
paths. PR-8's reviews hand-fixed exactly this bug four times (requeue
racing ``stop()``, mid-split backpressure, post-stop slip-ins, permanent
dispatch failures). This rule proves the discipline statically, as a
typestate obligation: a value popped from a lane/queue acquires an
obligation that must be *discharged* before the function exits.

Obligation sources: ``x = <queue>.popleft() / .get() / .get_nowait() /
.pop()`` where the receiver is queue-shaped by name (``*queue*``, ``q``,
``lane``, ``pending``, ``inbox``). Discharges:

* completing: ``x.set_result(...)``, ``x.set_exception(...)``,
  ``x.cancel()`` — on ``x`` or anything reached through it
  (``x.future.set_exception(e)``);
* requeueing/handing off: ``x`` passed bare to an ``append`` /
  ``appendleft`` / ``put`` / ``submit`` / ``push``-shaped call;
* escaping: ``x`` returned, yielded, re-assigned, or stored into a
  container/attribute (someone else now holds it);
* interprocedural: ``x`` passed bare to a resolved callee whose
  bottom-up summary says that parameter position is discharged
  (``self._fail_batch(batch, err)``); an *unresolvable* call discharges
  conservatively — silence over noise.

The walk reuses the shared terminator machinery (branch may-merges,
loop/with/try semantics — :mod:`..walker`): a pending obligation at a
``return``, an uncaught ``raise``, or the end of the body is reported at
the **pop site**, naming the leaking exit. A ``raise`` under a ``try``
with handlers or a ``finally`` is not reported — the handler may still
complete the future (and usually does; that is the idiom).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from cycloneml_tpu.analysis.astutil import (FunctionInfo, assigned_names,
                                            call_name, last_component)
from cycloneml_tpu.analysis.dataflow import (EMPTY, TOP, join_sets,
                                             param_index, set_contains)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.walker import BlockWalker

#: pop-shaped methods that transfer ownership of a queued request
SOURCE_METHODS = {"popleft", "pop", "get", "get_nowait"}

#: receiver names that make a pop an obligation source (NOT "work"/"jobs":
#: worklist-pattern deques are pervasive and carry no futures)
def _queueish(receiver: Optional[str]) -> bool:
    if not receiver:
        return False
    last = receiver.rsplit(".", 1)[-1].lower().lstrip("_")
    return ("queue" in last or "inbox" in last or "backlog" in last
            or last in ("q", "lane", "pending", "inflight"))

#: completion methods on the obligated value (or through its attributes)
DISCHARGE_METHODS = {"set_result", "set_exception", "cancel"}

#: call names that take ownership when the value is passed bare
HANDOFF_WORDS = ("append", "appendleft", "put", "push", "submit", "enqueue",
                 "requeue", "add", "extend", "insert", "send", "emit",
                 "complete", "fail", "cancel", "resolve", "publish")


class ObligationLeakRule(DataflowRule):
    rule_id = "JX013"

    # -- summary: which of MY param positions do I discharge? ----------------
    def initial(self, fn: FunctionInfo, graph, ctx):
        params = param_index(fn)
        if not params:
            return EMPTY
        discharged = _own_discharged_names(fn, graph)
        return frozenset(params[n] for n in discharged if n in params)

    def transfer(self, fn: FunctionInfo, facts, graph, ctx):
        out = facts.get(fn, EMPTY)
        if out is TOP:
            return TOP
        params = param_index(fn)
        if not params:
            return out
        add = set()
        for site in graph.sites(fn):
            for target in site.targets:
                summary = facts.get(target)
                if not summary or summary is TOP:
                    continue
                for pi, expr in site.param_map(target):
                    if set_contains(summary, pi) \
                            and isinstance(expr, ast.Name) \
                            and expr.id in params:
                        add.add(params[expr.id])
        return join_sets(out, frozenset(add))

    # -- the check -----------------------------------------------------------
    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        graph = ctx.callgraph
        if graph is None:
            return
        if not _module_completes_futures(mod):
            # evidence gate (bugs-as-deviant-behavior): a queue is only a
            # REQUEST queue if this module somewhere completes futures —
            # worklist deques and event pumps never do, and obligating
            # them would be pure noise
            return
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        for fn in mod.functions:
            if fn.jit_reachable:
                continue
            w = _ObligationWalker(self, mod, fn, graph.sites_map(fn), facts)
            w.walk(getattr(fn.node, "body", []))
            yield from w.findings


class _ObligationWalker(BlockWalker):
    """``state`` maps name -> the pop Call that created its obligation."""

    def __init__(self, rule: ObligationLeakRule, mod: ModuleInfo,
                 fn: FunctionInfo, sites, facts):
        super().__init__()
        self.rule, self.mod, self.fn = rule, mod, fn
        self.sites, self.facts = sites, facts
        self.findings: List[Finding] = []
        self._reported: Set[int] = set()

    # -- sources -------------------------------------------------------------
    def run_stmt(self, stmt: ast.AST):
        if isinstance(stmt, ast.Assign):
            value = stmt.value
            if _is_source(value):
                for t in stmt.targets:
                    self.bind(t)
                names = [n for t in stmt.targets
                         for n in assigned_names(t)]
                if len(names) == 1:
                    self.state[names[0]] = value
                return None
            # escaping/aliasing assignment discharges bare mentions:
            # someone else holds the value now
            self.visit_expr(value)
            for name in _bare_names(value):
                self.state.pop(name, None)
            for t in stmt.targets:
                self.bind(t)
            return None
        if isinstance(stmt, ast.Return):
            # returning the value escapes it to the caller — discharge
            # BEFORE the exit check (the base walker only visits)
            if stmt.value is not None:
                self.visit_expr(stmt.value)
                for name in _bare_names(stmt.value):
                    self.state.pop(name, None)
            # a clean return runs no except handler — only an enclosing
            # `finally` (which may discharge) protects it
            if not self._return_protected():
                self.on_exit(stmt, "return")
            return "exit"
        return super().run_stmt(stmt)

    # -- expression scan: discharges -----------------------------------------
    def visit_expr(self, expr: ast.AST) -> None:
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(expr, ast.Call):
            for child in ast.iter_child_nodes(expr):
                self.visit_expr(child)
            self._visit_call(expr)
            return
        if isinstance(expr, (ast.Return, ast.Yield, ast.YieldFrom)):
            value = getattr(expr, "value", None)
            if value is not None:
                self.visit_expr(value)
                for name in _bare_names(value):
                    self.state.pop(name, None)   # escaped to the caller
            return
        for child in ast.iter_child_nodes(expr):
            self.visit_expr(child)

    def _visit_call(self, call: ast.Call) -> None:
        state = self.state
        name = call_name(call)
        base = last_component(name)
        # completion through the value: r.future.set_exception(e)
        if base in DISCHARGE_METHODS and isinstance(call.func, ast.Attribute):
            root = _root_name(call.func.value)
            if root is not None:
                state.pop(root, None)
                return
        args = list(call.args) + [kw.value for kw in call.keywords]
        bare = [n for a in args for n in _bare_names(a) if n in state]
        if not bare:
            return
        if base is not None and any(w in base.lower()
                                    for w in HANDOFF_WORDS):
            for n in bare:
                state.pop(n, None)
            return
        site = self.sites.get(id(call))
        if site is not None and site.targets:
            # resolved: trust the callee's summary for bare Name args ...
            for target in site.targets:
                summary = self.facts.get(target, EMPTY)
                for pi, expr in site.param_map(target):
                    if isinstance(expr, ast.Name) and expr.id in state \
                            and set_contains(summary, pi):
                        state.pop(expr.id, None)
            # ... but a mention wrapped in a container ([r], (r, err)) is
            # an opaque hand-off even to a resolved callee — silence wins
            for a in args:
                if not isinstance(a, ast.Name):
                    for n in _bare_names(a):
                        state.pop(n, None)
            return
        # unresolvable call: assume it takes ownership (silence > noise)
        for n in bare:
            state.pop(n, None)

    # -- exits ---------------------------------------------------------------
    def on_exit(self, stmt: Optional[ast.AST], kind: str) -> None:
        where = {"return": "this `return`",
                 "raise": "this `raise` (the error path)",
                 "end": "the end of the function"}[kind]
        line = getattr(stmt, "lineno", None)
        at = f" at line {line}" if line is not None else ""
        for name, src in list(self.state.items()):
            if id(src) in self._reported:
                continue
            self._reported.add(id(src))
            self.findings.append(self.rule.finding(
                self.mod, src,
                f"`{name}` is popped from the queue here but can reach "
                f"{where}{at} without `set_result`/`set_exception`/"
                f"requeue — a stranded request: its caller blocks on the "
                f"future forever; complete or requeue it on every path "
                f"(error paths included)",
                self.fn.qualname))


# -- helpers ------------------------------------------------------------------

_COMPLETION_METHODS = {"set_result", "set_exception"}


def _module_completes_futures(mod: ModuleInfo) -> bool:
    got = getattr(mod, "_jx013_evidence", None)
    if got is None:
        got = any(isinstance(n, ast.Attribute)
                  and n.attr in _COMPLETION_METHODS
                  for n in ast.walk(mod.tree))
        mod._jx013_evidence = got   # cached on the module record itself
    return got


def _is_source(value: ast.AST) -> bool:
    if not isinstance(value, ast.Call) \
            or not isinstance(value.func, ast.Attribute):
        return False
    if value.func.attr not in SOURCE_METHODS:
        return False
    from cycloneml_tpu.analysis.astutil import dotted_name
    return _queueish(dotted_name(value.func.value))


def _root_name(expr: ast.AST) -> Optional[str]:
    while isinstance(expr, ast.Attribute):
        expr = expr.value
    return expr.id if isinstance(expr, ast.Name) else None


def _bare_names(expr: ast.AST) -> Iterator[str]:
    """Names occurring in ``expr`` OUTSIDE pure attribute-receiver
    position: `r` and `[r, s]` yield, `r.n` does not (reading a field is
    not a hand-off)."""
    if isinstance(expr, ast.Name):
        yield expr.id
        return
    if isinstance(expr, ast.Attribute):
        return
    for child in ast.iter_child_nodes(expr):
        yield from _bare_names(child)


def _own_discharged_names(fn: FunctionInfo, graph) -> Set[str]:
    """Names this function's own body visibly discharges (completion
    calls, hand-off calls, loops over them discharging the element) —
    the facts-independent seed of the summary."""
    out: Set[str] = set()
    idx = graph.index(fn)
    for _ in range(2):   # element-of-loop discharge needs a second pass
        for call in idx.calls:
            name = call_name(call)
            base = last_component(name)
            if base in DISCHARGE_METHODS \
                    and isinstance(call.func, ast.Attribute):
                root = _root_name(call.func.value)
                if root is not None:
                    out.add(root)
                continue
            if base is not None and any(w in base.lower()
                                        for w in HANDOFF_WORDS):
                for a in call.args:
                    if isinstance(a, ast.Name):
                        out.add(a.id)
        for loop in idx.loops:
            if not isinstance(loop, (ast.For, ast.AsyncFor)):
                continue
            targets = set(assigned_names(loop.target))
            if targets & out and isinstance(loop.iter, ast.Name):
                # `for r in batch: r.future.set_exception(e)` discharges
                # every element — the container is discharged
                out.add(loop.iter.id)
    return out
