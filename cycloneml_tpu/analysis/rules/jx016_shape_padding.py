"""JX016 — shape and padding hazards: provable dim conflicts, unmasked
reductions over padded dims.

Two hazard classes, both invisible at the callsite and both currently
pinned only by tests:

**Provable shape mismatches.** The abstract interpreter carries symbolic
and concrete dims through constructors, broadcasting, and matmul; when
two *concrete* dims provably conflict (``jnp.zeros((4, d)) +
jnp.zeros((8, d))``, a matmul whose inner dims are unequal ints) the
program either fails at trace time deep inside a dispatch stack — far
from the line that built the wrong buffer — or, worse, broadcasts a
``1`` where a real dim was meant. Only provable conflicts are reported:
two distinct *symbols* may be equal at runtime and stay silent.

**Unmasked mean over a padded dim.** The repo pads everywhere rows meet
a fixed program shape: serving buckets pad request batches up to the
power-of-two bucket, ``deviceChunk`` pads the last L-BFGS chunk,
``blockify_arrays`` pads blocks to multiples. The invariant that makes
padding bitwise-neutral is that every reduction over the padded dim is
*masked* (weighted sums with w=0 pads, sum/count with explicit counts)
— a raw ``jnp.mean(x, axis=0)`` divides by the padded row count and
silently shifts every statistic. The interpreter marks dims padded at
``jnp.pad``/``np.pad``, the ``buf = np.zeros((bucket, d)); buf[:k] =
rows`` store idiom, and ``.at[:k].set(rows)``; slicing the dim back
down (``buf[:k]``) clears the mark. The check is interprocedural: a
kernel whose summary says "takes an unmasked mean over param 2's dim 0"
convicts the *caller* that passes a padded buffer, which is where the
fix belongs (mask the kernel or pass the true count).
"""

from __future__ import annotations

from typing import Iterator, Set

from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.shapes import AArray, ShapeRuleBase


class ShapePaddingRule(ShapeRuleBase, DataflowRule):
    rule_id = "JX016"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        if ctx.callgraph is None:
            return
        for fn in mod.functions:
            state = self.state_of(ctx, fn)
            if state is None:
                continue
            reported: Set[tuple] = set()
            for ev in state.events:
                if ev.kind == "mismatch":
                    key = ("mismatch", id(ev.node), ev.detail)
                    if key in reported:
                        continue
                    reported.add(key)
                    yield self.finding(
                        mod, ev.node,
                        f"provable shape mismatch: {ev.detail} — this "
                        f"either fails at trace time deep inside the "
                        f"dispatch stack or broadcasts a 1 where a real "
                        f"dim was meant; fix the operand shapes here",
                        fn.qualname)
                elif ev.kind == "mean":
                    aval = ev.aval
                    if not isinstance(aval, AArray) or not aval.padded:
                        continue
                    axes = ev.axes or frozenset()
                    hit = sorted(aval.padded) if not axes else sorted(
                        aval.padded & {a for a in axes
                                       if isinstance(a, int) and a >= 0})
                    if not hit:
                        continue
                    key = ("mean", id(ev.node))
                    if key in reported:
                        continue
                    reported.add(key)
                    via = f" ({ev.detail})" if ev.detail.startswith("via") \
                        else ""
                    yield self.finding(
                        mod, ev.node,
                        f"unmasked mean over padded dim "
                        f"{', '.join(map(str, hit))}{via} — the divisor "
                        f"counts the zero pad rows, silently shifting the "
                        f"statistic; mask the reduction (weighted sum / "
                        f"explicit count) or slice the padding off first",
                        fn.qualname)
