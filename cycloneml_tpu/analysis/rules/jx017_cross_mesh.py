"""JX017 — SPMD program reused across a mesh rebuild.

Every compiled program in this repo closes over the mesh it was built
under: ``tree_aggregate`` keys its cache on ``runtime.mesh``,
``shard_map`` bakes the device assignment into the executable, and the
serving layer AOT-warms bucket programs against the registration-time
mesh. When ``MeshSupervisor`` rebuilds after device loss (or elastic
scheduling resizes the mesh, ROADMAP item 5), every one of those
programs is stale — dispatching one either crashes on dead devices or
silently runs on the OLD device set. ``clear_program_cache`` exists
precisely to prevent this — but it only empties the *caches*; a local
or field that still **holds** a program object keeps dispatching it.
This rule checks the invariant statically.

The abstract fact is a **mesh-identity token**: an epoch counter that
advances at every rebuild event (``mesh.reset()``, ``rebuild_mesh``,
or a call into a helper whose JXSHAPE summary says it transitively
rebuilds — ``MeshSupervisor.recover`` counts through any number of
hops). A name bound to a program (a ``tree_aggregate``/``shard_map``
builder call, or a call into a helper whose summary says it *returns*
a program) carries the epoch at its build; dispatching it under a
later epoch is the finding. The check is interprocedural on both
sides: the program may be built in a helper and the rebuild buried in
another, with the conviction landing in the caller that holds the
stale reference.

Loop bodies are walked twice, so the second-iteration hazard —
program built before the loop, a recovery path inside it — is caught
even though the dispatch textually precedes the rebuild.

The sanctioned idiom stays silent: clear the cache, rebuild the mesh,
then REBUILD the program before dispatching (``MeshSupervisor.recover``
does exactly this) — a binding re-established after the rebuild
carries the current epoch.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from cycloneml_tpu.analysis.astutil import (call_name, dotted_name,
                                            last_component)
from cycloneml_tpu.analysis.dataflow import assign_targets
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.shapes import (PROGRAM_BUILDERS, REBUILD_DOTTED,
                                           REBUILD_LAST, ShapeRuleBase,
                                           summary_of)


class CrossMeshReuseRule(ShapeRuleBase, DataflowRule):
    rule_id = "JX017"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        graph = ctx.callgraph
        if graph is None:
            return
        facts = self.facts(ctx)
        for fn in mod.functions:
            walker = _EpochWalker(fn, graph, facts)
            walker.run()
            for node, name in walker.findings:
                yield self.finding(
                    mod, node,
                    f"program `{name}` was built under a previous mesh "
                    f"and is dispatched after a mesh rebuild — compiled "
                    f"programs close over their mesh's device assignment, "
                    f"so this runs on dead/old devices; rebuild the "
                    f"program after the rebuild (clear_program_cache + "
                    f"re-invoke the builder), the MeshSupervisor.recover "
                    f"idiom",
                    fn.qualname)


class _EpochWalker:
    """Source-order mesh-epoch tracking over one function's own body."""

    def __init__(self, fn, graph, facts):
        self.fn = fn
        self.graph = graph
        self.facts = facts
        self.sites = graph.sites_map(fn)
        self.epoch = 0
        self.bindings: Dict[str, int] = {}   # name / "self.x" -> build epoch
        self.findings: List[tuple] = []
        self._seen: Set[int] = set()

    def run(self):
        self._walk(getattr(self.fn.node, "body", []))

    # -- call classification --------------------------------------------------
    def _call_rebuilds(self, call: ast.Call) -> bool:
        name = call_name(call) or ""
        base = last_component(name) or ""
        if base in REBUILD_LAST or name in REBUILD_DOTTED:
            return True
        if name.endswith(".reset") and "mesh" in name.split(".")[0].lower():
            return True
        site = self.sites.get(id(call))
        if site is not None:
            return any(summary_of(self.facts, t).rebuilds
                       for t in site.targets)
        return False

    def _call_builds(self, call: ast.Call) -> bool:
        base = last_component(call_name(call) or "") or ""
        if base in PROGRAM_BUILDERS:
            return True
        site = self.sites.get(id(call))
        if site is not None:
            return any(summary_of(self.facts, t).returns_program
                       for t in site.targets)
        return False

    # -- walking --------------------------------------------------------------
    def _walk(self, stmts):
        for stmt in stmts:
            self._stmt(stmt)

    def _scan_calls(self, expr: ast.AST):
        """Visit every call inside an expression in source order:
        dispatches of tracked bindings are checked, rebuild events
        advance the epoch."""
        for node in ast.walk(expr):
            if not isinstance(node, ast.Call):
                continue
            target = self._binding_name(node.func)
            if target is not None and target in self.bindings:
                if self.bindings[target] < self.epoch \
                        and id(node) not in self._seen:
                    self._seen.add(id(node))
                    self.findings.append((node, target))
            if self._call_rebuilds(node):
                self.epoch += 1

    @staticmethod
    def _binding_name(func: ast.AST) -> Optional[str]:
        if isinstance(func, ast.Name):
            return func.id
        name = dotted_name(func)
        if name is not None and name.startswith("self.") \
                and name.count(".") == 1:
            return name
        return None

    def _stmt(self, stmt: ast.AST):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = getattr(stmt, "value", None)
            if value is None:
                return
            self._scan_calls(value)
            builds = isinstance(value, ast.Call) and self._call_builds(value)
            for target in assign_targets(stmt):
                name = self._target_name(target)
                if name is None:
                    continue
                if builds:
                    self.bindings[name] = self.epoch
                else:
                    self.bindings.pop(name, None)
        elif isinstance(stmt, ast.AugAssign):
            self._scan_calls(stmt.value)
        elif isinstance(stmt, (ast.Expr, ast.Return, ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self._scan_calls(child)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._scan_calls(stmt.iter)
            # twice: a rebuild late in the body precedes the next
            # iteration's dispatch
            self._walk(stmt.body)
            self._walk(stmt.body)
            self._walk(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self._scan_calls(stmt.test)
            self._walk(stmt.body)
            self._scan_calls(stmt.test)
            self._walk(stmt.body)
            self._walk(stmt.orelse)
        elif isinstance(stmt, ast.If):
            # the branches are EXCLUSIVE: a rebuild in the then-branch
            # must not convict a dispatch in the else-branch (the
            # `if mesh_dead: recover() else: dispatch` supervisor
            # shape). Code AFTER the If merges the max epoch of the
            # arms that can FALL THROUGH — a branch that returns/raises
            # never reaches the code below, so its rebuild does not
            # either (`if dead: recover(); return` then dispatch).
            self._scan_calls(stmt.test)
            before = self.epoch
            self._walk(stmt.body)
            after_body = self.epoch
            self.epoch = before
            self._walk(stmt.orelse)
            after_orelse = self.epoch
            merged = before
            if not _terminates(stmt.body):
                merged = max(merged, after_body)
            if not (stmt.orelse and _terminates(stmt.orelse)):
                merged = max(merged, after_orelse)
            self.epoch = merged
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._scan_calls(item.context_expr)
            self._walk(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._walk(stmt.body)
            for h in stmt.handlers:
                self._walk(h.body)
            self._walk(stmt.orelse)
            self._walk(stmt.finalbody)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                name = self._target_name(t)
                if name is not None:
                    self.bindings.pop(name, None)

    @staticmethod
    def _target_name(target: ast.AST) -> Optional[str]:
        return _target_name(target)


def _terminates(stmts) -> bool:
    """Does this block definitely NOT fall through (ends in
    return/raise/continue/break on every path)?"""
    for s in stmts:
        if isinstance(s, (ast.Return, ast.Raise, ast.Continue, ast.Break)):
            return True
        if isinstance(s, ast.If) and s.orelse and _terminates(s.body) \
                and _terminates(s.orelse):
            return True
    return False


def _target_name(target: ast.AST) -> Optional[str]:
    if isinstance(target, ast.Name):
        return target.id
    name = dotted_name(target)
    if name is not None and name.startswith("self.") \
            and name.count(".") == 1:
        return name
    return None
