"""JX001 — implicit host↔device synchronization.

Two shapes of the same hazard:

a) **Inside jit-reachable (traced) code**: ``float(x)`` / ``int(x)`` /
   ``bool(x)`` / ``x.item()`` / ``np.asarray(x)`` on a traced value.
   Under ``jax.jit`` these either raise a ``TracerConversionError`` at
   first trace or — worse, outside jit but on device values in a hot
   loop — force a blocking device->host transfer per call.

b) **In host driver code**: pulling several scalars piecemeal out of the
   result of a compiled aggregation program (``out = run(...)`` then
   ``float(out["loss"])``, ``float(out["wsum"])``, ...). Each conversion
   is its own blocking transfer through the dispatch relay; one
   ``jax.device_get(out)`` batches them into a single round trip. Only
   flagged at >= 2 pulls — a single conversion is already minimal.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set

from cycloneml_tpu.analysis.astutil import (TaintTracker, assigned_names,
                                            call_name, iter_own_statements,
                                            last_component)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule

COERCIONS = {"float", "int", "bool", "complex"}
HOST_ARRAY_CALLS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                    "onp.asarray", "onp.array"}
# callables whose result is a live device program: `out = prog(...)` marks
# `out` as a device pytree whose fields should be fetched with ONE
# device_get, not piecemeal conversions
PROGRAM_BUILDERS = {"tree_aggregate_fn", "tree_aggregate",
                    "tree_aggregate_with_state", "jit", "pjit"}


class HostSyncRule(Rule):
    rule_id = "JX001"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        for fn in mod.functions:
            if fn.jit_reachable:
                yield from self._check_traced(mod, fn)
            else:
                yield from self._check_piecemeal_pulls(mod, fn)

    # -- (a) syncs inside traced code ---------------------------------------
    def _check_traced(self, mod: ModuleInfo, fn) -> Iterator[Finding]:
        taint = TaintTracker(fn.node, seed_params=fn.params_traced)
        for node in iter_own_statements(fn.node):
            if not isinstance(node, ast.Call):
                continue
            name = call_name(node)
            if name in COERCIONS and node.args:
                if taint.expr_tainted(node.args[0]):
                    yield self.finding(
                        mod, node,
                        f"`{name}()` on a traced value inside jit-reachable "
                        f"code forces a host sync (or a TracerConversionError "
                        f"under jit); keep the value on device or move the "
                        f"conversion outside the traced region",
                        fn.qualname)
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr in ("item", "tolist")
                    and not node.args
                    and taint.expr_tainted(node.func.value)):
                yield self.finding(
                    mod, node,
                    f"`.{node.func.attr}()` on a traced value inside "
                    f"jit-reachable code is an implicit device->host "
                    f"transfer",
                    fn.qualname)
            elif name in HOST_ARRAY_CALLS and node.args:
                if taint.expr_tainted(node.args[0]):
                    yield self.finding(
                        mod, node,
                        f"`{name}()` on a traced value materializes a host "
                        f"copy inside jit-reachable code; use jnp (or hoist "
                        f"the conversion out of the traced region)",
                        fn.qualname)

    # -- (b) piecemeal pulls in host drivers --------------------------------
    def _check_piecemeal_pulls(self, mod: ModuleInfo, fn) -> Iterator[Finding]:
        # names bound from a compiled-program factory: prog = ds.tree_aggregate_fn(f)
        program_names: Set[str] = set()
        # names bound from calling such a program: out = prog(...)
        output_pulls: Dict[str, List[ast.AST]] = {}
        fetched: Set[str] = set()

        for node in iter_own_statements(fn.node):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                callee = call_name(node.value)
                names = [n for t in node.targets for n in assigned_names(t)]
                if callee and last_component(callee) in PROGRAM_BUILDERS:
                    program_names.update(names)
                elif callee and last_component(callee) == "device_get":
                    for n in names:
                        fetched.add(n)
                elif callee in program_names or (
                        callee and callee.split(".", 1)[0] in program_names):
                    for n in names:
                        output_pulls.setdefault(n, [])
                continue
            if not isinstance(node, ast.Call):
                continue
            callee = call_name(node)
            if callee and last_component(callee) == "device_get":
                for arg in node.args:
                    for sub in ast.walk(arg):
                        if isinstance(sub, ast.Name):
                            fetched.add(sub.id)
                continue
            target = None
            if callee in COERCIONS and node.args:
                target = node.args[0]
            elif callee in HOST_ARRAY_CALLS and node.args:
                target = node.args[0]
            elif (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "item" and not node.args):
                target = node.func.value
            if target is None:
                continue
            for sub in ast.walk(target):
                if isinstance(sub, ast.Name) and sub.id in output_pulls \
                        and sub.id not in fetched:
                    output_pulls[sub.id].append(node)
                    break

        for name, pulls in output_pulls.items():
            if len(pulls) >= 2:
                yield self.finding(
                    mod, pulls[1],
                    f"{len(pulls)} separate implicit device->host transfers "
                    f"from aggregate output `{name}`; fetch once with "
                    f"`jax.device_get({name})` and convert on the host",
                    fn.qualname)
