"""graftlint rule registry.

Adding a rule: subclass :class:`~cycloneml_tpu.analysis.rules.base.Rule`
(pattern rule) or :class:`~cycloneml_tpu.analysis.rules.base.DataflowRule`
(adds an interprocedural transfer function — see docs/graftlint.md,
"dataflow engine"), give it the next ``JXnnn`` id, and list it here.
Each rule ships with a paired should-flag / should-pass fixture under
``tests/fixtures/graftlint/`` pinning its precision.
"""

from cycloneml_tpu.analysis.rules.base import DataflowRule, Rule
from cycloneml_tpu.analysis.rules.jx001_host_sync import HostSyncRule
from cycloneml_tpu.analysis.rules.jx002_traced_control_flow import \
    TracedControlFlowRule
from cycloneml_tpu.analysis.rules.jx003_prng_reuse import PRNGReuseRule
from cycloneml_tpu.analysis.rules.jx004_fp64_drift import FP64DriftRule
from cycloneml_tpu.analysis.rules.jx005_collective_axes import \
    CollectiveAxisRule
from cycloneml_tpu.analysis.rules.jx006_jit_mutation import JitMutationRule
from cycloneml_tpu.analysis.rules.jx007_thread_dispatch import \
    ThreadDispatchRule
from cycloneml_tpu.analysis.rules.jx008_recompile import RecompileHazardRule
from cycloneml_tpu.analysis.rules.jx009_use_after_donate import \
    UseAfterDonateRule
from cycloneml_tpu.analysis.rules.jx010_collective_divergence import \
    CollectiveDivergenceRule
from cycloneml_tpu.analysis.rules.jx011_lockset_race import LocksetRaceRule
from cycloneml_tpu.analysis.rules.jx012_lock_order import LockOrderRule
from cycloneml_tpu.analysis.rules.jx013_obligation_leak import \
    ObligationLeakRule
from cycloneml_tpu.analysis.rules.jx014_blocking_under_lock import \
    BlockingUnderLockRule
from cycloneml_tpu.analysis.rules.jx015_sharding_spec import ShardingSpecRule
from cycloneml_tpu.analysis.rules.jx016_shape_padding import ShapePaddingRule
from cycloneml_tpu.analysis.rules.jx017_cross_mesh import CrossMeshReuseRule
from cycloneml_tpu.analysis.rules.jx018_host_materialize import \
    HostMaterializeRule
from cycloneml_tpu.analysis.rules.jx019_conf_keys import ConfKeyRule
from cycloneml_tpu.analysis.rules.jx020_fault_coverage import \
    FaultCoverageRule
from cycloneml_tpu.analysis.rules.jx021_event_drift import EventDriftRule
from cycloneml_tpu.analysis.rules.jx022_lifecycle import LifecycleRule
from cycloneml_tpu.analysis.rules.jx023_seeded_determinism import \
    SeededDeterminismRule

# JX020 precedes JX023 so it is the registered JXFAULT fixpoint client
# (the engine runs one client per analysis_id; JX023 reads the summaries)
ALL_RULES = (HostSyncRule, TracedControlFlowRule, PRNGReuseRule,
             FP64DriftRule, CollectiveAxisRule, JitMutationRule,
             ThreadDispatchRule, RecompileHazardRule, UseAfterDonateRule,
             CollectiveDivergenceRule, LocksetRaceRule, LockOrderRule,
             ObligationLeakRule, BlockingUnderLockRule, ShardingSpecRule,
             ShapePaddingRule, CrossMeshReuseRule, HostMaterializeRule,
             ConfKeyRule, FaultCoverageRule, EventDriftRule, LifecycleRule,
             SeededDeterminismRule)


def default_rules():
    return [cls() for cls in ALL_RULES]


def rules_by_id(ids):
    wanted = {i.strip().upper() for i in ids}
    return [cls() for cls in ALL_RULES if cls.rule_id in wanted]


__all__ = ["Rule", "DataflowRule", "ALL_RULES", "default_rules",
           "rules_by_id"]
