"""JX012 — lock-order inversion: cyclic acquisition = potential deadlock.

Deadlock needs four conditions; the only one a codebase can engineer away
statically is *circular wait*: if every thread acquires locks in one
global order, no cycle of "holds A, wants B" can close. The rule builds
that order's witness — a **lock acquisition graph** with an edge A → B
for every place B is acquired while A is held:

* lexically: ``with self._lock:`` containing ``with self._cv:``;
* interprocedurally: a call under ``with A:`` whose (transitively
  resolved) callee acquires B — the callee's *acquired-locks* summary is
  a bottom-up dataflow fact, so a lock taken three helpers deep still
  draws the edge at the outermost call site.

Locks are named by where they live, abstracted over instances
(``ModelLane._cv``, ``module.py::_round_lock``) — the rule checks the
class-level *discipline*, not a heap. Any cycle in the graph is reported
at every participating acquisition site; a same-lock self-edge on a
non-reentrant lock (plain ``threading.Lock``) is the degenerate cycle —
self-deadlock on re-entry. ``RLock`` and default-constructed
``Condition`` (RLock-backed) self-edges are exempt.

The clean idioms stay silent: a consistent global order draws an acyclic
graph; the snapshot-then-call pattern (copy shared state under the lock,
*release*, then call into another lock's owner) draws no edge at all —
that is exactly why it is the recommended fix.
"""

from __future__ import annotations

import ast
from collections import defaultdict
from typing import Dict, Iterator, List, Optional, Tuple

from cycloneml_tpu.analysis.astutil import FunctionInfo
from cycloneml_tpu.analysis.dataflow import EMPTY, TOP, join_sets
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.locks import model_for, pretty_lock
from cycloneml_tpu.analysis.rules.base import DataflowRule


class _Edge:
    __slots__ = ("src", "dst", "node", "fn", "mod_path", "via")

    def __init__(self, src: str, dst: str, node: ast.AST,
                 fn: FunctionInfo, via: Optional[str] = None):
        self.src, self.dst = src, dst
        self.node, self.fn = node, fn
        self.mod_path = fn.module_path
        self.via = via      # callee qualname when the edge is a call edge


class LockOrderRule(DataflowRule):
    rule_id = "JX012"

    def __init__(self):
        self._edges: Optional[List[_Edge]] = None
        self._cyclic: Dict[Tuple[str, str], str] = {}

    # -- summary: locks this function (transitively) acquires ----------------
    def initial(self, fn: FunctionInfo, graph, ctx):
        return model_for(ctx).info(fn).acquired

    def transfer(self, fn: FunctionInfo, facts, graph, ctx):
        out = model_for(ctx).info(fn).acquired
        for site in graph.sites(fn):
            for target in site.targets:
                got = facts.get(target, EMPTY)
                if got is TOP:
                    continue    # widened: degrade to no-edge, not all-edge
                out = join_sets(out, got)
                if out is TOP:
                    return TOP
        return out

    # -- the check: build the graph once, report cyclic edges per module -----
    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        if self._edges is None:
            self._build(ctx)
        for edge in self._edges:
            if edge.mod_path != mod.path:
                continue
            cycle = self._cyclic.get((edge.src, edge.dst))
            if cycle is None:
                continue
            how = (f"via `{edge.via}`, which acquires it transitively"
                   if edge.via else "nested acquisition")
            if edge.src == edge.dst:
                yield self.finding(
                    mod, edge.node,
                    f"`{_pretty(edge.src)}` is re-acquired while already "
                    f"held ({how}) — it is not reentrant "
                    f"(`threading.Lock`): the thread deadlocks on itself; "
                    f"use an RLock only if the recursion is intended, "
                    f"otherwise restructure so the inner path does not "
                    f"re-take the lock",
                    edge.fn.qualname)
            else:
                yield self.finding(
                    mod, edge.node,
                    f"lock-order inversion: `{_pretty(edge.dst)}` is "
                    f"acquired while holding `{_pretty(edge.src)}` "
                    f"({how}), but the reverse order also exists — "
                    f"cycle {cycle}; two threads taking the two paths "
                    f"concurrently deadlock. Pick one global order, or "
                    f"snapshot under one lock, release, then call",
                    edge.fn.qualname)

    def _build(self, ctx: AnalysisContext) -> None:
        model = model_for(ctx)
        graph = ctx.callgraph
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        edges: List[_Edge] = []
        seen = set()   # (src, dst, fn, line) dedup

        def add(src: str, dst: str, node: ast.AST, fn: FunctionInfo,
                via: Optional[str] = None) -> None:
            if src == dst and model.is_reentrant(src):
                return
            key = (src, dst, id(fn), getattr(node, "lineno", 0))
            if key in seen:
                return
            seen.add(key)
            edges.append(_Edge(src, dst, node, fn, via))

        if graph is not None:
            for fn in graph.all_functions:
                info = model.info(fn)
                for lw in info.withs:
                    for held in lw.held:
                        add(held, lw.lock, lw.node, fn)
                sites = graph.sites_map(fn)
                for call_id, held in info.call_locks.items():
                    if not held:
                        continue
                    site = sites.get(call_id)
                    if site is None:
                        continue
                    for target in site.targets:
                        got = facts.get(target, EMPTY)
                        if got is TOP or not got:
                            continue
                        for dst in got:
                            for src in held:
                                add(src, dst, site.node, fn,
                                    via=target.qualname)
        self._edges = edges
        self._cyclic = _cyclic_edges(edges)


def _cyclic_edges(edges: List[_Edge]) -> Dict[Tuple[str, str], str]:
    """(src, dst) pairs that sit inside a cycle of the acquisition graph,
    mapped to a printable representative cycle. Tarjan SCCs: an edge is
    cyclic iff both endpoints share an SCC (self-loops trivially so)."""
    adj: Dict[str, set] = defaultdict(set)
    for e in edges:
        adj[e.src].add(e.dst)
    index: Dict[str, int] = {}
    low: Dict[str, int] = {}
    comp: Dict[str, int] = {}
    stack: List[str] = []
    on_stack: set = set()
    counter = [0]
    comp_id = [0]

    def strongconnect(root: str) -> None:
        work = [(root, iter(sorted(adj.get(root, ()))))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack.add(root)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = low[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(adj.get(w, ())))))
                    advanced = True
                    break
                if w in on_stack:
                    low[v] = min(low[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                cid = comp_id[0]
                comp_id[0] += 1
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp[w] = cid
                    if w == v:
                        break

    nodes = set(adj) | {e.dst for e in edges}
    for n in sorted(nodes):
        if n not in index:
            strongconnect(n)

    members: Dict[int, List[str]] = defaultdict(list)
    for n, cid in comp.items():
        members[cid].append(n)

    out: Dict[Tuple[str, str], str] = {}
    for e in edges:
        if e.src == e.dst:
            out[(e.src, e.dst)] = f"{_pretty(e.src)} → {_pretty(e.src)}"
            continue
        if comp.get(e.src) != comp.get(e.dst):
            continue
        if len(members[comp[e.src]]) < 2:
            continue
        cyc = _find_cycle(adj, e.src, e.dst)
        out[(e.src, e.dst)] = cyc
    return out


def _find_cycle(adj: Dict[str, set], src: str, dst: str) -> str:
    """A printable representative cycle through edge src→dst: BFS a path
    dst ⇝ src, then close it."""
    from collections import deque
    prev: Dict[str, Optional[str]] = {dst: None}
    q = deque([dst])
    while q:
        v = q.popleft()
        if v == src:
            break
        for w in sorted(adj.get(v, ())):
            if w not in prev:
                prev[w] = v
                q.append(w)
    if src not in prev:
        return f"{_pretty(src)} → {_pretty(dst)} → … → {_pretty(src)}"
    path = [src]
    while prev[path[-1]] is not None:
        path.append(prev[path[-1]])
    path.reverse()                      # dst ... src
    names = [_pretty(n) for n in [src] + path]
    return " → ".join(names)


_pretty = pretty_lock
