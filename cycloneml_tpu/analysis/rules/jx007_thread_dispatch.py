"""JX007 — thread-pool / thread dispatch of jit/SPMD entry points.

Every jitted step is a gang-scheduled SPMD program over the WHOLE mesh.
Dispatching such programs concurrently from a ``ThreadPoolExecutor`` (or
a raw ``threading.Thread``) interleaves the per-device executions of
different programs and deadlocks XLA's collective rendezvous — the
``OneVsRest(parallelism=4)`` hang PR 2 root-caused and
``mesh.safe_fit_parallelism`` guards at runtime; this rule mechanizes the
pattern statically. A submit/map/Thread-target callable is flagged when
it (transitively, within the module) reaches an SPMD dispatch surface:
an estimator/optimizer ``.fit`` / ``.fit_stacked`` / ``.minimize`` /
``.optimize``, a ``tree_aggregate`` family call, or a program built by
``jax.jit``/``pjit``/``tree_aggregate_fn`` in an enclosing scope.

The sanctioned parallel path is the STACKED fit engine (vmapped model
axis — one program, one gang schedule; docs/multi-model.md); host-tier
pools over plain Python work are untouched, as are callables the
analyzer cannot resolve (e.g. function-valued parameters).
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from cycloneml_tpu.analysis.astutil import (FunctionInfo, assigned_names,
                                            call_name, iter_own_statements,
                                            last_component)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule

POOL_TYPES = {"ThreadPoolExecutor", "ProcessPoolExecutor"}
THREAD_TYPES = {"Thread", "Timer"}
DISPATCH_METHODS = {"fit", "fit_stacked", "minimize", "optimize",
                    "optimize_stacked", "device_line_search"}
DISPATCH_CALLS = {"tree_aggregate", "tree_aggregate_fn",
                  "tree_aggregate_with_state", "all_gather_hosts",
                  "psum_over_mesh", "all_to_all_repartition"}
# names bound from these hold a compiled SPMD program: calling one IS a
# dispatch (same set JX001 tracks for batched-readback analysis)
PROGRAM_BUILDERS = {"tree_aggregate_fn", "tree_aggregate",
                    "tree_aggregate_with_state", "jit", "pjit"}
_MAX_DEPTH = 3


class ThreadDispatchRule(Rule):
    rule_id = "JX007"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        toplevel: Dict[str, FunctionInfo] = {}
        methods: Dict[Tuple[str, str], FunctionInfo] = {}
        children: Dict[str, List[FunctionInfo]] = {}
        for fn in mod.functions:
            simple = fn.qualname.rsplit(".", 1)[-1]
            if fn.parent is None and fn.class_name is None:
                toplevel[simple] = fn
            if fn.class_name is not None and fn.parent is None:
                methods[(fn.class_name, simple)] = fn
            if fn.parent is not None:
                children.setdefault(fn.parent.qualname, []).append(fn)
        tables = (toplevel, methods, children)
        for fn in mod.functions:
            yield from self._check_function(mod, fn, tables)

    # -- per-function scan ----------------------------------------------------
    def _check_function(self, mod: ModuleInfo, fn: FunctionInfo,
                        tables) -> Iterator[Finding]:
        pools: Set[str] = set()
        programs = _program_names(fn.node)
        for node in iter_own_statements(fn.node):
            # pool bindings: `pool = cf.ThreadPoolExecutor(...)` and
            # `with cf.ThreadPoolExecutor(...) as pool:`
            if isinstance(node, ast.Assign) \
                    and isinstance(node.value, ast.Call) \
                    and last_component(call_name(node.value)) in POOL_TYPES:
                for t in node.targets:
                    pools.update(assigned_names(t))
            elif isinstance(node, ast.withitem) \
                    and isinstance(node.context_expr, ast.Call) \
                    and last_component(call_name(node.context_expr)) \
                    in POOL_TYPES \
                    and node.optional_vars is not None:
                pools.update(assigned_names(node.optional_vars))
            if not isinstance(node, ast.Call):
                continue
            target = self._submitted_callable(node, pools)
            if target is None:
                continue
            kind, expr = target
            if self._dispatches_spmd(expr, mod, fn, tables, programs,
                                     set(), _MAX_DEPTH):
                yield self.finding(
                    mod, node,
                    f"{kind} dispatches a jit/SPMD entry point from a "
                    f"worker thread; concurrent SPMD programs deadlock the "
                    f"shared mesh's collective rendezvous — use the "
                    f"stacked (vmapped model-axis) fit engine or run "
                    f"serially (mesh.safe_fit_parallelism)",
                    fn.qualname)

    @staticmethod
    def _submitted_callable(node: ast.Call, pools: Set[str]):
        """(description, callable expr) for pool.map/submit and
        Thread(target=...) calls, else None."""
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr in ("map", "submit") \
                and isinstance(node.func.value, ast.Name) \
                and node.func.value.id in pools \
                and node.args:
            return (f"`.{node.func.attr}()` on a thread pool", node.args[0])
        if last_component(call_name(node)) in THREAD_TYPES:
            for kw in node.keywords:
                if kw.arg == "target":
                    return ("`threading.Thread(target=...)`", kw.value)
        return None

    # -- does the callable reach an SPMD dispatch surface? --------------------
    def _dispatches_spmd(self, expr: ast.AST, mod: ModuleInfo,
                         scope: FunctionInfo, tables, programs: Set[str],
                         visited: Set[int], depth: int) -> bool:
        if depth <= 0:
            return False
        info = self._resolve(expr, scope, tables)
        if info is not None:
            if id(info) in visited:
                return False
            visited.add(id(info))
            body: ast.AST = info.node
        elif isinstance(expr, ast.Lambda):
            body = expr
        else:
            return False  # unresolvable (parameter, import, builtin)
        # programs bound in the callable itself count too
        local_programs = programs | _program_names(body)
        for sub in (iter_own_statements(body)
                    if not isinstance(body, ast.Lambda)
                    else ast.walk(body.body)):
            if not isinstance(sub, ast.Call):
                continue
            name = call_name(sub)
            base = last_component(name)
            if base in DISPATCH_METHODS or base in DISPATCH_CALLS:
                return True
            if isinstance(sub.func, ast.Name) \
                    and sub.func.id in local_programs:
                return True
            # transitive: resolve local/self calls one level down
            owner = info if info is not None else scope
            if self._dispatches_spmd(sub.func, mod, owner, tables,
                                     local_programs, visited, depth - 1):
                return True
        return False

    @staticmethod
    def _resolve(expr: ast.AST, scope: FunctionInfo,
                 tables) -> Optional[FunctionInfo]:
        toplevel, methods, children = tables
        if isinstance(expr, ast.Name):
            walk = scope
            while walk is not None:
                for child in children.get(walk.qualname, []):
                    if child.qualname.rsplit(".", 1)[-1] == expr.id:
                        return child
                walk = walk.parent
            return toplevel.get(expr.id)
        if isinstance(expr, ast.Attribute) \
                and isinstance(expr.value, ast.Name) \
                and expr.value.id in ("self", "cls") \
                and scope is not None and scope.class_name:
            return methods.get((scope.class_name, expr.attr))
        return None


def _program_names(fn_node: ast.AST) -> Set[str]:
    """Names bound from a compiled-program factory in this function's own
    body (``prog = ds.tree_aggregate_fn(f)`` / ``go = jax.jit(f)``)."""
    out: Set[str] = set()
    stmts = (iter_own_statements(fn_node)
             if not isinstance(fn_node, ast.Lambda) else ())
    for node in stmts:
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Call) \
                and last_component(call_name(node.value)) in PROGRAM_BUILDERS:
            for t in node.targets:
                out.update(assigned_names(t))
    return out
