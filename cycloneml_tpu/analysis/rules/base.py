"""Rule protocols: pattern rules and interprocedural dataflow rules.

Two kinds of rule, one registry:

* :class:`Rule` — the PR-1 contract, unchanged: per-module ``check()``
  with cross-module context via ``ctx``. Every existing rule keeps
  working without modification.
* :class:`DataflowRule` — adds a transfer function. Before any
  ``check()`` runs, the engine (:mod:`..dataflow`) iterates every
  dataflow rule's ``transfer`` over the call graph to a fixpoint; the
  converged per-function summaries are then readable in ``check()`` via
  :meth:`DataflowRule.summary`. Migration for rule authors: keep your
  ``check()`` exactly as it was, move any would-be cross-function logic
  into ``initial``/``transfer``, and consult the summary where you
  previously only had the local AST (docs/graftlint.md, "dataflow
  engine").
"""

from __future__ import annotations

import ast
from typing import Iterator

from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo


class Rule:
    """A graftlint rule. Subclasses set ``rule_id`` and implement
    :meth:`check` yielding findings for one module (cross-module context
    arrives via ``ctx``)."""

    rule_id: str = "JX000"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: ModuleInfo, node: ast.AST, message: str,
                function: str = "") -> Finding:
        stmt = _enclosing_statement(mod, node)
        return Finding(rule=self.rule_id, path=mod.path,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       end_line=_statement_extent(stmt),
                       start_line=getattr(stmt, "lineno", 0) or 0,
                       message=message, function=function)


def _enclosing_statement(mod: ModuleInfo, node: ast.AST) -> ast.AST:
    """The innermost STATEMENT containing ``node``. A finding may anchor
    on an inner expression (the ``float(...)`` operand of a larger
    assignment); the suppression contract covers any physical line of
    the enclosing statement, not just the flagged node's own span.
    Findings are rare, so the per-finding tree walk is cheap."""
    line = getattr(node, "lineno", None)
    end = getattr(node, "end_lineno", None) or line
    tree = getattr(mod, "tree", None)
    if line is None or tree is None:
        return node
    best, best_key = node, None
    for stmt in ast.walk(tree):
        if not isinstance(stmt, ast.stmt):
            continue
        s0 = getattr(stmt, "lineno", None)
        s1 = getattr(stmt, "end_lineno", None)
        if s0 is None or s1 is None or s0 > line or s1 < end:
            continue
        # innermost: smallest line span, then deepest indentation
        key = (s1 - s0, -getattr(stmt, "col_offset", 0))
        if best_key is None or key < best_key:
            best, best_key = stmt, key
    return best


def _statement_extent(node: ast.AST) -> int:
    """Last physical line an inline suppression for this finding may sit
    on. For compound statements (if/while/for) only the HEADER counts —
    a ``disable`` buried in the body must not silence a finding on the
    branch itself."""
    if isinstance(node, (ast.If, ast.While)):
        return getattr(node.test, "end_lineno", 0) or 0
    if isinstance(node, (ast.For, ast.AsyncFor)):
        return getattr(node.iter, "end_lineno", 0) or 0
    if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                         ast.ClassDef)):
        return getattr(node, "lineno", 0) or 0
    return getattr(node, "end_lineno", 0) or 0


class DataflowRule(Rule):
    """A rule with an interprocedural summary.

    The dataflow engine computes one abstract fact per function by
    iterating :meth:`transfer` to a fixpoint over the call graph
    (callee summaries feed caller summaries, callers re-queued on
    change). Facts must come from a small join-semilattice —
    use the primitives in :mod:`..dataflow` (bools, ``frozenset | TOP``)
    so the fixpoint terminates; ``top()`` is the hard-widening backstop.
    """

    #: summaries are keyed by this id; defaults to the rule id
    @property
    def analysis_id(self) -> str:
        return self.rule_id

    def initial(self, fn, graph, ctx):
        """Seed facts from ``fn``'s own body (no callee knowledge)."""
        raise NotImplementedError

    def transfer(self, fn, facts, graph, ctx):
        """Recompute ``fn``'s summary from its body + ``facts`` of its
        callees. MUST be monotone w.r.t. the fact lattice."""
        raise NotImplementedError

    def top(self, fn, graph, ctx):
        """The "anything possible" summary, used to hard-widen when the
        per-function visit budget is exhausted."""
        from cycloneml_tpu.analysis.dataflow import TOP
        return TOP

    def summary(self, ctx: AnalysisContext, fn, default=None):
        if ctx.dataflow is None:
            return default
        return ctx.dataflow.summary(self.analysis_id, fn, default)
