"""Rule protocol: one class per rule id, registered in rules/__init__."""

from __future__ import annotations

import ast
from typing import Iterator

from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo


class Rule:
    """A graftlint rule. Subclasses set ``rule_id`` and implement
    :meth:`check` yielding findings for one module (cross-module context
    arrives via ``ctx``)."""

    rule_id: str = "JX000"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, mod: ModuleInfo, node: ast.AST, message: str,
                function: str = "") -> Finding:
        return Finding(rule=self.rule_id, path=mod.path,
                       line=getattr(node, "lineno", 0),
                       col=getattr(node, "col_offset", 0),
                       message=message, function=function)
