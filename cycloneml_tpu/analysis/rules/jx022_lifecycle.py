"""JX022 — lifecycle typestate: stop/close discipline on runtime objects.

The distributed runtime's long-lived objects (ModelLane, ShardStream,
the heartbeat pair, SpanShipper, the context itself) share one protocol:
construct -> use -> stop/close, where stop latches a flag and guarded
methods reject dispatch afterwards. PR 8/11/13 each hand-fixed a
violation of it. This rule infers the per-class state machine from the
code (:func:`~..registries.lifecycle_registry`: a stop/close/shutdown
method that latches ``self._stop = True`` / ``self._stop.set()``;
guarded methods test the flag and raise) and convicts three deviations:

* **dispatch-after-stop** — a guarded method called on an instance a
  path has already stopped (`lane.stop(); lane.submit(x)` raises by
  construction). Interprocedural: passing the instance to a callee whose
  bottom-up summary says it tears that parameter down counts as the
  stop.
* **teardown leak** — a locally constructed lifecycle instance that can
  reach a function exit neither stopped nor escaped (returned, stored,
  handed off); the thread/queue it owns outlives the function. The walk
  is the JX013 obligation machinery on the shared
  :class:`~..walker.BlockWalker` — branch may-merges, loop/try/finally
  semantics, escape-before-exit — with stop/close as the discharge.
* **unlocked double-transition** — a method that tests a bool stop flag
  and writes it with either access outside a lock-ish ``with``: the
  check-then-act pair races a concurrent stop (two threads both observe
  "not stopped" and both run the teardown body). Event flags are exempt
  (``Event.is_set``/``set`` are atomic); JX011's lockset facts see the
  field accesses but not the transition pairing.

The summary (``frozenset`` of parameter positions torn down) propagates
bottom-up exactly like JX013's discharge summary, so
``_teardown(lane)``-style helpers both discharge the obligation and mark
the instance stopped in their callers.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from cycloneml_tpu.analysis.astutil import (FunctionInfo, assigned_names,
                                            call_name, dotted_name,
                                            last_component)
from cycloneml_tpu.analysis.dataflow import (EMPTY, TOP, join_sets,
                                             param_index, set_contains)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.registries import (STOP_METHOD_NAMES,
                                               LifecycleClass, _self_attr,
                                               lifecycle_registry)
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.walker import BlockWalker

#: with-context names that make a flag access lock-protected
_LOCKISH = ("lock", "mutex", "cv", "cond")


def _lockish_with(item_expr: ast.AST) -> bool:
    name = dotted_name(item_expr)
    if name is None and isinstance(item_expr, ast.Call):
        name = call_name(item_expr)
    last = (last_component(name) or "").lstrip("_").lower()
    return any(w in last for w in _LOCKISH)


class LifecycleRule(DataflowRule):
    rule_id = "JX022"

    # -- summary: which of MY param positions do I tear down? ----------------
    def initial(self, fn: FunctionInfo, graph, ctx):
        params = param_index(fn)
        if not params:
            return EMPTY
        torn = set()
        for call in graph.index(fn).calls:
            if isinstance(call.func, ast.Attribute) \
                    and call.func.attr in STOP_METHOD_NAMES \
                    and isinstance(call.func.value, ast.Name) \
                    and call.func.value.id in params:
                torn.add(params[call.func.value.id])
        return frozenset(torn)

    def transfer(self, fn: FunctionInfo, facts, graph, ctx):
        out = facts.get(fn, EMPTY)
        if out is TOP:
            return TOP
        params = param_index(fn)
        if not params:
            return out
        add = set()
        for site in graph.sites(fn):
            for target in site.targets:
                summary = facts.get(target)
                if not summary or summary is TOP:
                    continue
                for pi, expr in site.param_map(target):
                    if set_contains(summary, pi) \
                            and isinstance(expr, ast.Name) \
                            and expr.id in params:
                        add.add(params[expr.id])
        return join_sets(out, frozenset(add))

    # -- the check -----------------------------------------------------------
    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        registry = lifecycle_registry(ctx)
        if not registry:
            return
        yield from self._flag_races(mod, registry)
        graph = ctx.callgraph
        if graph is None:
            return
        if not any(name in ln for ln in mod.source_lines
                   for name in registry):
            return
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        for fn in mod.functions:
            if fn.jit_reachable:
                continue
            w = _LifecycleWalker(self, mod, fn, graph.sites_map(fn), facts,
                                 registry)
            w.walk(getattr(fn.node, "body", []))
            yield from w.findings

    # -- (c): unlocked flag check-then-act -----------------------------------
    def _flag_races(self, mod: ModuleInfo,
                    registry: Dict[str, LifecycleClass]
                    ) -> Iterator[Finding]:
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            lc = registry.get(node.name)
            if lc is None or lc.module_path != mod.path:
                continue
            bool_flags = {f for f, kind in lc.flags.items()
                          if kind == "bool"}
            if not bool_flags:
                continue
            for m in node.body:
                if not isinstance(m, (ast.FunctionDef,
                                      ast.AsyncFunctionDef)):
                    continue
                reads, writes = _flag_accesses(m, bool_flags)
                for flag in bool_flags:
                    fread = [r for r in reads if r[0] == flag]
                    fwrite = [w for w in writes if w[0] == flag]
                    if not fread or not fwrite:
                        continue
                    unlocked = [n for _, n, locked in fwrite + fread
                                if not locked]
                    if not unlocked:
                        continue
                    qual = f"{node.name}.{m.name}"
                    yield self.finding(
                        mod, unlocked[0],
                        f"`{qual}` tests `self.{flag}` and writes it, "
                        f"with this access outside any lock — the "
                        f"check-then-act pair races a concurrent "
                        f"{'/'.join(sorted(lc.stop_methods))}(): two "
                        f"threads can both observe 'not stopped' and "
                        f"both run the transition body; hold one lock "
                        f"across the test AND the write",
                        qual)


def _flag_accesses(method: ast.AST, flags: Set[str]
                   ) -> Tuple[List[tuple], List[tuple]]:
    """``(reads, writes)`` of bool stop flags in one method body, each a
    ``(flag, node, locked)`` triple; ``locked`` = lexically inside a
    lock-ish ``with``."""
    reads: List[tuple] = []
    writes: List[tuple] = []

    def scan(stmts, locked: bool) -> None:
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            if isinstance(stmt, (ast.With, ast.AsyncWith)):
                now = locked or any(_lockish_with(i.context_expr)
                                    for i in stmt.items)
                scan(stmt.body, now)
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                for sub in ast.walk(stmt.test):
                    attr = _self_attr(sub)
                    if attr in flags:
                        reads.append((attr, stmt, locked))
            if isinstance(stmt, ast.Assign) \
                    and isinstance(stmt.value, ast.Constant) \
                    and isinstance(stmt.value.value, bool):
                for t in stmt.targets:
                    attr = _self_attr(t)
                    if attr in flags:
                        writes.append((attr, stmt, locked))
            for name in ("body", "orelse", "finalbody"):
                scan(getattr(stmt, name, []) or [], locked)
            for h in getattr(stmt, "handlers", []) or []:
                scan(h.body, locked)

    scan(getattr(method, "body", []), False)
    return reads, writes


class _LifecycleWalker(BlockWalker):
    """(a) dispatch-after-stop and (b) teardown leaks over one body.

    ``state`` maps a local name to the constructor Call that created its
    live lifecycle instance (the pending teardown obligation);
    ``stopped`` is the sticky may-analysis record of names a walked path
    has torn down, with the stop site and class."""

    def __init__(self, rule: LifecycleRule, mod: ModuleInfo,
                 fn: FunctionInfo, sites, facts,
                 registry: Dict[str, LifecycleClass]):
        super().__init__()
        self.rule, self.mod, self.fn = rule, mod, fn
        self.sites, self.facts, self.registry = sites, facts, registry
        self.findings: List[Finding] = []
        self._reported: Set[int] = set()
        #: name -> (stop node, class name, how) — sticky across merges
        self.stopped: Dict[str, tuple] = {}

    def _class_of(self, name: str) -> Optional[str]:
        src = self.state.get(name)
        if src is None:
            return None
        return last_component(call_name(src) or "")

    def bind(self, target: ast.AST) -> None:
        for n in assigned_names(target):
            self.state.pop(n, None)
            self.stopped.pop(n, None)

    # -- sources / escapes ---------------------------------------------------
    def run_stmt(self, stmt: ast.AST):
        if isinstance(stmt, ast.Assign):
            value = stmt.value
            cls = self._constructed(value)
            self.visit_expr(value)
            if cls is not None:
                for t in stmt.targets:
                    self.bind(t)
                names = [n for t in stmt.targets
                         for n in assigned_names(t)]
                if len(names) == 1 and all(
                        isinstance(t, ast.Name) for t in stmt.targets):
                    self.state[names[0]] = value
                return None
            # escaping/aliasing assignment: someone else holds it now
            for name in _bare_names(value):
                self.state.pop(name, None)
            for t in stmt.targets:
                self.bind(t)
            return None
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self.visit_expr(stmt.value)
                for name in _bare_names(stmt.value):
                    self.state.pop(name, None)   # escaped to the caller
            if not self._return_protected():
                self.on_exit(stmt, "return")
            return "exit"
        return super().run_stmt(stmt)

    def _constructed(self, value: ast.AST) -> Optional[str]:
        if isinstance(value, ast.Call):
            cls = last_component(call_name(value) or "")
            if cls in self.registry:
                return cls
        return None

    # -- expression scan -----------------------------------------------------
    def visit_expr(self, expr: ast.AST) -> None:
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(expr, ast.Call):
            for child in ast.iter_child_nodes(expr):
                self.visit_expr(child)
            self._visit_call(expr)
            return
        if isinstance(expr, (ast.Yield, ast.YieldFrom)):
            value = getattr(expr, "value", None)
            if value is not None:
                self.visit_expr(value)
                for name in _bare_names(value):
                    self.state.pop(name, None)
            return
        for child in ast.iter_child_nodes(expr):
            self.visit_expr(child)

    def _visit_call(self, call: ast.Call) -> None:
        state = self.state
        # method call on a tracked instance: x.stop() / x.submit(...)
        if isinstance(call.func, ast.Attribute) \
                and isinstance(call.func.value, ast.Name):
            recv = call.func.value.id
            meth = call.func.attr
            if recv in state:
                cls = self._class_of(recv)
                if meth in STOP_METHOD_NAMES:
                    state.pop(recv, None)
                    self.stopped[recv] = (call, cls, f"{cls}.{meth}")
                    return
            elif recv in self.stopped:
                _, cls, how = self.stopped[recv]
                lc = self.registry.get(cls or "")
                if lc is not None and meth in lc.guarded \
                        and id(call) not in self._reported:
                    self._reported.add(id(call))
                    self.findings.append(self.rule.finding(
                        self.mod, call,
                        f"`{recv}.{meth}()` dispatches on a {cls} a "
                        f"path has already stopped ({how} above) — "
                        f"`{meth}` tests the stop flag and raises; "
                        f"reorder the teardown or re-check liveness "
                        f"before dispatching",
                        self.fn.qualname))
                return
        args = list(call.args) + [kw.value for kw in call.keywords]
        bare = [n for a in args for n in _bare_names(a) if n in state]
        if not bare:
            return
        site = self.sites.get(id(call))
        if site is not None and site.targets:
            # resolved: a callee whose summary tears the param down both
            # discharges the obligation AND marks the instance stopped
            for target in site.targets:
                summary = self.facts.get(target, EMPTY)
                for pi, expr in site.param_map(target):
                    if isinstance(expr, ast.Name) and expr.id in state \
                            and set_contains(summary, pi):
                        cls = self._class_of(expr.id)
                        state.pop(expr.id, None)
                        self.stopped[expr.id] = (
                            call, cls, f"{target.qualname}()")
            # container-wrapped mentions are an opaque hand-off
            for a in args:
                if not isinstance(a, ast.Name):
                    for n in _bare_names(a):
                        state.pop(n, None)
            return
        # unresolvable call: assume it takes ownership (silence > noise)
        for n in bare:
            state.pop(n, None)

    # -- exits ---------------------------------------------------------------
    def on_exit(self, stmt: Optional[ast.AST], kind: str) -> None:
        where = {"return": "this `return`",
                 "raise": "this `raise` (the error path)",
                 "end": "the end of the function"}[kind]
        line = getattr(stmt, "lineno", None)
        at = f" at line {line}" if line is not None else ""
        for name, src in list(self.state.items()):
            if id(src) in self._reported:
                continue
            self._reported.add(id(src))
            cls = last_component(call_name(src) or "")
            stops = "/".join(sorted(self.registry[cls].stop_methods)) \
                if cls in self.registry else "stop"
            self.findings.append(self.rule.finding(
                self.mod, src,
                f"`{name}` ({cls}) is constructed here but can reach "
                f"{where}{at} without `{stops}()` — the thread/queue it "
                f"owns outlives the function (teardown leak); stop it "
                f"on every path, use `with`, or hand it off",
                self.fn.qualname))


def _bare_names(expr: ast.AST):
    """Names in ``expr`` outside pure attribute-receiver position (the
    JX013 escape notion: `x` and `[x]` yield, `x.field` does not)."""
    if isinstance(expr, ast.Name):
        yield expr.id
        return
    if isinstance(expr, ast.Attribute):
        return
    for child in ast.iter_child_nodes(expr):
        yield from _bare_names(child)
