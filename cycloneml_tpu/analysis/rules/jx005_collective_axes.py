"""JX005 — collective axis names must exist on the mesh.

``jax.lax.psum(x, "dta")`` raises a NameError-like failure only when the
program is actually traced inside a ``shard_map``/``pmap`` with that axis
— i.e. at runtime, on the device path, possibly only on the multihost
config that CI doesn't run. The mesh axes are declared exactly once
(``cycloneml_tpu/mesh.py``: ``DATA_AXIS``/``REPLICA_AXIS``/
``MODEL_AXIS``), so every string-literal axis name handed to a collective
is checked against them at lint time.

Variables are skipped unless they can be resolved: a ``Name``/
``Attribute`` whose final component is one of the declared
``*_AXIS`` constants passes; anything else dynamic is ignored (the rule
is for typos, not dataflow).
"""

from __future__ import annotations

import ast
from typing import Iterator, List, Optional

from cycloneml_tpu.analysis.astutil import call_name, dotted_name, \
    iter_own_statements, last_component
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule

# collective -> index of the positional axis-name argument
COLLECTIVES = {"psum": 1, "pmean": 1, "pmax": 1, "pmin": 1,
               "all_gather": 1, "ppermute": 1, "pshuffle": 1,
               "psum_scatter": 1, "all_to_all": 1, "axis_index": 0,
               "axis_size": 0, "pbroadcast": 1}
# only axis_name NAMES a mesh axis; `axis=` on all_gather/all_to_all/
# psum_scatter is the integer ARRAY axis and must not shadow the
# positional name slot
AXIS_KWARGS = ("axis_name",)


class CollectiveAxisRule(Rule):
    rule_id = "JX005"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        valid = set(ctx.valid_axes)
        const_names = set(ctx.axis_constant_names)
        for fn in mod.functions:
            for node in iter_own_statements(fn.node):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                if not name or not name.startswith(("jax.lax.", "lax.")):
                    continue
                op = last_component(name)
                if op not in COLLECTIVES:
                    continue
                axis_arg = self._axis_argument(node, COLLECTIVES[op])
                if axis_arg is None:
                    continue
                for bad in self._invalid_axes(axis_arg, valid, const_names):
                    yield self.finding(
                        mod, node,
                        f"`{op}` over unknown mesh axis {bad!r}; declared "
                        f"axes are {sorted(valid)} (mesh.py) — a typo here "
                        f"only fails at trace time on the device path",
                        fn.qualname)

    @staticmethod
    def _axis_argument(call: ast.Call, pos: int) -> Optional[ast.AST]:
        for kw in call.keywords:
            if kw.arg in AXIS_KWARGS:
                return kw.value
        if len(call.args) > pos:
            return call.args[pos]
        return None

    @staticmethod
    def _invalid_axes(node: ast.AST, valid, const_names) -> List[str]:
        """Invalid string-literal axis names in ``node`` (tuple/list of
        axes checked element-wise; unresolvable dynamics skipped)."""
        items = node.elts if isinstance(node, (ast.Tuple, ast.List)) \
            else [node]
        bad: List[str] = []
        for item in items:
            if isinstance(item, ast.Constant) and isinstance(item.value, str):
                if item.value not in valid:
                    bad.append(item.value)
                continue
            name = dotted_name(item)
            if name is not None:
                final = last_component(name)
                if final.endswith("_AXIS") and final not in const_names:
                    bad.append(final)
        return bad
