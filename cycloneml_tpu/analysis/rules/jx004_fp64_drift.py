"""JX004 — dtype drift across the data/accumulator tier boundary.

Two hazards, one boundary (docs/mixed-precision.md):

**fp64 drift.** Without ``jax.config.update("jax_enable_x64", True)``,
JAX silently downcasts every float64 request to float32 — so device code
that asks for ``jnp.float64`` / ``dtype="float64"`` is either a silent
downcast (TPU default) or, where x64 IS enabled, a 2x memory + severe
MXU perf hit smuggled into a hot path. Either way an explicit
module-level guard (any mention of ``jax_enable_x64``) is required
context for fp64 in jit-reachable code; absent that, it's flagged.

**Narrow accumulation.** The other direction of the same boundary: bf16
and fp8 (``cyclone.data.dtype``) are legal STORAGE — design matrices
live there — but the tier ends at the kernel: every cross-device
reduction must carry the fp32 accumulator (``cyclone.compute.dtype``).
A ``psum`` whose operand is narrow accumulates at storage width — 8
mantissa bits (bf16) or 3 (``float8_e4m3fn``) / 2 (``float8_e5m2``)
across the whole mesh — and is flagged regardless of any x64 guard (the
guard legitimizes fp64, not narrow reductions).

Narrowness is a DATAFLOW fact, not a callsite pattern: the PR-6 audit
had to hand-check five estimators precisely because the original rule
only saw casts written literally at the psum. The rule now carries a
``returns_narrow`` summary per function (a return value that is an
explicit bf16/f16 cast, transitively through call chains) and a
source-order scan of local names, so both forms are caught::

    y = x.astype(jnp.bfloat16)
    jax.lax.psum(y, "data")              # flagged (local name)

    jax.lax.psum(_to_storage(x), "data") # flagged (helper returns narrow)

``np.float64`` on the HOST side (optimizer state, readbacks) is idiomatic
and untouched — only jit-reachable functions are scanned.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, Optional, Set

from cycloneml_tpu.analysis.astutil import (assigned_names, call_name,
                                            dotted_name,
                                            iter_own_statements)
from cycloneml_tpu.analysis.dataflow import (assign_targets,
                                             own_nodes_in_order)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule

F64_DOTTED = {"jnp.float64", "jax.numpy.float64", "np.float64",
              "numpy.float64", "jnp.complex128", "jax.numpy.complex128"}
F64_STRINGS = {"float64", "f64", "complex128"}

NARROW_DOTTED = {"jnp.bfloat16", "jax.numpy.bfloat16", "ml_dtypes.bfloat16",
                 "jnp.float16", "jax.numpy.float16", "np.float16",
                 "numpy.float16",
                 # the fp8 storage rung: 3 (e4m3) / 2 (e5m2) mantissa bits
                 # — a psum at this width is even less an accumulator
                 # than bf16's 8
                 "jnp.float8_e4m3fn", "jax.numpy.float8_e4m3fn",
                 "ml_dtypes.float8_e4m3fn",
                 "jnp.float8_e5m2", "jax.numpy.float8_e5m2",
                 "ml_dtypes.float8_e5m2"}
NARROW_STRINGS = {"bfloat16", "bf16", "float16", "f16",
                  "float8_e4m3fn", "float8_e5m2", "float8", "f8"}

PSUM_CALLS = {"jax.lax.psum", "lax.psum", "psum", "psum_over_mesh",
              "collectives.psum_over_mesh", "jax.lax.pmean", "lax.pmean",
              "pmean"}


class FP64DriftRule(DataflowRule):
    rule_id = "JX004"

    # -- dataflow summary: does this function RETURN a narrow value? ---------
    def initial(self, fn, graph, ctx) -> bool:
        return self._returns_narrow(graph.index(fn), None, None)

    def transfer(self, fn, facts, graph, ctx) -> bool:
        if facts.get(fn, False):
            return True
        return self._returns_narrow(graph.index(fn), graph.sites_map(fn),
                                    facts)

    def top(self, fn, graph, ctx) -> bool:
        return True

    def _returns_narrow(self, idx, sites, facts) -> bool:
        for stmt in idx.returns:
            if stmt.value is None:
                continue
            # narrowness AT the return site: assigns textually after an
            # early return must not leak backwards into its verdict
            narrow_names = self._narrow_names(idx, sites, facts,
                                              upto=stmt.lineno)
            if self._expr_narrow(stmt.value, narrow_names, sites, facts):
                return True
        return False

    def _narrow_names(self, idx, sites, facts,
                      upto: Optional[int] = None) -> Set[str]:
        """Local names holding a narrow value at line ``upto`` (end of
        function when None), tracked in source order (``idx.assigns`` is
        source-ordered) so re-widening (``y = y.astype(jnp.float32)``)
        clears the mark — and a narrowing AFTER the queried line doesn't
        count against it."""
        out: Set[str] = set()
        for node in idx.assigns:
            if upto is not None and node.lineno >= upto:
                break
            narrow = self._expr_narrow(node.value, out, sites, facts)
            for t in assign_targets(node):
                for name in assigned_names(t):
                    if narrow:
                        out.add(name)
                    else:
                        out.discard(name)
        return out

    def _expr_narrow(self, expr: ast.AST, narrow_names: Set[str],
                     sites, facts) -> bool:
        if isinstance(expr, ast.Name):
            return expr.id in narrow_names
        if self._narrow_value(expr):
            return True
        if isinstance(expr, ast.Call) and sites is not None \
                and facts is not None:
            site = sites.get(id(expr))
            if site is not None and any(
                    facts.get(t, False) for t in site.targets):
                return True
        return False

    # -- the check -----------------------------------------------------------
    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        graph = ctx.callgraph
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        for fn in mod.functions:
            if not fn.jit_reachable:
                continue
            sites = graph.sites_map(fn) if graph is not None else None
            idx = graph.index(fn) if graph is not None else None
            for node in iter_own_statements(fn.node):
                if not mod.has_x64_guard:
                    hit = self._f64_use(node)
                    if hit:
                        yield self.finding(
                            mod, node,
                            f"{hit} in jit-reachable code without a "
                            f"`jax_enable_x64` guard in the module — silently "
                            f"downcast to float32 on default TPU configs (or a "
                            f"2x HBM + MXU perf hit where x64 is on); pass the "
                            f"dtype in from the data tier or guard the module",
                            fn.qualname)
                        continue
                # narrow-accumulator check runs regardless of the x64
                # guard: the guard legitimizes fp64 storage, not bf16 sums
                # across the mesh
                hit = self._narrow_psum(node, idx, sites, facts)
                if hit:
                    yield self.finding(
                        mod, node,
                        f"psum of a {hit} value — the collective "
                        f"accumulates at storage width (8 mantissa bits "
                        f"mesh-wide); bf16 is a STORAGE tier "
                        f"(cyclone.data.dtype) and ends at the kernel: "
                        f"upcast to the fp32 accumulator "
                        f"(cyclone.compute.dtype) before the psum",
                        fn.qualname)

    @staticmethod
    def _f64_use(node: ast.AST) -> Optional[str]:
        # dtype=<f64> keyword or positional dtype constants
        if isinstance(node, ast.keyword) and node.arg == "dtype":
            v = node.value
            name = dotted_name(v)
            if name in F64_DOTTED:
                return f"`dtype={name}`"
            if isinstance(v, ast.Constant) and v.value in F64_STRINGS:
                return f'`dtype="{v.value}"`'
            return None
        # direct casts: jnp.float64(x) / x.astype("float64")
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in F64_DOTTED:
                return f"`{name}(...)` cast"
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args):
                arg = node.args[0]
                aname = dotted_name(arg)
                if aname in F64_DOTTED:
                    return f"`.astype({aname})`"
                if isinstance(arg, ast.Constant) and arg.value in F64_STRINGS:
                    return f'`.astype("{arg.value}")`'
        return None

    def _narrow_psum(self, node: ast.AST, idx, sites,
                     facts) -> Optional[str]:
        """A psum/pmean whose operand is narrow: an explicit cast at the
        callsite, a local name assigned narrow (source-order tracked AT
        the callsite — a narrowing after the psum doesn't taint it), or
        a call into a returns-narrow function — the last two are the
        dataflow upgrades over the PR-1 cast-at-the-callsite pattern."""
        if not isinstance(node, ast.Call):
            return None
        if call_name(node) not in PSUM_CALLS or not node.args:
            return None
        operand = node.args[0]
        direct = self._narrow_value(operand)
        if direct:
            return direct
        if isinstance(operand, ast.Name) and idx is not None \
                and operand.id in self._narrow_names(idx, sites, facts,
                                                     upto=node.lineno):
            return f"narrow-assigned (`{operand.id}`)"
        if isinstance(operand, ast.Call) and sites is not None:
            site = sites.get(id(operand))
            if site is not None and any(
                    facts.get(t, False) for t in site.targets):
                return (f"`{call_name(operand)}(...)`-returned narrow")
        return None

    @staticmethod
    def _narrow_value(expr: ast.AST) -> Optional[str]:
        if not isinstance(expr, ast.Call):
            return None
        name = call_name(expr)
        if name in NARROW_DOTTED:
            return f"`{name}(...)`-cast"
        # x.astype(bf16-ish)
        if (isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "astype" and expr.args):
            arg = expr.args[0]
            aname = dotted_name(arg)
            if aname in NARROW_DOTTED:
                return f"`.astype({aname})`"
            if isinstance(arg, ast.Constant) and arg.value in NARROW_STRINGS:
                return f'`.astype("{arg.value}")`'
        # jnp.asarray(x, dtype=bf16) / jnp.zeros(..., dtype="bfloat16")
        for kw in expr.keywords:
            if kw.arg == "dtype":
                kname = dotted_name(kw.value)
                if kname in NARROW_DOTTED:
                    return f"`dtype={kname}`"
                if isinstance(kw.value, ast.Constant) \
                        and kw.value.value in NARROW_STRINGS:
                    return f'`dtype="{kw.value.value}"`'
        return None
