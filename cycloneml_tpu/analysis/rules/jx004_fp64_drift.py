"""JX004 — fp64 literal/dtype drift in device code.

Without ``jax.config.update("jax_enable_x64", True)``, JAX silently
downcasts every float64 request to float32 — so device code that asks
for ``jnp.float64`` / ``dtype="float64"`` is either a silent downcast
(TPU default) or, where x64 IS enabled, a 2x memory + severe MXU perf
hit smuggled into a hot path. Either way an explicit module-level guard
(any mention of ``jax_enable_x64``) is required context for fp64 in
jit-reachable code; absent that, it's flagged.

``np.float64`` on the HOST side (optimizer state, readbacks) is idiomatic
and untouched — only jit-reachable functions are scanned.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from cycloneml_tpu.analysis.astutil import (call_name, dotted_name,
                                            iter_own_statements)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule

F64_DOTTED = {"jnp.float64", "jax.numpy.float64", "np.float64",
              "numpy.float64", "jnp.complex128", "jax.numpy.complex128"}
F64_STRINGS = {"float64", "f64", "complex128"}


class FP64DriftRule(Rule):
    rule_id = "JX004"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        if mod.has_x64_guard:
            return
        for fn in mod.functions:
            if not fn.jit_reachable:
                continue
            for node in iter_own_statements(fn.node):
                hit = self._f64_use(node)
                if hit:
                    yield self.finding(
                        mod, node,
                        f"{hit} in jit-reachable code without a "
                        f"`jax_enable_x64` guard in the module — silently "
                        f"downcast to float32 on default TPU configs (or a "
                        f"2x HBM + MXU perf hit where x64 is on); pass the "
                        f"dtype in from the data tier or guard the module",
                        fn.qualname)

    @staticmethod
    def _f64_use(node: ast.AST) -> Optional[str]:
        # dtype=<f64> keyword or positional dtype constants
        if isinstance(node, ast.keyword) and node.arg == "dtype":
            v = node.value
            name = dotted_name(v)
            if name in F64_DOTTED:
                return f"`dtype={name}`"
            if isinstance(v, ast.Constant) and v.value in F64_STRINGS:
                return f'`dtype="{v.value}"`'
            return None
        # direct casts: jnp.float64(x) / x.astype("float64")
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in F64_DOTTED:
                return f"`{name}(...)` cast"
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args):
                arg = node.args[0]
                aname = dotted_name(arg)
                if aname in F64_DOTTED:
                    return f"`.astype({aname})`"
                if isinstance(arg, ast.Constant) and arg.value in F64_STRINGS:
                    return f'`.astype("{arg.value}")`'
        return None
