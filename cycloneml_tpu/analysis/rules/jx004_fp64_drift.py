"""JX004 — dtype drift across the data/accumulator tier boundary.

Two hazards, one boundary (docs/mixed-precision.md):

**fp64 drift.** Without ``jax.config.update("jax_enable_x64", True)``,
JAX silently downcasts every float64 request to float32 — so device code
that asks for ``jnp.float64`` / ``dtype="float64"`` is either a silent
downcast (TPU default) or, where x64 IS enabled, a 2x memory + severe
MXU perf hit smuggled into a hot path. Either way an explicit
module-level guard (any mention of ``jax_enable_x64``) is required
context for fp64 in jit-reachable code; absent that, it's flagged.

**Narrow accumulation.** The other direction of the same boundary: bf16
(``cyclone.data.dtype``) is legal STORAGE — design matrices live there —
but the tier ends at the kernel: every cross-device reduction must carry
the fp32 accumulator (``cyclone.compute.dtype``). A ``psum`` whose
operand is explicitly cast to bf16/f16 accumulates at storage width —
8 mantissa bits across the whole mesh — and is flagged regardless of any
x64 guard (the guard legitimizes fp64, not narrow reductions).

``np.float64`` on the HOST side (optimizer state, readbacks) is idiomatic
and untouched — only jit-reachable functions are scanned.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from cycloneml_tpu.analysis.astutil import (call_name, dotted_name,
                                            iter_own_statements)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule

F64_DOTTED = {"jnp.float64", "jax.numpy.float64", "np.float64",
              "numpy.float64", "jnp.complex128", "jax.numpy.complex128"}
F64_STRINGS = {"float64", "f64", "complex128"}

NARROW_DOTTED = {"jnp.bfloat16", "jax.numpy.bfloat16", "ml_dtypes.bfloat16",
                 "jnp.float16", "jax.numpy.float16", "np.float16",
                 "numpy.float16"}
NARROW_STRINGS = {"bfloat16", "bf16", "float16", "f16"}

PSUM_CALLS = {"jax.lax.psum", "lax.psum", "psum", "psum_over_mesh",
              "collectives.psum_over_mesh", "jax.lax.pmean", "lax.pmean",
              "pmean"}


class FP64DriftRule(Rule):
    rule_id = "JX004"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        for fn in mod.functions:
            if not fn.jit_reachable:
                continue
            for node in iter_own_statements(fn.node):
                if not mod.has_x64_guard:
                    hit = self._f64_use(node)
                    if hit:
                        yield self.finding(
                            mod, node,
                            f"{hit} in jit-reachable code without a "
                            f"`jax_enable_x64` guard in the module — silently "
                            f"downcast to float32 on default TPU configs (or a "
                            f"2x HBM + MXU perf hit where x64 is on); pass the "
                            f"dtype in from the data tier or guard the module",
                            fn.qualname)
                        continue
                # narrow-accumulator check runs regardless of the x64
                # guard: the guard legitimizes fp64 storage, not bf16 sums
                # across the mesh
                hit = self._narrow_psum(node)
                if hit:
                    yield self.finding(
                        mod, node,
                        f"psum of a {hit} value — the collective "
                        f"accumulates at storage width (8 mantissa bits "
                        f"mesh-wide); bf16 is a STORAGE tier "
                        f"(cyclone.data.dtype) and ends at the kernel: "
                        f"upcast to the fp32 accumulator "
                        f"(cyclone.compute.dtype) before the psum",
                        fn.qualname)

    @staticmethod
    def _f64_use(node: ast.AST) -> Optional[str]:
        # dtype=<f64> keyword or positional dtype constants
        if isinstance(node, ast.keyword) and node.arg == "dtype":
            v = node.value
            name = dotted_name(v)
            if name in F64_DOTTED:
                return f"`dtype={name}`"
            if isinstance(v, ast.Constant) and v.value in F64_STRINGS:
                return f'`dtype="{v.value}"`'
            return None
        # direct casts: jnp.float64(x) / x.astype("float64")
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name in F64_DOTTED:
                return f"`{name}(...)` cast"
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "astype" and node.args):
                arg = node.args[0]
                aname = dotted_name(arg)
                if aname in F64_DOTTED:
                    return f"`.astype({aname})`"
                if isinstance(arg, ast.Constant) and arg.value in F64_STRINGS:
                    return f'`.astype("{arg.value}")`'
        return None

    @classmethod
    def _narrow_psum(cls, node: ast.AST) -> Optional[str]:
        """A psum/pmean whose operand is an EXPLICIT narrow cast — the
        direct-evidence form of storage-width accumulation (a deeper
        dataflow pass would chase names; the paired fixtures pin this
        rule's precision at the cast-at-the-callsite pattern)."""
        if not isinstance(node, ast.Call):
            return None
        if call_name(node) not in PSUM_CALLS or not node.args:
            return None
        return cls._narrow_value(node.args[0])

    @staticmethod
    def _narrow_value(expr: ast.AST) -> Optional[str]:
        if not isinstance(expr, ast.Call):
            return None
        name = call_name(expr)
        if name in NARROW_DOTTED:
            return f"`{name}(...)`-cast"
        # x.astype(bf16-ish)
        if (isinstance(expr.func, ast.Attribute)
                and expr.func.attr == "astype" and expr.args):
            arg = expr.args[0]
            aname = dotted_name(arg)
            if aname in NARROW_DOTTED:
                return f"`.astype({aname})`"
            if isinstance(arg, ast.Constant) and arg.value in NARROW_STRINGS:
                return f'`.astype("{arg.value}")`'
        # jnp.asarray(x, dtype=bf16) / jnp.zeros(..., dtype="bfloat16")
        for kw in expr.keywords:
            if kw.arg == "dtype":
                kname = dotted_name(kw.value)
                if kname in NARROW_DOTTED:
                    return f"`dtype={kname}`"
                if isinstance(kw.value, ast.Constant) \
                        and kw.value.value in NARROW_STRINGS:
                    return f'`dtype="{kw.value.value}"`'
        return None
