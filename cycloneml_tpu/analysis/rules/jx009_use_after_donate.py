"""JX009 — read of a buffer after it was donated to a jit program.

``donate_argnums`` hands an input buffer to XLA for in-place reuse — the
safety net the out-of-core streaming engine needs to overlap transfer
with compute without doubling HBM. The price: the donated ``jax.Array``
is DELETED the moment the program dispatches, and any later read raises
``RuntimeError: Array has been deleted`` — but only at runtime, only on
backends where the donation was usable, and possibly only on the code
path that re-reads. This rule proves the discipline statically.

Dataflow summary: the set of a function's OWN parameter positions that
end up donated when it is called — seeded from direct
``jax.jit(..., donate_argnums=...)`` program calls (module- or
function-local bindings and donate-decorated functions) and propagated
through wrappers (``advance(state)`` that internally feeds ``state`` into
a donating dispatch donates ITS caller's buffer just as surely). The
per-function check then runs a source-order deadness scan: a name read
after flowing into a donated position — with no rebinding in between —
is flagged, as is a donation inside a loop whose name is never rebound
from the program's result (the second iteration re-dispatches a deleted
buffer).

The idiomatic pattern stays silent::

    state = step(state, x)     # donated AND rebound: old buffer was dead

Only reachable-in-host-driver code is scanned: inside a traced region a
"donation" is an inner-jit no-op on tracers, not a buffer hand-off.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set

from cycloneml_tpu.analysis.astutil import (FunctionInfo, assigned_names,
                                            call_name, last_component)
from cycloneml_tpu.analysis.dataflow import (COMPREHENSION_NODES, EMPTY, TOP,
                                             CallSite, JitParams,
                                             ProgramBindingsCache,
                                             jit_params_of_function,
                                             join_sets, param_index,
                                             set_contains)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.walker import BlockWalker

# aval-level metadata survives deletion: a donated jax.Array keeps its
# shape/dtype/etc — only the BUFFER is gone, so these reads are legal
METADATA_ATTRS = frozenset({"shape", "dtype", "ndim", "size", "nbytes",
                            "sharding", "aval", "is_deleted"})


class UseAfterDonateRule(DataflowRule):
    rule_id = "JX009"

    def __init__(self):
        self._bindings = ProgramBindingsCache()
        self._own_donations: Dict[FunctionInfo, frozenset] = {}

    # -- summaries: which of MY params get donated when I'm called? ----------
    def initial(self, fn: FunctionInfo, graph, ctx):
        return self._static_donations(fn, graph, ctx)

    def transfer(self, fn: FunctionInfo, facts, graph, ctx):
        out = set()
        params = param_index(fn)
        if params:
            # facts-dependent part: params handed whole into a donated
            # position of a resolved callee (sites only — no AST re-walk)
            for site in graph.sites(fn):
                for target in site.targets:
                    summary = facts.get(target)
                    if not summary or summary is TOP:
                        continue
                    for pi, expr in site.param_map(target):
                        if set_contains(summary, pi) \
                                and isinstance(expr, ast.Name) \
                                and expr.id in params:
                            out.add(params[expr.id])
        return join_sets(
            join_sets(self._static_donations(fn, graph, ctx),
                      frozenset(out)),
            facts.get(fn, EMPTY))

    def _bindings_for(self, fn: FunctionInfo, ctx,
                      graph) -> Dict[str, JitParams]:
        return self._bindings.bindings_for(fn, ctx, graph)

    def _static_donations(self, fn: FunctionInfo, graph, ctx) -> frozenset:
        """Facts-independent donations of ``fn``'s own params: the
        donate-decorator contract plus flows into bound donating programs
        (cached — the fixpoint revisits only the sites part)."""
        got = self._own_donations.get(fn)
        if got is not None:
            return got
        jp = jit_params_of_function(fn)
        out: Set[int] = set(jp.donate_argnums) if jp else set()
        params = param_index(fn)
        if params:
            bindings = self._bindings_for(fn, ctx, graph)
            for node in graph.index(fn).calls:
                for name in _donated_names(node, bindings, None, None):
                    if name in params:
                        out.add(params[name])
        result = frozenset(out)
        self._own_donations[fn] = result
        return result

    # -- the check: source-order deadness scan -------------------------------
    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        for fn in mod.functions:
            if fn.jit_reachable:
                continue
            yield from self._check_fn(mod, fn, ctx)

    def _check_fn(self, mod: ModuleInfo, fn: FunctionInfo,
                  ctx: AnalysisContext) -> Iterator[Finding]:
        graph = ctx.callgraph
        if graph is None:
            return
        bindings = self._bindings_for(fn, ctx, graph)
        sites = graph.sites_map(fn)
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        w = _DonationWalker(self, mod, fn, bindings, sites, facts)
        w.walk(getattr(fn.node, "body", []))
        yield from w.findings


class _DonationWalker(BlockWalker):
    """Source-order deadness scan on the shared terminator walker.
    ``state`` maps name -> the donating Call that deleted its buffer."""

    def __init__(self, rule: UseAfterDonateRule, mod: ModuleInfo,
                 fn: FunctionInfo, bindings, sites, facts):
        super().__init__()
        self.rule, self.mod, self.fn = rule, mod, fn
        self.bindings, self.sites, self.facts = bindings, sites, facts
        self.findings: List[Finding] = []

    def visit_expr(self, expr: ast.AST) -> None:
        """In-order expression walk: reads checked against the dead set;
        donation marks apply AFTER the donating call's own argument
        evaluation (left-to-right, like the runtime)."""
        dead = self.state
        if isinstance(expr, ast.Name) and isinstance(expr.ctx, ast.Load):
            if expr.id in dead:
                don = dead[expr.id]
                self.findings.append(self.rule.finding(
                    self.mod, expr,
                    f"`{expr.id}` is read after being donated to a jit "
                    f"program at line {don.lineno} "
                    f"(`donate_argnums`) — the buffer is deleted by "
                    f"that dispatch; read before dispatching, or bind "
                    f"a fresh value from the program's result",
                    self.fn.qualname))
                dead.pop(expr.id, None)   # one finding per hazard
            return
        if isinstance(expr, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return
        if isinstance(expr, COMPREHENSION_NODES):
            self._visit_comprehension(expr)
            return
        if isinstance(expr, ast.Attribute) \
                and expr.attr in METADATA_ATTRS \
                and isinstance(expr.value, ast.Name):
            # x.shape / x.dtype after donation never touches the
            # deleted buffer — telemetry reads stay legal
            return
        if isinstance(expr, ast.Call):
            for child in ast.iter_child_nodes(expr):
                self.visit_expr(child)
            for name in _donated_names(expr, self.bindings,
                                       self.sites.get(id(expr)),
                                       self.facts):
                dead[name] = expr
            return
        for child in ast.iter_child_nodes(expr):
            self.visit_expr(child)

    def _visit_comprehension(self, comp: ast.AST) -> None:
        """A comprehension iterates: a donation in its body that is not
        rebound per-iteration (comprehensions CANNOT rebind an outer
        name) re-dispatches a deleted buffer on iteration two — the
        spelled-out-loop hazard in its most idiomatic form."""
        dead = self.state
        bound: Set[str] = set()
        for gen in comp.generators:
            self.visit_expr(gen.iter)
            bound.update(assigned_names(gen.target))
        before = set(dead)
        body = ([comp.key, comp.value]
                if isinstance(comp, ast.DictComp) else [comp.elt])
        for gen in comp.generators:
            body.extend(gen.ifs)
        for part in body:
            self.visit_expr(part)
        for name, don in list(dead.items()):
            if name in before or name in bound:
                continue
            self.findings.append(self.rule.finding(
                self.mod, don,
                f"`{name}` is donated inside this comprehension but "
                f"cannot be rebound from the program's result — the "
                f"next iteration dispatches a deleted buffer; use a "
                f"spelled-out loop with `{name} = prog({name}, ...)` "
                f"or lax.scan",
                self.fn.qualname))
            dead.pop(name, None)

    def on_loop_body_end(self, stmt: ast.AST, term, entered_with) -> None:
        # a name donated INSIDE the loop and still dead at the end of the
        # body is re-read by the donating dispatch on the next iteration —
        # unless every body path leaves the loop (return/raise/break):
        # then no second iteration exists ("continue" paths DO re-iterate
        # and stay checked)
        dead = self.state
        for name, don in ([] if term in ("exit", "break")
                          else list(dead.items())):
            if name in entered_with:
                continue
            if don.lineno >= stmt.lineno:
                self.findings.append(self.rule.finding(
                    self.mod, don,
                    f"`{name}` is donated inside this loop but "
                    f"never rebound from the program's result — "
                    f"the next iteration dispatches a deleted "
                    f"buffer; use `{name} = prog({name}, ...)` "
                    f"so the donation consumes a dead value",
                    self.fn.qualname))
                dead.pop(name, None)


def _donated_names(call: ast.Call, bindings: Dict[str, JitParams],
                   site: Optional[CallSite], facts) -> List[str]:
    """Plain names this call donates: via a bound donating program
    (``prog = jax.jit(f, donate_argnums=...)``), an inline
    ``jax.jit(f, donate_argnums=...)(args)`` dispatch, or a resolved
    callee whose summary says it donates that parameter."""
    out: List[str] = []
    donate: frozenset = EMPTY
    if isinstance(call.func, ast.Name) and call.func.id in bindings:
        donate = bindings[call.func.id].donate_argnums
    elif isinstance(call.func, ast.Call) \
            and last_component(call_name(call.func)) in ("jit", "pjit"):
        donate = parse_inline(call.func)
    if donate:
        for pos, arg in enumerate(call.args):
            if isinstance(arg, ast.Starred):
                break
            if pos in donate and isinstance(arg, ast.Name):
                out.append(arg.id)
    if site is not None and facts is not None:
        for target in site.targets:
            summary = facts.get(target)
            if not summary or summary is TOP:
                # TOP only arises from hard widening; treating it as
                # donate-nothing keeps the rule quiet over noise
                continue
            for pi, expr in site.param_map(target):
                if set_contains(summary, pi) and isinstance(expr, ast.Name):
                    out.append(expr.id)
    return out


def parse_inline(jit_call: ast.Call) -> frozenset:
    from cycloneml_tpu.analysis.dataflow import parse_jit_params
    return parse_jit_params(jit_call).donate_argnums
