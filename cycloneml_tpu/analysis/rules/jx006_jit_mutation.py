"""JX006 — jitted function mutating ``self`` / ``global`` / ``nonlocal``.

A side effect inside traced code runs exactly once, at trace time, then
never again: ``self.n_steps += 1`` inside a jitted step silently freezes
at its trace-time value while every cached re-execution skips it. The
same applies to ``global``/``nonlocal`` rebinding and to in-place
container mutation of ``self`` attributes. State must flow through the
function's arguments/returns (the carry), or live on the host side of
the dispatch boundary.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set

from cycloneml_tpu.analysis.astutil import assigned_names, iter_own_statements
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import Rule

MUTATING_METHODS = {"append", "extend", "insert", "add", "update", "pop",
                    "remove", "clear", "setdefault", "discard"}


class JitMutationRule(Rule):
    rule_id = "JX006"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext) -> Iterator[Finding]:
        for fn in mod.functions:
            if not fn.jit_reachable:
                continue
            declared: Set[str] = set()
            for node in iter_own_statements(fn.node):
                if isinstance(node, (ast.Global, ast.Nonlocal)):
                    declared.update(node.names)
            for node in iter_own_statements(fn.node):
                if isinstance(node, (ast.Assign, ast.AugAssign,
                                     ast.AnnAssign)):
                    targets = node.targets if isinstance(node, ast.Assign) \
                        else [node.target]
                    for t in targets:
                        if self._is_self_attribute(t):
                            yield self.finding(
                                mod, node,
                                "assignment to `self.*` inside jit-reachable "
                                "code runs once at trace time and then "
                                "silently freezes; thread state through the "
                                "carry/returns instead",
                                fn.qualname)
                        else:
                            hit = declared.intersection(assigned_names(t))
                            if hit:
                                yield self.finding(
                                    mod, node,
                                    f"rebinding global/nonlocal "
                                    f"{sorted(hit)} inside jit-reachable "
                                    f"code is a trace-time-only side "
                                    f"effect; return the value instead",
                                    fn.qualname)
                elif isinstance(node, ast.Call) \
                        and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in MUTATING_METHODS \
                        and self._is_self_attribute(node.func.value):
                    yield self.finding(
                        mod, node,
                        f"`self.*.{node.func.attr}(...)` inside "
                        f"jit-reachable code mutates host state at trace "
                        f"time only; accumulate through the carry instead",
                        fn.qualname)

    @staticmethod
    def _is_self_attribute(node: ast.AST) -> bool:
        while isinstance(node, (ast.Attribute, ast.Subscript)):
            node = node.value
        return isinstance(node, ast.Name) and node.id in ("self", "cls")
