"""JX023 — chaos paths must stay deterministic under a seeded replay.

Every chaos test in ``tests/test_chaos.py`` pins the same invariant:
with a seeded ``FaultSchedule``, the run replays bit-identically —
retries land in the same order, backoff jitter repeats, the journal
matches. That only holds if the code *between* the fault points is
itself deterministic. This rule enforces it at the source, scoped to
functions whose shared ``JXFAULT`` summary says they transitively reach
a ``faults.inject`` site (JX020 owns the fixpoint; this rule only reads
the summaries):

1. **module-global random** — ``random.random()`` & friends draw from
   the process-global generator any other thread advances; use the
   component's seeded ``random.Random(seed)`` instance;
2. **dropped rng plumbing** — a call to a helper that *offers* an
   ``rng=None`` parameter (``backoff_delay`` style) without passing one
   falls back to the global generator inside the helper — the plumbing
   exists and the call declines it;
3. **clock-derived branching** — ``time.time()``/``monotonic()`` inside
   a branch test makes control flow depend on wall-clock scheduling;
   deadline/timeout bookkeeping is exempt (a timeout compare is the
   *point* of reading the clock), keyed on deadline/timeout/budget/
   expiry names in the test;
4. **unordered iteration** — ``for x in {...}`` / ``set(...)`` iterates
   in hash order, which varies across processes (PYTHONHASHSEED) and
   so re-orders dispatch between a run and its replay; sort first.

Functions outside chaos scope are never checked — ordinary code may use
the global generator freely.
"""

from __future__ import annotations

import ast
from typing import Iterator, Optional

from cycloneml_tpu.analysis.astutil import (FunctionInfo, call_name,
                                            dotted_name)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule
from cycloneml_tpu.analysis.rules.jx020_fault_coverage import (FAULT_ANALYSIS,
                                                               fault_initial,
                                                               fault_transfer)

#: module-global draws from ``random`` (seeded-instance methods excluded)
UNSEEDED_RANDOM = frozenset({
    "random", "randint", "randrange", "uniform", "choice", "choices",
    "shuffle", "sample", "gauss", "expovariate", "normalvariate",
    "triangular", "betavariate",
})

#: wall-clock reads that make a branch test scheduling-dependent
CLOCK_CALLS = frozenset({
    "time.time", "time.monotonic", "time.perf_counter",
    "time.process_time",
})

#: names that mark a clock read as deadline bookkeeping (exempt)
_DEADLINE_WORDS = ("deadline", "timeout", "budget", "expir")


def _rng_param(fn: FunctionInfo) -> Optional[int]:
    """Position of an ``rng`` parameter defaulting to ``None``, if any."""
    args = getattr(fn.node, "args", None)
    if args is None:
        return None
    pos = list(getattr(args, "posonlyargs", [])) + list(args.args)
    defaults = list(args.defaults)
    for i, arg in enumerate(pos):
        if arg.arg != "rng":
            continue
        di = i - (len(pos) - len(defaults))
        if 0 <= di < len(defaults) \
                and isinstance(defaults[di], ast.Constant) \
                and defaults[di].value is None:
            return i
    for j, arg in enumerate(args.kwonlyargs):
        default = args.kw_defaults[j]
        if arg.arg == "rng" and isinstance(default, ast.Constant) \
                and default.value is None:
            return len(pos) + j
    return None


def _dynamic_args(call: ast.Call) -> bool:
    return any(isinstance(a, ast.Starred) for a in call.args) \
        or any(kw.arg is None for kw in call.keywords)


def _deadline_test(test: ast.AST) -> bool:
    for sub in ast.walk(test):
        name = sub.id if isinstance(sub, ast.Name) else \
            sub.attr if isinstance(sub, ast.Attribute) else None
        if name and any(w in name.lower() for w in _DEADLINE_WORDS):
            return True
    return False


def _unordered(expr: ast.AST) -> bool:
    if isinstance(expr, (ast.Set, ast.SetComp)):
        return True
    if isinstance(expr, ast.Call) \
            and call_name(expr) in ("set", "frozenset"):
        return True
    if isinstance(expr, ast.BinOp) and isinstance(
            expr.op, (ast.BitOr, ast.BitAnd, ast.Sub, ast.BitXor)):
        return _unordered(expr.left) or _unordered(expr.right)
    return False


def _own_nodes(fn: FunctionInfo):
    """Walk ``fn``'s body without descending into nested defs (those
    carry their own JXFAULT fact and are checked on their own)."""
    stack = list(getattr(fn.node, "body", []))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


class SeededDeterminismRule(DataflowRule):
    rule_id = "JX023"

    # shares the JXFAULT fixpoint JX020 registers; the engine runs one
    # client per analysis_id, so these only define the fact for safety
    @property
    def analysis_id(self) -> str:
        return FAULT_ANALYSIS

    def initial(self, fn: FunctionInfo, graph, ctx) -> bool:
        return fault_initial(fn, graph)

    def transfer(self, fn: FunctionInfo, facts, graph, ctx) -> bool:
        return fault_transfer(fn, facts, graph)

    def top(self, fn, graph, ctx) -> bool:
        return True

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        graph = ctx.callgraph
        if graph is None or ctx.dataflow is None:
            return
        facts = ctx.dataflow.summaries(self.analysis_id)
        for fn in mod.functions:
            if facts.get(fn) is not True or fn.jit_reachable:
                continue
            index = graph.index(fn)
            sites = graph.sites_map(fn)

            for call in index.calls:
                dotted = dotted_name(call.func)
                # 1. process-global random draws
                if dotted is not None and "." in dotted:
                    head, _, meth = dotted.partition(".")
                    if head == "random" and meth in UNSEEDED_RANDOM:
                        yield self.finding(
                            mod, call,
                            f"`{dotted}()` draws from the process-global "
                            f"generator on a chaos path (this function "
                            f"reaches a faults.inject site) — any other "
                            f"thread's draw shifts the sequence and the "
                            f"seeded replay diverges; use a component "
                            f"`random.Random(seed)` instance",
                            fn.qualname)
                        continue
                # 2. declined rng plumbing
                if _dynamic_args(call):
                    continue
                site = sites.get(id(call))
                if site is None:
                    continue
                for target in site.targets:
                    ri = _rng_param(target)
                    if ri is None:
                        continue
                    provided = {pi for pi, _ in site.param_map(target)}
                    if ri not in provided:
                        yield self.finding(
                            mod, call,
                            f"`{target.qualname}` offers an `rng=None` "
                            f"parameter but this chaos-path call omits "
                            f"it, so the helper falls back to the "
                            f"process-global generator and the seeded "
                            f"replay diverges; pass the component's "
                            f"seeded rng",
                            fn.qualname)
                        break

            # 3. clock reads deciding a branch
            for branch in index.branches:
                test = getattr(branch, "test", None)
                if test is None or _deadline_test(test):
                    continue
                clock = next(
                    (c for c in ast.walk(test)
                     if isinstance(c, ast.Call)
                     and dotted_name(c.func) in CLOCK_CALLS), None)
                if clock is not None:
                    yield self.finding(
                        mod, branch,
                        f"branch test reads the wall clock "
                        f"(`{dotted_name(clock.func)}()`) on a chaos "
                        f"path — control flow depends on scheduling and "
                        f"the seeded replay diverges; branch on counted "
                        f"state, or name the bound a deadline/timeout "
                        f"if this is genuine deadline bookkeeping",
                        fn.qualname)

            # 4. hash-order iteration feeding dispatch
            for node in _own_nodes(fn):
                iters = []
                if isinstance(node, (ast.For, ast.AsyncFor)):
                    iters.append(node.iter)
                elif isinstance(node, (ast.ListComp, ast.SetComp,
                                       ast.DictComp, ast.GeneratorExp)):
                    iters.extend(g.iter for g in node.generators)
                for it in iters:
                    if _unordered(it):
                        yield self.finding(
                            mod, node,
                            f"iterating a set on a chaos path visits "
                            f"elements in hash order, which varies with "
                            f"PYTHONHASHSEED across processes — the "
                            f"replay dispatches in a different order "
                            f"than the recorded run; wrap in sorted()",
                            fn.qualname)
