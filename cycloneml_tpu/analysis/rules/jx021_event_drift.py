"""JX021 — events emitted but handled nowhere (telemetry pipeline drift).

Every ``CycloneEvent`` rides one pipeline: posted on the ListenerBus,
folded into the status store by ``AppStatusListener.on_event``'s
dispatch on the literal type name, journaled by ``to_json`` (which
writes the name under ``"Event"``), replayed by the history provider and
rolled up by the REST/webui surface. A subclass added without a handler
branch drifts silently: the post succeeds, the journal grows, and the
event reaches no store field, no REST route, no replay — PR 12's
``BlocksMigrated`` did exactly this.

The registry is the ``CycloneEvent`` subclass closure discovered from
class bases across the analyzed set; an event is **handled** when its
exact class name appears as a string literal anywhere in the set (the
``elif kind == "JobStart"`` idiom — journal filters and webui rollups
dispatch on the same literal). A constructor call of an event no literal
mentions convicts at the emit site.

When the ``CycloneEvent`` base itself is not in the analyzed set the
rule stays silent — no registry, nothing to cross-check.
"""

from __future__ import annotations

import ast
from typing import Iterator

from cycloneml_tpu.analysis.astutil import call_name, last_component
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.registries import (_node_owners, event_registry,
                                               handled_event_names)
from cycloneml_tpu.analysis.rules.base import Rule


class EventDriftRule(Rule):
    rule_id = "JX021"

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        registry = event_registry(ctx)
        if not registry:
            return
        # cheap text gate: most modules construct no events at all
        if not any(n in ln for ln in mod.source_lines for n in registry):
            return
        handled = handled_event_names(ctx)
        owners = _node_owners(mod)
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            name = last_component(call_name(node) or "")
            if name not in registry or name in handled:
                continue
            yield self.finding(
                mod, node,
                f"`{name}` is emitted here but its type name appears in "
                f"no handler in the analyzed set — the event reaches no "
                f"status-store field, no REST route, no history replay "
                f"(AppStatusListener.on_event dispatches on the literal "
                f"name); add the on_event branch (util/status.py) and "
                f"surface it, or drop the event",
                owners.get(id(node), ""))
