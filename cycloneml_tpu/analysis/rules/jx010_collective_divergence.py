"""JX010 — collective reachable under host-divergent branching.

Every collective is a RENDEZVOUS: all mesh participants must execute the
same program in the same order, or the straggler side blocks forever (the
PR-2 ``OneVsRest`` deadlock class, this time across hosts instead of
threads). GSPMD's program-uniformity invariant says the *structure* of
the dispatched program may not depend on values that differ per process.
A Python branch whose condition derives from a host-LOCAL source —
``jax.process_index()``, wall-clock time, ``random``, pids, hostnames,
environment variables — violates exactly that when a collective is
reachable under it: process 0 dispatches the psum program, process 1
never shows up, and the mesh hangs at 3 a.m. with no traceback.

Two dataflow summaries make the rule interprocedural:

* ``reaches_collective`` — the function (transitively, through resolved
  callees) dispatches a collective (``psum``-family, ``tree_aggregate``
  family, ``all_gather_hosts``, ...).
* ``returns_divergent`` — its return value derives from a host-local
  source, so ``if is_primary():`` is as hazardous as
  ``if jax.process_index() == 0:``.

Uniform branches stay silent: config flags, shape checks, values reduced
THROUGH a collective (already mesh-uniform by construction), and
divergent branches that only guard host-local work (logging, primary-only
checkpoint writes) are all fine.
"""

from __future__ import annotations

import ast
from typing import Iterator, Set, Tuple

from cycloneml_tpu.analysis.astutil import (FunctionInfo, assigned_names,
                                            call_name, last_component)
from cycloneml_tpu.analysis.dataflow import assign_targets
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule

# dispatch surfaces that rendezvous the mesh (jax.lax collectives + the
# repo's own aggregate waists)
COLLECTIVE_CALLS = {"psum", "pmean", "pmax", "pmin", "all_gather",
                    "all_to_all", "ppermute", "pshuffle", "psum_scatter",
                    "psum_over_mesh", "tree_aggregate",
                    "tree_aggregate_with_state", "all_gather_hosts",
                    "all_to_all_repartition"}

# host-local value sources: full dotted form (module functions whose bare
# name would be too common) ...
DIVERGENT_DOTTED = {
    "jax.process_index", "time.time", "time.monotonic", "time.perf_counter",
    "time.time_ns", "time.monotonic_ns", "random.random", "random.randint",
    "random.uniform", "random.choice", "random.sample", "random.shuffle",
    "random.getrandbits", "os.getenv", "os.getpid", "os.urandom",
    "os.environ.get", "socket.gethostname", "uuid.uuid1", "uuid.uuid4",
    "secrets.token_bytes", "secrets.token_hex",
}
# ... and bare names that are unambiguous however imported
DIVERGENT_BARE = {"process_index", "host_id", "monotonic", "perf_counter",
                  "getpid", "gethostname", "uuid4"}


class CollectiveDivergenceRule(DataflowRule):
    rule_id = "JX010"

    # facts: (reaches_collective, returns_divergent)
    def initial(self, fn: FunctionInfo, graph, ctx) -> Tuple[bool, bool]:
        idx = graph.index(fn)
        return (_own_collective(fn),
                _returns_divergent(idx, set(), lambda call: False))

    def transfer(self, fn: FunctionInfo, facts, graph, ctx
                 ) -> Tuple[bool, bool]:
        reaches, div = facts.get(fn, (False, False))
        reaches = reaches or _own_collective(fn)
        sites = graph.sites_map(fn)
        idx = graph.index(fn)

        def callee_divergent(call: ast.Call) -> bool:
            site = sites.get(id(call))
            return site is not None and any(
                facts.get(t, (False, False))[1] for t in site.targets)

        if not reaches:
            for site in graph.sites(fn):
                if any(facts.get(t, (False, False))[0]
                       for t in site.targets):
                    reaches = True
                    break
        if not div:
            div_names = _divergent_names(idx, callee_divergent)
            div = _returns_divergent(idx, div_names, callee_divergent)
        return (reaches, div)

    def top(self, fn, graph, ctx):
        return (True, True)

    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        graph = ctx.callgraph
        if graph is None:
            return
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        for fn in mod.functions:
            idx = graph.index(fn)
            if not idx.branches:
                continue
            sites = graph.sites_map(fn)

            def callee_divergent(call: ast.Call) -> bool:
                site = sites.get(id(call))
                return site is not None and any(
                    facts.get(t, (False, False))[1] for t in site.targets)

            def call_reaches_collective(call: ast.Call) -> bool:
                if last_component(call_name(call)) in COLLECTIVE_CALLS:
                    return True
                site = sites.get(id(call))
                return site is not None and any(
                    facts.get(t, (False, False))[0] for t in site.targets)

            div_names = _divergent_names(idx, callee_divergent)
            for node in idx.branches:
                if not _expr_divergent(node.test, div_names,
                                       callee_divergent):
                    continue
                hit = _branch_collective(node, call_reaches_collective)
                if hit is None:
                    continue
                yield self.finding(
                    mod, node,
                    f"collective `{_describe(hit)}` is reachable under a "
                    f"branch on a host-local value — mesh participants can "
                    f"disagree on program structure and deadlock the "
                    f"rendezvous (every process must dispatch the same "
                    f"collectives in the same order); hoist the collective "
                    f"out of the branch or derive the condition from a "
                    f"mesh-uniform value",
                    fn.qualname)


def _describe(call: ast.Call) -> str:
    return call_name(call) or "<call>"


def _own_collective(fn: FunctionInfo) -> bool:
    for name in fn.calls:
        if last_component(name) in COLLECTIVE_CALLS:
            return True
    return False


def _call_divergent_source(call: ast.Call) -> bool:
    name = call_name(call)
    if name is None:
        return False
    if name in DIVERGENT_DOTTED:
        return True
    base = last_component(name)
    return base in DIVERGENT_BARE


def _expr_divergent(expr: ast.AST, div_names: Set[str],
                    callee_divergent) -> bool:
    if isinstance(expr, ast.Name):
        return expr.id in div_names
    if isinstance(expr, ast.Call):
        if last_component(call_name(expr)) in COLLECTIVE_CALLS:
            # a value reduced THROUGH a collective is mesh-uniform by
            # construction — `pmax(elapsed)` launders a host-local input
            # (every participant sees the same reduced result)
            return False
        if _call_divergent_source(expr) or callee_divergent(expr):
            return True
    if isinstance(expr, ast.Subscript):
        # os.environ["..."] reads
        from cycloneml_tpu.analysis.astutil import dotted_name
        if dotted_name(expr.value) == "os.environ":
            return True
    return any(_expr_divergent(child, div_names, callee_divergent)
               for child in ast.iter_child_nodes(expr))


def _divergent_names(idx, callee_divergent) -> Set[str]:
    """Names assigned from host-divergent expressions, two-pass
    (loop-carried assignments converge on the second pass)."""
    out: Set[str] = set()
    for _ in range(2):
        for stmt in idx.assigns:
            if _expr_divergent(stmt.value, out, callee_divergent):
                for t in assign_targets(stmt):
                    out.update(assigned_names(t))
    return out


def _returns_divergent(idx, div_names: Set[str],
                       callee_divergent) -> bool:
    for stmt in idx.returns:
        if stmt.value is not None and _expr_divergent(
                stmt.value, div_names, callee_divergent):
            return True
    return False


def _branch_collective(node: ast.AST, call_reaches_collective):
    """First collective-reaching call under a branch (its own statements
    only, nested defs excluded), else None. ``IfExp`` arms are single
    expressions — the one-line `agg(x) if primary else None` spelling
    deadlocks exactly like the block form."""
    body = node.body if isinstance(node.body, list) else [node.body]
    orelse = getattr(node, "orelse", [])
    orelse = orelse if isinstance(orelse, list) else [orelse]
    stack = body + orelse
    while stack:
        sub = stack.pop(0)
        if isinstance(sub, (ast.FunctionDef, ast.AsyncFunctionDef,
                            ast.Lambda, ast.ClassDef)):
            continue
        if isinstance(sub, ast.Call) and call_reaches_collective(sub):
            return sub
        stack.extend(ast.iter_child_nodes(sub))
    return None
