"""JX011 — field read/written outside its inferred guarding lock.

Python locks are annotation-free: nothing in the source says which lock
guards ``self._count``. The rule recovers the discipline two ways at once
(RacerD's lockset summaries + Engler's "bugs as deviant behavior"
inference): for each class, every ``self.<field>`` access is paired with
the lockset held around it — lexically (``with self._lock:`` blocks) and
interprocedurally (a helper only ever called with the lock held inherits
*locks-held-at-entry* through the call graph, a must-analysis iterated
downward over callers) — and each field's guard is inferred from the
**majority** of its accesses. An access with an empty lockset where the
majority holds the inferred guard is a deviant: a data race window.

Writes are the severe case (lost updates, torn multi-field invariants);
unguarded reads still flag (a reader can observe a half-updated pair like
``_sum``/``_count``) with read severity in the message.

What stays silent, by design:

* fields with no write outside ``__init__``-style ownership methods
  (publish-then-read-only is safe without locks);
* classes whose accesses never hold a lock (single-threaded by
  convention — inferring a guard needs evidence one exists);
* the double-checked fast path: an unguarded *read* in a function that
  ALSO accesses the same field under the inferred guard (the re-check
  idiom: cheap racy peek, then decide under the lock);
* lock fields themselves, and accesses whose effective lockset is
  non-empty but merely different (a field consistently guarded by two
  locks in different phases is a design smell, not this rule's race).

Suppress a deliberate racy read (e.g. a monotonic stats peek) with
``# graftlint: disable=JX011`` and a comment saying why it is benign.
"""

from __future__ import annotations

from collections import defaultdict
from typing import Dict, Iterator, List, Tuple

from cycloneml_tpu.analysis.astutil import FunctionInfo
from cycloneml_tpu.analysis.dataflow import EMPTY, TOP, meet_sets
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.locks import (OWNERSHIP_METHODS, SelfAccess,
                                          lockish_name, model_for,
                                          pretty_lock)
from cycloneml_tpu.analysis.rules.base import DataflowRule


def _method_name(fn: FunctionInfo) -> str:
    return fn.qualname.rsplit(".", 1)[-1]


class LocksetRaceRule(DataflowRule):
    rule_id = "JX011"
    #: the fact is what CALL CONTEXTS establish — propagate caller->callee
    direction = "down"

    # -- summary: locks guaranteed held at entry (must-analysis) -------------
    def initial(self, fn: FunctionInfo, graph, ctx):
        # greatest fixpoint: start optimistic (TOP = "all locks") and meet
        # downward over call contexts; a function with no resolved callers
        # is an entry point — nothing is guaranteed held
        return TOP if graph.callers_of(fn) else EMPTY

    def transfer(self, fn: FunctionInfo, facts, graph, ctx):
        callers = graph.callers_of(fn)
        if not callers:
            return EMPTY
        model = model_for(ctx)
        entry = TOP
        for caller in callers:
            if _method_name(caller) in OWNERSHIP_METHODS:
                # a call from __init__ runs pre-publication — that
                # context is single-threaded and must not weaken the
                # meet (`_load_state` called bare from __init__ AND
                # under the lock from the elector thread is guarded
                # where it matters)
                continue
            caller_entry = facts.get(caller, EMPTY)
            info = model.info(caller)
            for site in graph.sites(caller):
                if fn not in site.targets:
                    continue
                held = info.call_locks.get(id(site.node), EMPTY)
                if caller_entry is TOP:
                    contrib = TOP
                else:
                    contrib = frozenset(caller_entry) | held
                entry = meet_sets(entry, contrib)
                if entry is not TOP and not entry:
                    return EMPTY    # already bottom — stop early
        # every caller is an ownership context: the accesses are owned
        # (TOP = "treat as guarded"), not racy
        return entry

    def top(self, fn, graph, ctx):
        # widening for a must-analysis degrades to "assume guarded":
        # silence over noise when the fixpoint budget blows
        return TOP

    # -- the check: per-class guard inference --------------------------------
    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        model = model_for(ctx)
        entry_of = (ctx.dataflow.summaries(self.analysis_id)
                    if ctx.dataflow is not None else {})

        by_class: Dict[str, List[FunctionInfo]] = defaultdict(list)
        for fn in mod.functions:
            if fn.class_name is not None and fn.parent is None:
                by_class[fn.class_name].append(fn)

        for cls, methods in by_class.items():
            lock_fields = model.lock_fields.get(cls, {})
            # field -> [(access, effective lockset | TOP)]
            records: Dict[str, List[Tuple[SelfAccess, object]]] = \
                defaultdict(list)
            for fn in methods:
                if _method_name(fn) in OWNERSHIP_METHODS:
                    continue
                entry = entry_of.get(fn, EMPTY)
                for acc in model.info(fn).accesses:
                    if acc.field in lock_fields or lockish_name(acc.field):
                        continue
                    if entry is TOP:
                        eff = TOP
                    else:
                        eff = acc.locks | frozenset(entry)
                    records[acc.field].append((acc, eff))
            for field, recs in records.items():
                yield from self._check_field(mod, cls, field, recs)

    def _check_field(self, mod: ModuleInfo, cls: str, field: str,
                     recs) -> Iterator[Finding]:
        if not any(acc.is_write for acc, _ in recs):
            return
        # candidate guards: every concrete lock seen on any access
        candidates = set()
        for _, eff in recs:
            if eff is not TOP:
                candidates.update(eff)
        if not candidates:
            return
        guard, guarded = None, -1
        for lock in sorted(candidates):
            n = sum(1 for _, eff in recs
                    if eff is TOP or lock in eff)
            if n > guarded:
                guard, guarded = lock, n
        unguarded = len(recs) - guarded
        # the majority must hold the guard — deviants are the minority
        if guarded < max(unguarded, 1):
            return
        # functions that touch the field under the guard (for the
        # double-checked-read exemption)
        checked_fns = {acc.fn for acc, eff in recs
                       if eff is TOP or guard in eff}
        for acc, eff in recs:
            if eff is TOP or eff:
                continue          # guarded, or held under SOME lock
            if not acc.is_write and acc.fn in checked_fns:
                continue          # double-checked fast path
            kind = "write" if acc.is_write else "read"
            severity = ("lost updates / torn invariants"
                        if acc.is_write
                        else "can observe a half-updated state")
            yield self.finding(
                mod, acc.node,
                f"unguarded {kind} of `self.{field}`: {guarded} of "
                f"{len(recs)} accesses of `{cls}.{field}` hold "
                f"`{pretty_lock(guard)}`, this one holds no lock — "
                f"{severity}; take the lock here (or suppress with a "
                f"comment saying why this racy {kind} is benign)",
                acc.fn.qualname)
