"""JX008 — compile-cache explosion at a jit entry point.

Staged computation means the Python callsite is a CACHE LOOKUP: jit
programs are keyed on abstract shapes/dtypes plus the concrete values of
static arguments. Feed that key something that varies per loop iteration
and every "dispatch" silently pays a full trace + XLA compile — seconds
per step instead of microseconds, unbounded cache growth, and the
compile-once discipline the serving engine depends on is gone. The
retracing pitfalls are exactly Frostig et al.'s staged-programming
hazards; this rule mechanizes them:

* a **loop-varying value in a static position** (``static_argnums`` /
  ``static_argnames``) — one compile per distinct value;
* a **loop-varying shape** in a traced position (``prog(x[:i])``,
  ``jnp.arange(i)`` operands) — one compile per distinct shape; pad to
  bucketed shapes or lift the loop into the program (``lax.scan``);
* an **unhashable static argument** (list/dict/set literal) — fails the
  cache lookup outright (TypeError at every call);
* a **program built inside a loop** (``jax.jit(...)`` /
  ``tree_aggregate_fn(...)`` in the body) — a fresh, empty cache each
  iteration defeats caching even for identical shapes.

Dataflow summaries make the check interprocedural: each function's
summary records which of its OWN parameters land (transitively, through
wrappers) in a value-keyed position (``value_keyed``) or flow whole into
a traced operand slot (``shape_keyed``) of some jit entry. The loop scan
then flags a call like ``run_one(x, i)`` even though the ``static_argnums``
entry point is two frames away.

Only host driver code is scanned for the loop hazards: a Python loop
inside a traced function unrolls into ONE program — its per-iteration
"calls" are trace-time inlining, not cache lookups.
"""

from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Set, Tuple

from cycloneml_tpu.analysis.astutil import (FunctionInfo, assigned_names,
                                            call_name, last_component)
from cycloneml_tpu.analysis.dataflow import (COMPREHENSION_NODES, EMPTY, TOP,
                                             assign_targets,
                                             CallSite, JitParams,
                                             ProgramBindingsCache,
                                             jit_params_of_function,
                                             join_sets, param_index,
                                             set_contains)
from cycloneml_tpu.analysis.engine import AnalysisContext, Finding, ModuleInfo
from cycloneml_tpu.analysis.rules.base import DataflowRule

PROGRAM_BUILD_CALLS = {"jit", "pjit", "tree_aggregate_fn",
                       "tree_aggregate_with_state"}
SHAPE_BUILDER_CALLS = {"zeros", "ones", "full", "empty", "arange",
                       "linspace", "eye"}
UNHASHABLE_NODES = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp,
                    ast.SetComp)


class RecompileHazardRule(DataflowRule):
    rule_id = "JX008"

    def __init__(self):
        self._bindings = ProgramBindingsCache()
        self._static_sinks: Dict[FunctionInfo,
                                 Tuple[frozenset, frozenset]] = {}

    # -- summaries -----------------------------------------------------------
    # facts: (value_keyed, shape_keyed) — param-index sets (frozenset|TOP)
    def initial(self, fn: FunctionInfo, graph, ctx):
        return self._scan_static(fn, graph, ctx)

    def transfer(self, fn: FunctionInfo, facts, graph, ctx):
        vk0, sk0 = self._scan_static(fn, graph, ctx)
        params = param_index(fn)
        vk: Set[int] = set()
        sk: Set[int] = set()
        if params:
            # facts-dependent part: params flowing into wrapper callees'
            # sink positions (sites only — no AST re-walk per visit)
            for site in graph.sites(fn):
                for target in site.targets:
                    if jit_params_of_function(target) is not None:
                        continue   # handled by the static scan
                    summary = facts.get(target)
                    if summary is None:
                        continue
                    tvk, tsk = summary
                    for pi, expr in site.param_map(target):
                        if set_contains(tvk, pi):
                            _sink_value(expr, params, vk)
                        elif set_contains(tsk, pi):
                            _sink_shape(expr, params, vk, sk)
        old_vk, old_sk = facts.get(fn, (EMPTY, EMPTY))
        return (join_sets(join_sets(vk0, frozenset(vk)), old_vk),
                join_sets(join_sets(sk0, frozenset(sk)), old_sk))

    def top(self, fn, graph, ctx):
        return (TOP, TOP)

    def _bindings_for(self, fn: FunctionInfo, ctx,
                      graph) -> Dict[str, JitParams]:
        return self._bindings.bindings_for(fn, ctx, graph)

    def _scan_static(self, fn: FunctionInfo, graph, ctx
                     ) -> Tuple[frozenset, frozenset]:
        """Facts-independent sinks: params feeding bound jit programs and
        jit-decorated callees directly (cached; the fixpoint revisits
        only the wrapper part)."""
        got = self._static_sinks.get(fn)
        if got is not None:
            return got
        params = param_index(fn)
        if not params:
            self._static_sinks[fn] = (EMPTY, EMPTY)
            return (EMPTY, EMPTY)
        bindings = self._bindings_for(fn, ctx, graph)
        sites = graph.sites_map(fn)
        resolve = _resolver_for(fn, graph)
        vk: Set[int] = set()
        sk: Set[int] = set()
        for node in graph.index(fn).calls:
            for pos_kind, expr in _entry_arg_kinds(node, bindings,
                                                   sites.get(id(node)),
                                                   None, resolve):
                if pos_kind == "static":
                    _sink_value(expr, params, vk)
                else:
                    _sink_shape(expr, params, vk, sk)
        result = (frozenset(vk), frozenset(sk))
        self._static_sinks[fn] = result
        return result

    # -- the check -----------------------------------------------------------
    def check(self, mod: ModuleInfo, ctx: AnalysisContext
              ) -> Iterator[Finding]:
        graph = ctx.callgraph
        if graph is None:
            return
        facts = (ctx.dataflow.summaries(self.analysis_id)
                 if ctx.dataflow is not None else {})
        for fn in mod.functions:
            bindings = self._bindings_for(fn, ctx, graph)
            sites = graph.sites_map(fn)
            resolve = _resolver_for(fn, graph)
            # unhashable statics fail regardless of loops or reachability
            yield from self._check_unhashable(mod, fn, bindings, sites,
                                              graph, resolve)
            if fn.jit_reachable:
                continue   # a loop inside a trace unrolls into ONE program
            flagged: Set[int] = set()
            for node in graph.index(fn).loops:
                varying = _loop_varying_names(node)
                if varying:
                    yield from self._check_loop(
                        mod, fn, node, varying, bindings, sites, facts,
                        flagged, resolve)
                yield from self._check_builds_in_loop(mod, fn, node,
                                                      flagged)

    def _check_unhashable(self, mod, fn, bindings, sites, graph, resolve
                          ) -> Iterator[Finding]:
        for node in graph.index(fn).calls:
            for kind, expr in _entry_arg_kinds(node, bindings,
                                               sites.get(id(node)), None,
                                               resolve):
                if kind == "static" and isinstance(expr, UNHASHABLE_NODES):
                    yield self.finding(
                        mod, node,
                        "unhashable static argument (list/dict/set) to a "
                        "jit entry point — the compile-cache lookup raises "
                        "TypeError at every call; pass a tuple or other "
                        "hashable config",
                        fn.qualname)

    def _check_loop(self, mod, fn, loop, varying: Set[str], bindings,
                    sites, facts, flagged: Set[int], resolve
                    ) -> Iterator[Finding]:
        for node in _loop_body_nodes(loop):
            if not isinstance(node, ast.Call) or id(node) in flagged:
                continue
            for kind, expr in _entry_arg_kinds(node, bindings,
                                               sites.get(id(node)), facts,
                                               resolve):
                if kind == "static":
                    hit = _names_in(expr) & varying
                    if hit:
                        flagged.add(id(node))
                        yield self.finding(
                            mod, node,
                            f"loop-varying value `{sorted(hit)[0]}` feeds a "
                            f"compile-cache-keyed (static) position of a "
                            f"jit entry point — a NEW program is traced and "
                            f"compiled every iteration (cache-key "
                            f"explosion); hoist the static out of the loop "
                            f"or make it a traced operand",
                            fn.qualname)
                        break
                else:
                    hit = _shape_determinant_names(expr) & varying
                    if hit:
                        flagged.add(id(node))
                        yield self.finding(
                            mod, node,
                            f"loop-varying shape (`{sorted(hit)[0]}` sizes "
                            f"an operand) fed to a jit entry point — each "
                            f"distinct shape recompiles; pad to fixed "
                            f"shape buckets or lift the loop into the "
                            f"program (lax.scan/fori_loop)",
                            fn.qualname)
                        break

    def _check_builds_in_loop(self, mod, fn, loop, flagged: Set[int]
                              ) -> Iterator[Finding]:
        for node in _loop_body_nodes(loop):
            if not isinstance(node, ast.Call) or id(node) in flagged:
                continue
            base = last_component(call_name(node))
            if base in PROGRAM_BUILD_CALLS:
                flagged.add(id(node))
                yield self.finding(
                    mod, node,
                    f"`{base}(...)` builds a jit program INSIDE a loop — "
                    f"each iteration gets a fresh, empty compile cache, so "
                    f"even identical shapes recompile; build once outside "
                    f"the loop and dispatch the bound program",
                    fn.qualname)


# -- helpers ------------------------------------------------------------------

def _resolver_for(fn, graph):
    """Callee-name resolution bound to ``fn``'s scope (memoized by the
    shared CallResolver)."""
    return lambda name: graph.resolver.resolve(fn, name)


def _sink_value(expr: ast.AST, params: Dict[str, int],
                vk: Set[int]) -> None:
    """Params named anywhere in ``expr`` feed a value-keyed cache slot."""
    for name in _names_in(expr):
        if name in params:
            vk.add(params[name])


def _sink_shape(expr: ast.AST, params: Dict[str, int], vk: Set[int],
                sk: Set[int]) -> None:
    """A param passed WHOLE into a traced slot is shape-keyed; a param
    sizing the operand (slice bound / constructor size) is value-keyed —
    its value picks the shape."""
    if isinstance(expr, ast.Name) and expr.id in params:
        sk.add(params[expr.id])
    for name in _shape_determinant_names(expr):
        if name in params:
            vk.add(params[name])


def _names_in(expr: ast.AST) -> Set[str]:
    return {n.id for n in ast.walk(expr)
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)}


def _shape_determinant_names(expr: ast.AST) -> Set[str]:
    """Names whose VALUE determines the shape of ``expr``'s result:
    slice bounds (``x[:i]``) and size arguments of array constructors
    (``jnp.arange(i)``, ``jnp.zeros((i, d))``)."""
    out: Set[str] = set()
    for node in ast.walk(expr):
        if isinstance(node, ast.Subscript):
            slices = [node.slice]
            if isinstance(node.slice, ast.Tuple):
                slices = list(node.slice.elts)
            for sl in slices:
                if isinstance(sl, ast.Slice):
                    for bound in (sl.lower, sl.upper, sl.step):
                        if bound is not None:
                            out.update(_names_in(bound))
        elif isinstance(node, ast.Call):
            if last_component(call_name(node)) in SHAPE_BUILDER_CALLS:
                shape_args = list(node.args[:1]) + [
                    kw.value for kw in node.keywords if kw.arg == "shape"]
                for a in shape_args:
                    out.update(_names_in(a))
    return out


def _kw_static_names(jp: JitParams, resolve) -> frozenset:
    """Param NAMES behind ``static_argnums`` when the wrapped function
    resolves — JAX keys a keyword call onto the static position just
    like the positional form (``prog(x, width=i)`` recompiles per
    distinct ``i``), so the classification must too."""
    if not jp.static_argnums or jp.wrapped is None or resolve is None:
        return EMPTY
    targets = resolve(jp.wrapped)
    if len(targets) != 1:
        return EMPTY
    params = param_index(targets[0])
    return frozenset(n for n, i in params.items()
                     if i in jp.static_argnums)


def _entry_arg_kinds(call: ast.Call, bindings: Dict[str, JitParams],
                     site: Optional[CallSite], facts, resolve=None
                     ) -> List[Tuple[str, ast.AST]]:
    """Classify this call's arguments against jit-entry semantics:
    ("static", expr) for value-keyed positions, ("traced", expr) for
    traced operand positions. Empty when the callee is not a known jit
    entry / hazard-carrying wrapper. ``resolve`` (name ->
    [FunctionInfo]) maps keyword calls onto static_argnums positions
    via the wrapped function's signature."""
    out: List[Tuple[str, ast.AST]] = []
    # 1) a bound program name: prog = jax.jit(f, static_argnums=...)
    jp: Optional[JitParams] = None
    if isinstance(call.func, ast.Name) and call.func.id in bindings:
        jp = bindings[call.func.id]
    if jp is not None:
        if jp.statics_known:
            for pos, arg in enumerate(call.args):
                if isinstance(arg, ast.Starred):
                    break
                out.append(("static" if pos in jp.static_argnums
                            else "traced", arg))
            kw_static = _kw_static_names(jp, resolve)
            for kw in call.keywords:
                if kw.arg is not None:
                    out.append(("static" if (kw.arg in jp.static_argnames
                                             or kw.arg in kw_static)
                                else "traced", kw.value))
        return out
    if site is None:
        return out
    for target in site.targets:
        tjp = jit_params_of_function(target)
        if tjp is not None:
            # a jit-decorated function called directly
            if not tjp.statics_known:
                continue
            params = param_index(target)
            static_idx = set(tjp.static_argnums) | {
                params[n] for n in tjp.static_argnames if n in params}
            for pi, expr in site.param_map(target):
                out.append(("static" if pi in static_idx else "traced",
                            expr))
        elif facts is not None:
            # 3) a wrapper whose summary carries sink positions
            summary = facts.get(target)
            if summary is None:
                continue
            vk, sk = summary
            for pi, expr in site.param_map(target):
                if set_contains(vk, pi):
                    out.append(("static", expr))
                elif set_contains(sk, pi):
                    out.append(("traced", expr))
    return out


def _loop_varying_names(loop: ast.AST) -> Set[str]:
    """Names that take a new value each iteration: the for-target (or
    every comprehension generator target, plus one derivation pass over
    body assignments), or counters aug-assigned in a while body."""
    varying: Set[str] = set()
    if isinstance(loop, (ast.For, ast.AsyncFor)):
        varying.update(assigned_names(loop.target))
    elif isinstance(loop, COMPREHENSION_NODES):
        for gen in loop.generators:
            varying.update(assigned_names(gen.target))
    for node in _loop_body_nodes(loop):
        if isinstance(node, ast.AugAssign):
            varying.update(assigned_names(node.target))
    # one derivation pass: names assigned from varying expressions
    for node in _loop_body_nodes(loop):
        if isinstance(node, (ast.Assign, ast.AnnAssign)) \
                and getattr(node, "value", None) is not None:
            if _names_in(node.value) & varying:
                for t in assign_targets(node):
                    varying.update(assigned_names(t))
    return varying


def _loop_body_nodes(loop: ast.AST):
    """All nodes under a loop body (orelse excluded — it runs once),
    nested defs excluded. For comprehensions the per-iteration body is
    the element expression(s) plus inner generators' iterables and every
    `if` filter (the FIRST iterable is evaluated once, outside)."""
    if isinstance(loop, COMPREHENSION_NODES):
        stack = ([loop.key, loop.value] if isinstance(loop, ast.DictComp)
                 else [loop.elt])
        for i, gen in enumerate(loop.generators):
            if i > 0:
                stack.append(gen.iter)
            stack.extend(gen.ifs)
    else:
        stack = list(loop.body)
    while stack:
        node = stack.pop(0)
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))
