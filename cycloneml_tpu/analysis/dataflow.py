"""Interprocedural dataflow: abstract facts propagated over the call graph.

PR 1's rule pack was per-function pattern matching — good enough for
hazards whose evidence sits on one line, blind to anything split across a
call boundary. This module adds the missing layer: a **function-summary
dataflow engine** in the classic worklist style. Each participating rule
(:class:`~cycloneml_tpu.analysis.rules.base.DataflowRule`) contributes a
transfer function computing ONE summary fact per function from the
function's own body plus its callees' current summaries; the engine
iterates bottom-up over the :class:`CallGraph` (re-queuing CALLERS of any
function whose summary changed) until a fixpoint. Rules then run their
usual per-module ``check()`` with the converged summaries available via
``ctx.dataflow``.

Facts live in small, explicitly bounded lattices so the fixpoint provably
terminates:

* bools join with ``or`` (monotone, height 2);
* parameter-index sets join with union, **widened** to the absorbing
  :data:`TOP` element once they outgrow :data:`SET_WIDEN_LIMIT`;
* a per-function visit budget (:data:`MAX_VISITS`) hard-widens to the
  rule's ``top()`` as a backstop against a non-monotone transfer bug —
  a wrong summary must degrade to "unknown", never to an endless loop.

``TOP`` always means *any/unknown* — membership tests succeed, so rules
degrade toward (possibly noisy) conservatism rather than silence; in
practice the limits are never hit by real code (a function with 32
distinct hazard-carrying parameters is its own finding).
"""

from __future__ import annotations

import ast
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

from cycloneml_tpu.analysis.astutil import (FunctionInfo, call_name,
                                            iter_own_statements,
                                            last_component)
from cycloneml_tpu.analysis.reachability import CallResolver

# -- lattice primitives -------------------------------------------------------

SET_WIDEN_LIMIT = 32   # parameter-index sets wider than this widen to TOP
MAX_VISITS = 24        # per-function transfer budget before hard-widening

COMPREHENSION_NODES = (ast.ListComp, ast.SetComp, ast.GeneratorExp,
                       ast.DictComp)


class _Top:
    """The absorbing "any/unknown" lattice element (singleton)."""

    _instance: Optional["_Top"] = None

    def __new__(cls):
        if cls._instance is None:
            cls._instance = super().__new__(cls)
        return cls._instance

    def __repr__(self):
        return "TOP"

    def __contains__(self, item):   # `x in TOP` is always true
        return True


TOP = _Top()

EMPTY = frozenset()


def join_sets(a, b, limit: int = SET_WIDEN_LIMIT):
    """Join two powerset elements (``frozenset | TOP``): union, widened to
    :data:`TOP` past ``limit``. TOP is absorbing."""
    if a is TOP or b is TOP:
        return TOP
    u = frozenset(a) | frozenset(b)
    return TOP if len(u) > limit else u


def set_contains(s, item) -> bool:
    """Membership under the powerset-with-TOP lattice."""
    return s is TOP or item in s


def meet_sets(a, b):
    """Meet (intersection) of two powerset elements; TOP is the identity.

    Must-analyses (JX011's locks-held-at-entry: a lock counts only when
    EVERY call path holds it) iterate downward from TOP with this, where
    the join-based facts iterate upward from EMPTY."""
    if a is TOP:
        return b
    if b is TOP:
        return a
    return frozenset(a) & frozenset(b)


def join_bools(a: bool, b: bool) -> bool:
    return bool(a) or bool(b)


# -- call graph ---------------------------------------------------------------

@dataclass
class CallSite:
    """One resolved-or-not call expression inside a function's own body."""

    node: ast.Call
    name: str                              # dotted callee as written
    targets: Tuple[FunctionInfo, ...]      # () when unresolvable

    def arg_for_param(self, target: FunctionInfo, index: int
                      ) -> Optional[ast.AST]:
        """The argument expression feeding ``target``'s parameter at
        positional ``index``, accounting for the bound-method offset
        (``self.m(x)`` feeds ``x`` to param 1). None when the mapping is
        out of range or obscured by ``*args``."""
        for i, expr in self.param_map(target):
            if i == index:
                return expr
        return None

    def param_map(self, target: FunctionInfo
                  ) -> List[Tuple[int, ast.AST]]:
        """(callee param index, argument expr) pairs for one resolved
        target. Starred args end the positional mapping (everything after
        them is unknown); keywords map by parameter name."""
        params = _ordered_params(target)
        offset = 0
        if isinstance(self.node.func, ast.Attribute) and params[:1] in (
                ["self"], ["cls"]):
            offset = 1
        out: List[Tuple[int, ast.AST]] = []
        for pos, arg in enumerate(self.node.args):
            if isinstance(arg, ast.Starred):
                break
            out.append((pos + offset, arg))
        for kw in self.node.keywords:
            if kw.arg is not None and kw.arg in params:
                out.append((params.index(kw.arg), kw.value))
        return out


def _ordered_params(fn: FunctionInfo) -> List[str]:
    args = getattr(fn.node, "args", None)
    if args is None:
        return []
    return [a.arg for a in (list(args.posonlyargs) + list(args.args)
                            + list(args.kwonlyargs))]


def param_index(fn: FunctionInfo) -> Dict[str, int]:
    """name -> position over posonly+pos+kwonly — the same ordering
    :meth:`CallSite.param_map` emits, so positions line up."""
    return {name: i for i, name in enumerate(_ordered_params(fn))}


class ProgramBindingsCache:
    """name -> :class:`JitParams` visible inside a function: the
    module-level ``prog = jax.jit(f, ...)`` bindings plus the function's
    own local ones, cached at both levels. Shared by every rule that
    needs to know which names dispatch jit programs (JX008/JX009) — one
    implementation, one cache discipline."""

    def __init__(self):
        self._mod: Dict[str, Dict[str, JitParams]] = {}
        self._fn: Dict[FunctionInfo, Dict[str, JitParams]] = {}

    def bindings_for(self, fn: FunctionInfo, ctx,
                     graph: "CallGraph") -> Dict[str, JitParams]:
        got = self._fn.get(fn)
        if got is not None:
            return got
        mod = ctx.modules.get(fn.module_path)
        if fn.module_path not in self._mod:
            self._mod[fn.module_path] = (
                module_program_bindings(mod) if mod is not None else {})
        table = dict(self._mod[fn.module_path])
        collect_program_bindings(graph.index(fn).assigns, table)
        self._fn[fn] = table
        return table


@dataclass
class FunctionIndex:
    """One-walk node index for a function's own body, in SOURCE order.

    Transfer functions run many times per function during the fixpoint;
    anything that re-walks the AST per visit turns the engine quadratic
    in practice. Rules read these pre-collected lists instead."""

    calls: List[ast.Call] = field(default_factory=list)
    assigns: List[ast.Assign] = field(default_factory=list)
    returns: List[ast.Return] = field(default_factory=list)
    loops: List[ast.AST] = field(default_factory=list)
    branches: List[ast.AST] = field(default_factory=list)


class CallGraph:
    """Per-function call sites with resolved targets + reverse edges.

    Built once per analysis on top of the reachability pass's
    :class:`CallResolver`; both directions are needed — forward edges for
    transfer functions (a summary reads its callees'), reverse edges for
    the worklist (a changed summary re-queues its callers).
    """

    def __init__(self, modules: Dict[str, "object"],
                 resolver: Optional[CallResolver] = None):
        self.modules = modules
        self.resolver = resolver or CallResolver(modules)
        self.all_functions: List[FunctionInfo] = []
        self.callsites: Dict[FunctionInfo, List[CallSite]] = {}
        self.callers: Dict[FunctionInfo, Set[FunctionInfo]] = {}
        self._sites_map: Dict[FunctionInfo, Dict[int, CallSite]] = {}
        self._index: Dict[FunctionInfo, FunctionIndex] = {}
        for mod in modules.values():
            for fn in mod.functions:
                self.all_functions.append(fn)
                sites: List[CallSite] = []
                for node in iter_own_statements(fn.node):
                    if not isinstance(node, ast.Call):
                        continue
                    name = call_name(node)
                    if not name:
                        continue
                    targets = tuple(self.resolver.resolve(fn, name))
                    sites.append(CallSite(node, name, targets))
                    for t in targets:
                        self.callers.setdefault(t, set()).add(fn)
                self.callsites[fn] = sites

    def sites(self, fn: FunctionInfo) -> List[CallSite]:
        return self.callsites.get(fn, [])

    def sites_map(self, fn: FunctionInfo) -> Dict[int, CallSite]:
        """id(call node) -> CallSite, cached per function."""
        got = self._sites_map.get(fn)
        if got is None:
            got = {id(s.node): s for s in self.callsites.get(fn, [])}
            self._sites_map[fn] = got
        return got

    def index(self, fn: FunctionInfo) -> FunctionIndex:
        got = self._index.get(fn)
        if got is None:
            got = FunctionIndex()
            for node in own_nodes_in_order(fn.node):
                if isinstance(node, ast.Call):
                    got.calls.append(node)
                elif isinstance(node, ast.Assign):
                    got.assigns.append(node)
                elif isinstance(node, ast.AnnAssign) \
                        and node.value is not None:
                    # `y: jax.Array = ...` binds exactly like `y = ...`
                    got.assigns.append(node)
                elif isinstance(node, ast.Return):
                    got.returns.append(node)
                elif isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                    got.loops.append(node)
                    if isinstance(node, ast.While):
                        got.branches.append(node)
                elif isinstance(node, COMPREHENSION_NODES):
                    # comprehensions iterate too — `[prog(x, i) for i in
                    # ns]` recompiles exactly like the spelled-out loop
                    got.loops.append(node)
                elif isinstance(node, (ast.If, ast.IfExp)):
                    got.branches.append(node)
            self._index[fn] = got
        return got

    def callers_of(self, fn: FunctionInfo) -> Set[FunctionInfo]:
        return self.callers.get(fn, set())


# -- fixpoint engine ----------------------------------------------------------

class DataflowResult:
    """Converged per-rule function summaries, handed to rules via
    ``ctx.dataflow``."""

    def __init__(self, graph: Optional[CallGraph] = None):
        self.graph = graph
        self._summaries: Dict[str, Dict[FunctionInfo, object]] = {}

    def summary(self, analysis_id: str, fn: FunctionInfo, default=None):
        return self._summaries.get(analysis_id, {}).get(fn, default)

    def summaries(self, analysis_id: str) -> Dict[FunctionInfo, object]:
        return self._summaries.get(analysis_id, {})


def run_dataflow(graph: CallGraph, clients: Sequence["object"],
                 ctx, timings: Optional[Dict[str, float]] = None
                 ) -> DataflowResult:
    """Iterate every client's transfer function to a fixpoint.

    ``clients`` are :class:`DataflowRule` instances (duck-typed: need
    ``analysis_id``, ``initial``, ``transfer``, ``top``). Each client's
    facts converge independently — summaries of one rule never feed
    another's transfer, which keeps per-rule precision reasoning local.

    Propagation direction is per-client (``client.direction``):

    * ``"up"`` (the default): a summary reads its CALLEES' facts, so a
      change re-queues the function's callers — donated params, blocking
      helpers, collective reachability all flow bottom-up.
    * ``"down"``: the fact describes what the CALL CONTEXTS establish
      (JX011's locks-held-at-entry), so the transfer reads the callers'
      facts and a change re-queues the function's CALLEES.
    """
    import time as _time
    result = DataflowResult(graph)
    for client in clients:
        t0 = _time.perf_counter()
        down = getattr(client, "direction", "up") == "down"
        facts: Dict[FunctionInfo, object] = {}
        for fn in graph.all_functions:
            facts[fn] = client.initial(fn, graph, ctx)
        work = deque(graph.all_functions)
        queued = set(id(fn) for fn in graph.all_functions)
        visits: Dict[int, int] = {}
        while work:
            fn = work.popleft()
            queued.discard(id(fn))
            new = client.transfer(fn, facts, graph, ctx)
            if new == facts[fn]:
                continue
            visits[id(fn)] = visits.get(id(fn), 0) + 1
            if visits[id(fn)] > MAX_VISITS:
                new = client.top(fn, graph, ctx)   # hard widen: terminate
                if new == facts[fn]:
                    continue
            facts[fn] = new
            if down:
                requeue = {t for site in graph.sites(fn)
                           for t in site.targets}
            else:
                requeue = graph.callers_of(fn)
            for nxt in requeue:
                if id(nxt) not in queued:
                    queued.add(id(nxt))
                    work.append(nxt)
        result._summaries[client.analysis_id] = facts
        if timings is not None:
            timings[client.analysis_id] = (
                timings.get(client.analysis_id, 0.0)
                + _time.perf_counter() - t0)
    return result


def own_nodes_in_order(fn_node: ast.AST):
    """Every node of a function body in SOURCE order (DFS pre-order),
    without descending into nested function/class defs.

    :func:`~cycloneml_tpu.analysis.astutil.iter_own_statements` walks
    breadth-first — fine for the two-pass taint fixpoint, wrong for scans
    that track rebinding (``y = narrow(); y = wide(); use(y)`` must see
    the re-widening LAST)."""
    stack: List[ast.AST] = list(reversed(getattr(fn_node, "body", [])))
    while stack:
        node = stack.pop()
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(reversed(list(ast.iter_child_nodes(node))))


# -- shared jit-call parsing (used by JX008/JX009) ----------------------------

JIT_WRAPPERS = {"jit", "pjit"}
# program factories with NO static/donate semantics of their own: every
# argument position of the resulting program is a traced operand
TRACED_PROGRAM_FACTORIES = {"tree_aggregate_fn", "tree_aggregate_with_state"}


@dataclass(frozen=True)
class JitParams:
    """Compile-cache-relevant parameters parsed off a ``jax.jit(...)``
    call (or decorator). ``statics_known`` is False when a static/donate
    spec exists but is not a literal we can read — rules must then skip
    static-position reasoning rather than guess."""

    static_argnums: frozenset = EMPTY
    static_argnames: frozenset = EMPTY
    donate_argnums: frozenset = EMPTY
    statics_known: bool = True
    #: dotted name of the wrapped callable (``jax.jit(_kernel, ...)`` →
    #: ``"_kernel"``) when readable — lets rules map KEYWORD calls onto
    #: static_argnums positions via the wrapped signature
    wrapped: Optional[str] = None


def _literal_ints(node: ast.AST) -> Optional[frozenset]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
            else:
                return None
        return frozenset(out)
    return None


def _literal_strs(node: ast.AST) -> Optional[frozenset]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return frozenset({node.value})
    if isinstance(node, (ast.Tuple, ast.List)):
        out = set()
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, str):
                out.add(e.value)
            else:
                return None
        return frozenset(out)
    return None


def parse_jit_params(call: ast.Call) -> JitParams:
    """JitParams off a ``jax.jit(f, static_argnums=..., donate_argnums=...)``
    call node. Non-literal specs degrade to ``statics_known=False``."""
    statics: frozenset = EMPTY
    names: frozenset = EMPTY
    donate: frozenset = EMPTY
    known = True
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            got = _literal_ints(kw.value)
            statics, known = (got, known) if got is not None else (EMPTY,
                                                                   False)
        elif kw.arg == "static_argnames":
            got = _literal_strs(kw.value)
            names, known = (got, known) if got is not None else (EMPTY, False)
        elif kw.arg == "donate_argnums":
            got = _literal_ints(kw.value)
            donate, known = (got, known) if got is not None else (EMPTY,
                                                                  False)
    wrapped: Optional[str] = None
    if call.args:
        from cycloneml_tpu.analysis.astutil import dotted_name
        w = dotted_name(call.args[0])
        # decorator spellings (@jax.jit(...) / @partial(jax.jit, ...))
        # put the wrapper itself in args[0] — that is not the wrapped fn
        if w and last_component(w) not in JIT_WRAPPERS:
            wrapped = w
    return JitParams(statics, names, donate, known, wrapped)


def jit_params_of_function(fn: FunctionInfo) -> Optional[JitParams]:
    """JitParams for a jit-DECORATED function (``@jax.jit``,
    ``@partial(jax.jit, static_argnums=...)``), else None."""
    if not fn.is_jit_decorated:
        return None
    for dec in getattr(fn.node, "decorator_list", []):
        if not isinstance(dec, ast.Call):
            continue
        name = call_name(dec)
        base = last_component(name)
        if base in JIT_WRAPPERS:            # @jax.jit(static_argnums=...)
            return parse_jit_params(dec)
        if base == "partial" and dec.args:  # @partial(jax.jit, ...)
            from cycloneml_tpu.analysis.astutil import dotted_name
            inner = dotted_name(dec.args[0])
            if inner and last_component(inner) in JIT_WRAPPERS:
                return parse_jit_params(dec)
    return JitParams()                       # bare @jax.jit: no statics


def assign_targets(stmt: ast.AST) -> List[ast.AST]:
    """Targets of an ``Assign`` OR ``AnnAssign`` (annotated assignments
    bind exactly one target) — every source-order binding scan must see
    both spellings."""
    if isinstance(stmt, ast.AnnAssign):
        return [stmt.target]
    return list(getattr(stmt, "targets", []))


def collect_program_bindings(stmts, into: Optional[Dict[str, JitParams]]
                             = None) -> Dict[str, JitParams]:
    """Names bound to compiled programs in a statement sequence:
    ``prog = jax.jit(f, ...)`` / ``run = ds.tree_aggregate_fn(kernel)``.
    ``stmts`` is a module body or a function's own-statement iterator."""
    from cycloneml_tpu.analysis.astutil import assigned_names
    out = into if into is not None else {}
    for node in stmts:
        if not (isinstance(node, (ast.Assign, ast.AnnAssign))
                and isinstance(getattr(node, "value", None), ast.Call)):
            continue
        base = last_component(call_name(node.value))
        if base in JIT_WRAPPERS:
            params = parse_jit_params(node.value)
        elif base in TRACED_PROGRAM_FACTORIES or base == "tree_aggregate":
            params = JitParams()
        else:
            continue
        for t in assign_targets(node):
            for n in assigned_names(t):
                out[n] = params
    return out


def module_program_bindings(mod) -> Dict[str, JitParams]:
    """Program bindings at MODULE level (``_step = jax.jit(_update, ...)``),
    visible to every function in the module."""
    body = []
    for stmt in mod.tree.body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            continue
        body.append(stmt)
    return collect_program_bindings(body)
