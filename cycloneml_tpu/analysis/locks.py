"""Shared lock modeling for the concurrency rules (JX011–JX014).

Python threading code carries no annotations: a guard is just a ``with
self._lock:`` block, and which lock guards which field is a convention in
the author's head. This module recovers the convention syntactically, one
place for every concurrency rule to share:

* **Lock identity.** A lock is named by where it lives, abstracted over
  instances (the RacerD move): ``with self._cv:`` inside a ``ModelLane``
  method is the lock ``ModelLane._cv`` whatever instance holds it;
  ``_lock = threading.Lock()`` at module level is ``<module>::_lock``.
  Two instances of one class are conflated by design — the rules reason
  about the locking *discipline* of the class, not a heap.
* **Lock discovery.** A ``with`` block is a lock region when its context
  expression is a plain name/attribute that either (a) was observed being
  bound to a ``threading.Lock/RLock/Condition/Semaphore`` anywhere in the
  analyzed set, or (b) has a lock-ish name (``*lock*``, ``_cv``, ``cond``,
  ``mutex``). ``with tracer.span(...)`` and other call-shaped contexts are
  never locks.
* **Per-function regions.** :meth:`LockModel.info` walks a function once
  and records, in source order: every ``self.<field>`` access with the
  lockset held around it, every call with the lockset held around it, and
  every lock-``with`` with its enclosing lockset (the *acquisition edge*
  raw material). Cached per function — the dataflow fixpoint revisits
  functions many times and must not re-walk ASTs.

``Condition`` objects count as their underlying lock (``with self._cv:``
acquires it); ``cv.wait()`` *releasing* the lock while blocked is modeled
by the rules that care (JX014's wait-idiom exemption), not here.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from cycloneml_tpu.analysis.astutil import FunctionInfo, dotted_name

#: threading factories whose result is a lockable (``with``-able) object
LOCK_FACTORIES = {"Lock", "RLock", "Condition", "Semaphore",
                  "BoundedSemaphore"}

#: methods excluded from guard accounting: the object is under
#: construction/destruction and unpublished — no other thread can race it
#: (RacerD's ownership exclusion, in its cheapest form)
OWNERSHIP_METHODS = {"__init__", "__new__", "__post_init__", "__del__"}

FROZEN_EMPTY = frozenset()

_NESTED_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda,
                ast.ClassDef)


def lockish_name(name: str) -> bool:
    """Does ``name`` look like a lock field by convention alone?"""
    low = name.lower().lstrip("_")
    return ("lock" in low or "mutex" in low
            or low in ("cv", "cond", "mu", "sem")
            or low.endswith("_cv") or low.endswith("cond"))


@dataclass
class SelfAccess:
    """One ``self.<field>`` read or write inside a method, with the locks
    held lexically around it (entry locks are the dataflow layer's
    business — see JX011)."""

    field: str
    is_write: bool
    node: ast.AST
    fn: FunctionInfo
    locks: frozenset


@dataclass
class LockWith:
    """One lock acquisition: which lock, where, and what was already
    held when it was taken (a non-empty ``held`` makes it a nested
    acquisition — a lock-order edge). Covers both ``with lock:`` blocks
    and bare ``lock.acquire()`` calls; for the latter the held-region is
    unknown (no ``release()`` pairing is attempted) so only the
    acquisition EDGE is modeled, never an extended lockset."""

    lock: str
    node: ast.AST            # the With statement / the acquire() Call
    item_expr: ast.AST       # the context expression (for line anchoring)
    held: frozenset          # locks held when this one was acquired
    fn: FunctionInfo


@dataclass
class FnLocks:
    """One function's lock-relevant facts, collected in a single walk."""

    accesses: List[SelfAccess] = field(default_factory=list)
    withs: List[LockWith] = field(default_factory=list)
    #: id(Call node) -> locks held lexically around that call
    call_locks: Dict[int, frozenset] = field(default_factory=dict)
    #: every distinct lock this function acquires itself
    acquired: frozenset = FROZEN_EMPTY


class LockModel:
    """Lazily built, per-analysis-run view of the file set's locks.

    Construct one per rule instance per run (cheap); the expensive parts
    (per-function walks, the global lock-field scan) are cached inside.
    """

    def __init__(self, ctx):
        self.ctx = ctx
        self._fn: Dict[FunctionInfo, FnLocks] = {}
        self._fields: Optional[Dict[str, Dict[str, str]]] = None
        self._module_locks: Optional[Dict[str, Dict[str, str]]] = None

    # -- discovery -----------------------------------------------------------

    def _discover(self) -> None:
        """One pass over every module: ``self.<f> = threading.<Factory>()``
        assignments (per class) and module-level lock bindings."""
        fields: Dict[str, Dict[str, str]] = {}
        mod_locks: Dict[str, Dict[str, str]] = {}
        for path, mod in self.ctx.modules.items():
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Assign):
                    continue
                kind = _lock_factory_kind(node.value)
                if kind is None:
                    continue
                for tgt in node.targets:
                    tname = dotted_name(tgt)
                    if tname is None:
                        continue
                    parts = tname.split(".")
                    if parts[0] in ("self", "cls") and len(parts) == 2:
                        cls = _enclosing_class_of(mod, node)
                        if cls:
                            fields.setdefault(cls, {})[parts[1]] = kind
                    elif len(parts) == 1:
                        mod_locks.setdefault(path, {})[parts[0]] = kind
        self._fields = fields
        self._module_locks = mod_locks

    @property
    def lock_fields(self) -> Dict[str, Dict[str, str]]:
        if self._fields is None:
            self._discover()
        return self._fields

    @property
    def module_locks(self) -> Dict[str, Dict[str, str]]:
        if self._module_locks is None:
            self._discover()
        return self._module_locks

    def is_reentrant(self, lock_id: str) -> bool:
        """RLock-backed locks may be re-acquired by the holding thread —
        a self-edge on one is not a self-deadlock. Default-constructed
        ``Condition()`` wraps an RLock, so it is reentrant too."""
        cls_or_mod, _, tail = lock_id.partition("::")
        if tail:   # module-level lock
            kind = self.module_locks.get(cls_or_mod, {}).get(tail)
        else:
            cls, _, fld = lock_id.partition(".")
            kind = self.lock_fields.get(cls, {}).get(fld)
        return kind in ("RLock", "Condition")

    # -- lock identity -------------------------------------------------------

    def lock_id(self, expr: ast.AST, fn: FunctionInfo) -> Optional[str]:
        """Canonical lock name for a with-context expression, or None when
        the expression is not (recognizably) a lock."""
        name = dotted_name(expr)
        if name is None:
            return None
        parts = name.split(".")
        if parts[0] in ("self", "cls") and fn.class_name:
            fields = self.lock_fields.get(fn.class_name, {})
            fld = parts[1] if len(parts) == 2 else parts[-1]
            if (len(parts) == 2 and parts[1] in fields) or lockish_name(fld):
                return f"{fn.class_name}.{'.'.join(parts[1:])}"
            return None
        if len(parts) == 1:
            known = self.module_locks.get(fn.module_path, {})
            if parts[0] in known or lockish_name(parts[0]):
                return f"{fn.module_path}::{parts[0]}"
            return None
        # foreign chain (s._lock where s is a local): keep it scoped to the
        # observing class/module — a distinct node, never unified across
        # classes (type inference is out of scope; call summaries unify
        # the common acquire-via-method pattern instead)
        if lockish_name(parts[-1]):
            scope = fn.class_name or fn.module_path
            return f"{scope}.{name}"
        return None

    # -- per-function walk ---------------------------------------------------

    def info(self, fn: FunctionInfo) -> FnLocks:
        got = self._fn.get(fn)
        if got is not None:
            return got
        out = FnLocks()
        acquired = set()
        self._walk(getattr(fn.node, "body", []), FROZEN_EMPTY, fn, out,
                   acquired)
        out.acquired = frozenset(acquired)
        self._fn[fn] = out
        return out

    def _walk(self, body, held: frozenset, fn: FunctionInfo,
              out: FnLocks, acquired: set) -> None:
        for stmt in body:
            self._walk_node(stmt, held, fn, out, acquired)

    def _walk_node(self, node: ast.AST, held: frozenset, fn: FunctionInfo,
                   out: FnLocks, acquired: set) -> None:
        if isinstance(node, _NESTED_DEFS):
            return
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held
            for item in node.items:
                # the context expression evaluates under the OUTER lockset
                self._walk_node(item.context_expr, inner, fn, out, acquired)
                lid = self.lock_id(item.context_expr, fn)
                if lid is not None:
                    out.withs.append(LockWith(lid, node, item.context_expr,
                                              inner, fn))
                    acquired.add(lid)
                    inner = inner | {lid}
                if item.optional_vars is not None:
                    self._walk_node(item.optional_vars, inner, fn, out,
                                    acquired)
            self._walk(node.body, inner, fn, out, acquired)
            return
        if isinstance(node, ast.Call):
            out.call_locks[id(node)] = held
            if (isinstance(node.func, ast.Attribute)
                    and node.func.attr == "acquire"):
                # bare `lock.acquire()` is an acquisition edge too —
                # `with A: A.acquire()` is the guaranteed self-deadlock
                # a with-only model would miss
                lid = self.lock_id(node.func.value, fn)
                if lid is not None:
                    out.withs.append(LockWith(lid, node, node.func.value,
                                              held, fn))
                    acquired.add(lid)
        elif (isinstance(node, ast.Subscript)
              and isinstance(node.ctx, (ast.Store, ast.Del))
              and isinstance(node.value, ast.Attribute)
              and isinstance(node.value.value, ast.Name)
              and node.value.value.id == "self"):
            # `self._data[k] = v` MUTATES the field: a write for guard
            # inference, though the attribute itself is only loaded
            out.accesses.append(SelfAccess(
                node.value.attr, True, node.value, fn, held))
            self._walk_node(node.slice, held, fn, out, acquired)
            return
        elif (isinstance(node, ast.Attribute)
              and isinstance(node.value, ast.Name)
              and node.value.id == "self"):
            out.accesses.append(SelfAccess(
                node.attr, isinstance(node.ctx, (ast.Store, ast.Del)),
                node, fn, held))
        for child in ast.iter_child_nodes(node):
            self._walk_node(child, held, fn, out, acquired)


def model_for(ctx) -> LockModel:
    """The shared per-run LockModel, cached on the AnalysisContext: three
    concurrency rules read the same lock regions — walking 170+ modules'
    functions once per RULE would triple the lint's lock-analysis cost."""
    model = getattr(ctx, "_lock_model", None)
    if model is None or model.ctx is not ctx:
        model = LockModel(ctx)
        ctx._lock_model = model
    return model


def pretty_lock(lock_id: str) -> str:
    """Human form of a lock id: `Class.field` stays as-is (the class
    matters — it may not be the reader's), module locks render as
    `file.py:name`."""
    cls_or_mod, _, tail = lock_id.partition("::")
    if tail:
        return f"{cls_or_mod.rsplit('/', 1)[-1]}:{tail}"
    return lock_id


def _lock_factory_kind(value: ast.AST) -> Optional[str]:
    """'Lock' / 'RLock' / 'Condition' / ... when ``value`` is a
    ``threading.<Factory>()`` call, else None."""
    if not isinstance(value, ast.Call):
        return None
    name = dotted_name(value.func)
    if name is None:
        return None
    parts = name.split(".")
    if parts[-1] in LOCK_FACTORIES and (len(parts) == 1
                                        or parts[0] in ("threading", "th")):
        return parts[-1]
    return None


def _enclosing_class_of(mod, node: ast.AST) -> Optional[str]:
    """The innermost class whose span contains ``node`` (line-range based:
    cheap and good enough for lock-field discovery)."""
    line = getattr(node, "lineno", None)
    if line is None:
        return None
    best: Optional[Tuple[int, str]] = None
    for cand in ast.walk(mod.tree):
        if not isinstance(cand, ast.ClassDef):
            continue
        c0, c1 = cand.lineno, getattr(cand, "end_lineno", cand.lineno)
        if c0 <= line <= c1 and (best is None or c1 - c0 < best[0]):
            best = (c1 - c0, cand.name)
    return best[1] if best else None
