"""Incremental (``--changed``) analysis: git-scoped reporting + parse cache.

The interprocedural engine needs the WHOLE file set — a changed wrapper
can create a hazard whose finding lands in an unchanged caller, and the
call graph/reachability/dataflow passes are only correct globally. What
``--changed`` narrows is the expensive part: per-module rule checks run
(and findings are reported) only for files touched per ``git diff``,
while parsing reuses a pickled module cache keyed on content hash. Net:
the lint gate's cost tracks the size of the CHANGE, not the repo.

The cache stores fully parsed :class:`~.engine.ModuleInfo` objects
(AST + function table + suppressions). Reachability mutates
``FunctionInfo.jit_reachable`` in place, so cached entries are reset on
reuse — the flags are a per-run verdict, not a parse artifact. Any cache
trouble (version skew, pickle errors, truncation) falls back to a fresh
parse; the cache is an accelerator, never a correctness dependency.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import subprocess
from typing import Dict, List, Optional, Sequence, Set

from cycloneml_tpu.analysis.engine import ModuleInfo, load_module

CACHE_VERSION = 4   # bump when ModuleInfo/FunctionInfo shape changes
# (v4: JX020-JX023 summary schemas — JXFAULT reachability + JX022
# teardown-param sets joined the pickled per-module fact surface)
DEFAULT_CACHE = ".graftlint-cache.pkl"


def summary_schema() -> str:
    """Fingerprint of the fact kinds the current rule pack derives from a
    parsed module: every dataflow analysis id, sorted. Cached modules are
    only parse artifacts — summaries are recomputed per run — but a
    cache written by an OLDER analyzer may predate fields the NEWER
    fact extraction reads off ``ModuleInfo``/``FunctionInfo`` (v3's
    lockset/acquisition/obligation kinds); keying the cache on the
    schema makes that impossible by construction instead of by audit."""
    from cycloneml_tpu.analysis.rules import ALL_RULES
    from cycloneml_tpu.analysis.rules.base import DataflowRule
    ids = sorted(cls.rule_id for cls in ALL_RULES
                 if issubclass(cls, DataflowRule))
    return ",".join(ids)


def git_toplevel(cwd: Optional[str] = None) -> Optional[str]:
    """The repo root per ``git rev-parse --show-toplevel``; None when git
    (or a repo) is unavailable."""
    try:
        proc = subprocess.run(
            ["git", "rev-parse", "--show-toplevel"], cwd=cwd,
            capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    out = proc.stdout.strip()
    return out or None


def git_changed_files(base: Optional[str] = None,
                      cwd: Optional[str] = None) -> Optional[Set[str]]:
    """ABSOLUTE paths of changed ``.py`` files: worktree + index changes
    against HEAD (or ``base...HEAD`` when a base ref is given) plus
    untracked files. git emits repo-root-relative names whatever
    directory it runs from, so they are resolved against ``git rev-parse
    --show-toplevel`` — NOT the process cwd, which may be a subdirectory.
    None when git is unavailable — the caller must fall back to a full
    run, not silently lint nothing. Raises ``ValueError`` when git works
    but ``base`` is not a resolvable ref (a typo, or a path mistaken for
    the BASE argument) — that is a usage error, not a fallback case."""
    def run(*args: str) -> Optional[List[str]]:
        try:
            proc = subprocess.run(
                ["git", *args], cwd=cwd, capture_output=True, text=True,
                timeout=30)
        except (OSError, subprocess.TimeoutExpired):
            return None
        if proc.returncode != 0:
            return None
        return [ln.strip() for ln in proc.stdout.splitlines() if ln.strip()]

    top = run("rev-parse", "--show-toplevel")
    if not top:
        return None
    root = top[0]
    if base and run("rev-parse", "--verify", "--quiet",
                    f"{base}^{{commit}}") is None:
        hint = (" (it names a path — analyzed paths are positional "
                "arguments, BASE is a git ref)" if os.path.exists(base)
                else "")
        raise ValueError(f"--changed: {base!r} is not a git ref{hint}")
    out: Set[str] = set()
    diffs = run("diff", "--name-only", "HEAD")
    if diffs is None:
        return None
    out.update(diffs)
    if base:
        merged = run("diff", "--name-only", f"{base}...HEAD")
        if merged is None:
            return None
        out.update(merged)
    untracked = run("ls-files", "--others", "--exclude-standard")
    if untracked is not None:
        out.update(untracked)
    return {os.path.join(root, p) for p in out if p.endswith(".py")}


class ParseCache:
    """Content-hash-keyed pickle cache of parsed modules."""

    def __init__(self, path: str):
        self.path = path
        self._entries: Dict[str, tuple] = {}   # rel -> (sha, ModuleInfo)
        self._dirty = False
        self.hits = 0
        self.misses = 0
        self._load()

    def _load(self) -> None:
        try:
            with open(self.path, "rb") as fh:
                payload = pickle.load(fh)
            if payload.get("version") == CACHE_VERSION \
                    and payload.get("schema") == summary_schema():
                self._entries = payload.get("modules", {})
        except (OSError, pickle.PickleError, EOFError, AttributeError,
                KeyError, ValueError, ImportError):
            # ImportError: a refactor moved/renamed a pickled class out
            # from under a stale cache — fall back to a fresh parse, the
            # cache is an accelerator, never a correctness dependency
            self._entries = {}

    def save(self) -> None:
        if not self._dirty:
            return
        try:
            tmp = f"{self.path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as fh:
                pickle.dump({"version": CACHE_VERSION,
                             "schema": summary_schema(),
                             "modules": self._entries}, fh,
                            protocol=pickle.HIGHEST_PROTOCOL)
            os.replace(tmp, self.path)
        except (OSError, pickle.PickleError, RecursionError):
            pass   # cache write failure never fails the lint

    def load_module(self, path: str, rel: str) -> Optional[ModuleInfo]:
        """Drop-in for :func:`~.engine.load_module` with cache reuse."""
        try:
            with open(path, "rb") as fh:
                digest = hashlib.sha256(fh.read()).hexdigest()
        except OSError:
            return None
        hit = self._entries.get(rel)
        if hit is not None and hit[0] == digest:
            self.hits += 1
            mod = hit[1]
            for fn in mod.functions:
                # per-run verdicts, recomputed by the reachability pass
                fn.jit_reachable = False
                fn.passed_to_tracer = False
            return mod
        self.misses += 1
        mod = load_module(path, rel)
        if mod is not None:
            self._entries[rel] = (digest, mod)
            self._dirty = True
        return mod


def changed_report_set(paths: Sequence[str],
                       changed: Set[str]) -> Set[str]:
    """Map changed files (ABSOLUTE paths, from :func:`git_changed_files`)
    onto the engine's module-path convention (relative to the parent of
    each analyzed root). Only files at or under an analyzed root match:
    the roots scope the gate — a changed file elsewhere in the repo is
    not part of this lint run and must not inflate its file count."""
    out: Set[str] = set()
    roots = [os.path.realpath(p) for p in paths]
    for ch in changed:
        ach = os.path.realpath(ch)
        for root in roots:
            r = root.rstrip(os.sep)
            if ach == r or ach.startswith(r + os.sep):
                base = os.path.dirname(r)
                out.add(os.path.relpath(ach, base).replace(os.sep, "/"))
    return out
