"""graftlint engine: file walker, rule registry, suppressions, findings.

The engine parses every ``.py`` file under the given paths once, runs the
jit-reachability pass over the whole file set (rules need cross-module
call-graph context), then applies each registered rule per module.
Findings carry a stable fingerprint ``(rule, path, function)`` so the
committed baseline survives line-number churn.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

from cycloneml_tpu.analysis.astutil import collect_suppressions
from cycloneml_tpu.analysis.reachability import (FunctionInfo,
                                                 ModuleFunctions,
                                                 compute_reachability)

DEFAULT_AXES = ("data", "replica", "model")


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative posix path
    line: int
    col: int
    message: str
    function: str = ""   # enclosing function qualname ("" = module level)
    end_line: int = 0    # last physical line of the flagged statement
                         # (0 = single-line; suppressions match the extent)
    start_line: int = 0  # FIRST physical line of the flagged statement —
                         # the finding may anchor on an inner expression
                         # lines below it (0 = same as `line`)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.function}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "end_line": max(self.end_line, self.line),
                "function": self.function, "message": self.message}


@dataclass
class ModuleInfo:
    path: str
    tree: ast.Module
    source_lines: List[str]
    mf: ModuleFunctions
    functions: List[FunctionInfo] = field(default_factory=list)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    has_x64_guard: bool = False


DEFAULT_AXIS_CONSTANTS = {"DATA_AXIS": "data", "REPLICA_AXIS": "replica",
                          "MODEL_AXIS": "model"}


@dataclass
class AnalysisContext:
    """Cross-module state every rule receives."""

    modules: Dict[str, ModuleInfo]
    valid_axes: Sequence[str] = DEFAULT_AXES
    # names of module-level constants that hold a valid axis name
    axis_constant_names: Set[str] = field(default_factory=set)
    # constant name -> axis value (DATA_AXIS -> "data"): the abstract
    # interpreter resolves P((REPLICA_AXIS, DATA_AXIS)) specs through it
    axis_constants: Dict[str, str] = field(default_factory=dict)
    # interprocedural layer (set by analyze_paths): the resolved call
    # graph and the converged DataflowRule summaries
    callgraph: Optional[object] = None
    dataflow: Optional[object] = None


def _discover_axes(modules: Dict[str, ModuleInfo]):
    """Pull the declared mesh axis names out of ``mesh.py`` if it is part
    of the analyzed set: module-level ``X_AXIS = "name"`` assignments.
    Returns (axis values, constant names, constant->value mapping)."""
    axes: List[str] = []
    names: Set[str] = set()
    mapping: Dict[str, str] = {}
    for path, mod in modules.items():
        if os.path.basename(path) != "mesh.py":
            continue
        for stmt in mod.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id.endswith("_AXIS")
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                axes.append(stmt.value.value)
                names.add(stmt.targets[0].id)
                mapping[stmt.targets[0].id] = stmt.value.value
    return (tuple(axes) if axes else DEFAULT_AXES,
            names or set(DEFAULT_AXIS_CONSTANTS),
            mapping or dict(DEFAULT_AXIS_CONSTANTS))


def load_module(path: str, rel: str) -> Optional[ModuleInfo]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except (SyntaxError, UnicodeDecodeError, OSError):
        return None
    lines = source.splitlines()
    mf = ModuleFunctions(rel, tree)
    return ModuleInfo(
        path=rel, tree=tree, source_lines=lines, mf=mf,
        functions=mf.functions,
        suppressions=collect_suppressions(lines),
        has_x64_guard=("jax_enable_x64" in source or "enable_x64" in source))


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return out


def _relpath(path: str, roots: Sequence[str]) -> str:
    """Repo-relative stable path: relative to the parent of the analyzed
    root so ``cycloneml_tpu/ml/...`` stays stable wherever the CLI runs."""
    ap = os.path.abspath(path)
    for r in roots:
        base = os.path.dirname(os.path.abspath(r).rstrip(os.sep))
        if ap.startswith(base + os.sep):
            return os.path.relpath(ap, base).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def _is_suppressed(mod: ModuleInfo, finding: Finding) -> bool:
    """Inline suppressions match the WHOLE statement extent: a
    ``# graftlint: disable=RULE`` on any physical line of a multi-line
    call covers a finding anchored to the statement's first line."""
    end = max(finding.end_line, finding.line)
    start = min(finding.start_line or finding.line, finding.line)
    # a directive on the line ABOVE the statement already projects onto
    # the statement's first line via collect_suppressions' own-line
    # handling
    for ln in range(start, end + 1):
        sup = mod.suppressions.get(ln)
        if sup and (finding.rule in sup or "ALL" in sup):
            return True
    return False


def analyze_paths(paths: Sequence[str], rules=None,
                  valid_axes: Optional[Sequence[str]] = None,
                  only_paths: Optional[Set[str]] = None,
                  module_loader=None,
                  timings: Optional[Dict[str, float]] = None
                  ) -> List[Finding]:
    """Run the rule pack over ``paths`` (files or directories).

    Returns findings AFTER inline-suppression filtering, sorted by
    (path, line). Baseline filtering is the caller's business
    (:mod:`.baseline`) so reporters can show both views.

    ``only_paths`` (repo-relative posix paths) restricts which modules
    the rules CHECK — the parse, reachability, call-graph, and dataflow
    passes still cover the full file set so interprocedural facts stay
    correct (incremental ``--changed`` mode). The check set is widened
    over REVERSE call edges: a change in a callee can create findings in
    its (transitive) callers — `advance()` growing `donate_argnums`
    makes an untouched caller's `state.sum()` a use-after-donate — so
    those callers' modules are checked too, keeping the incremental gate
    as strict as the full one. ``module_loader`` replaces
    :func:`load_module` (the parse cache hook); it must accept the same
    ``(path, rel)`` signature.

    ``timings``, when a dict is passed, is filled with per-rule wall
    time in seconds: one entry per rule id (its ``check()`` over every
    module, plus its dataflow fixpoint when it owns one) and one entry
    per SHARED dataflow analysis (``JXSHAPE``, the abstract shape
    domain serving JX015–JX018) — rule authors see their cost on every
    ``--json`` run.
    """
    import time as _time
    if rules is None:
        from cycloneml_tpu.analysis.rules import default_rules
        rules = default_rules()
    loader = module_loader or load_module

    modules: Dict[str, ModuleInfo] = {}
    for f in collect_files(paths):
        mod = loader(f, _relpath(f, paths))
        if mod is not None:
            modules[mod.path] = mod

    from cycloneml_tpu.analysis.dataflow import CallGraph, run_dataflow
    from cycloneml_tpu.analysis.reachability import CallResolver
    resolver = CallResolver(modules)
    compute_reachability(modules, resolver)
    graph = CallGraph(modules, resolver)

    axes, axis_names, axis_map = _discover_axes(modules)
    ctx = AnalysisContext(
        modules=modules,
        valid_axes=tuple(valid_axes) if valid_axes is not None else axes,
        axis_constant_names=axis_names,
        axis_constants=axis_map,
        callgraph=graph)

    from cycloneml_tpu.analysis.rules.base import DataflowRule
    # rules may SHARE one dataflow analysis (the JX015-018 shape rules
    # all read the JXSHAPE summaries) — dedupe by analysis_id so the
    # shared fixpoint runs once, not once per rule
    clients, seen_ids = [], set()
    for r in rules:
        if isinstance(r, DataflowRule) and r.analysis_id not in seen_ids:
            seen_ids.add(r.analysis_id)
            clients.append(r)
    dataflow_timings: Dict[str, float] = {}
    ctx.dataflow = run_dataflow(graph, clients, ctx,
                                timings=dataflow_timings)

    check_paths: Optional[Set[str]] = None
    if only_paths is not None:
        from collections import deque
        check_paths = set(only_paths)
        seed = [fn for path in only_paths if path in modules
                for fn in modules[path].functions]
        work = deque(seed)
        seen = {id(fn) for fn in seed}
        while work:
            fn = work.popleft()
            for caller in graph.callers_of(fn):
                if id(caller) in seen:
                    continue
                seen.add(id(caller))
                check_paths.add(caller.module_path)
                work.append(caller)

    findings: List[Finding] = []
    rule_seconds: Dict[str, float] = {r.rule_id: 0.0 for r in rules}
    for mod in modules.values():
        if check_paths is not None and mod.path not in check_paths:
            continue
        for rule in rules:
            credit0 = dict(getattr(ctx, "shared_time_credit", None) or {})
            t0 = _time.perf_counter()
            for finding in rule.check(mod, ctx):
                if _is_suppressed(mod, finding):
                    continue
                findings.append(finding)
            elapsed = _time.perf_counter() - t0
            # shared lazily-built analyses (the JXSHAPE check-time
            # interpretation) record what they cost inside a check via
            # ctx.shared_time_credit — re-attribute that to the shared
            # analysis, not to whichever rule happened to touch the
            # cache first
            credit1 = getattr(ctx, "shared_time_credit", None) or {}
            for key, total in credit1.items():
                delta = total - credit0.get(key, 0.0)
                if delta > 0:
                    rule_seconds[key] = rule_seconds.get(key, 0.0) + delta
                    elapsed -= delta
            rule_seconds[rule.rule_id] += max(elapsed, 0.0)
    if timings is not None:
        # a rule that owns its analysis folds the fixpoint into its
        # total; shared analyses (JXSHAPE) get their own entry
        for aid, secs in dataflow_timings.items():
            rule_seconds[aid] = rule_seconds.get(aid, 0.0) + secs
        timings.update({k: round(v, 4) for k, v in rule_seconds.items()})
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
