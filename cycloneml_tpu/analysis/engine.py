"""graftlint engine: file walker, rule registry, suppressions, findings.

The engine parses every ``.py`` file under the given paths once, runs the
jit-reachability pass over the whole file set (rules need cross-module
call-graph context), then applies each registered rule per module.
Findings carry a stable fingerprint ``(rule, path, function)`` so the
committed baseline survives line-number churn.
"""

from __future__ import annotations

import ast
import os
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set

from cycloneml_tpu.analysis.astutil import collect_suppressions
from cycloneml_tpu.analysis.reachability import (FunctionInfo,
                                                 ModuleFunctions,
                                                 compute_reachability)

DEFAULT_AXES = ("data", "replica", "model")


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative posix path
    line: int
    col: int
    message: str
    function: str = ""   # enclosing function qualname ("" = module level)

    @property
    def fingerprint(self) -> str:
        return f"{self.rule}:{self.path}:{self.function}"

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "function": self.function,
                "message": self.message}


@dataclass
class ModuleInfo:
    path: str
    tree: ast.Module
    source_lines: List[str]
    mf: ModuleFunctions
    functions: List[FunctionInfo] = field(default_factory=list)
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    has_x64_guard: bool = False


@dataclass
class AnalysisContext:
    """Cross-module state every rule receives."""

    modules: Dict[str, ModuleInfo]
    valid_axes: Sequence[str] = DEFAULT_AXES
    # names of module-level constants that hold a valid axis name
    axis_constant_names: Set[str] = field(default_factory=set)


def _discover_axes(modules: Dict[str, ModuleInfo]):
    """Pull the declared mesh axis names out of ``mesh.py`` if it is part
    of the analyzed set: module-level ``X_AXIS = "name"`` assignments."""
    axes: List[str] = []
    names: Set[str] = set()
    for path, mod in modules.items():
        if os.path.basename(path) != "mesh.py":
            continue
        for stmt in mod.tree.body:
            if (isinstance(stmt, ast.Assign) and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                    and stmt.targets[0].id.endswith("_AXIS")
                    and isinstance(stmt.value, ast.Constant)
                    and isinstance(stmt.value.value, str)):
                axes.append(stmt.value.value)
                names.add(stmt.targets[0].id)
    return (tuple(axes) if axes else DEFAULT_AXES,
            names or {"DATA_AXIS", "REPLICA_AXIS", "MODEL_AXIS"})


def load_module(path: str, rel: str) -> Optional[ModuleInfo]:
    try:
        with open(path, "r", encoding="utf-8") as fh:
            source = fh.read()
        tree = ast.parse(source, filename=path)
    except (SyntaxError, UnicodeDecodeError, OSError):
        return None
    lines = source.splitlines()
    mf = ModuleFunctions(rel, tree)
    return ModuleInfo(
        path=rel, tree=tree, source_lines=lines, mf=mf,
        functions=mf.functions,
        suppressions=collect_suppressions(lines),
        has_x64_guard=("jax_enable_x64" in source or "enable_x64" in source))


def collect_files(paths: Sequence[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            out.append(p)
        elif os.path.isdir(p):
            for root, dirs, files in os.walk(p):
                dirs[:] = sorted(d for d in dirs
                                 if d not in ("__pycache__", ".git"))
                for f in sorted(files):
                    if f.endswith(".py"):
                        out.append(os.path.join(root, f))
    return out


def _relpath(path: str, roots: Sequence[str]) -> str:
    """Repo-relative stable path: relative to the parent of the analyzed
    root so ``cycloneml_tpu/ml/...`` stays stable wherever the CLI runs."""
    ap = os.path.abspath(path)
    for r in roots:
        base = os.path.dirname(os.path.abspath(r).rstrip(os.sep))
        if ap.startswith(base + os.sep):
            return os.path.relpath(ap, base).replace(os.sep, "/")
    return path.replace(os.sep, "/")


def analyze_paths(paths: Sequence[str], rules=None,
                  valid_axes: Optional[Sequence[str]] = None) -> List[Finding]:
    """Run the rule pack over ``paths`` (files or directories).

    Returns findings AFTER inline-suppression filtering, sorted by
    (path, line). Baseline filtering is the caller's business
    (:mod:`.baseline`) so reporters can show both views.
    """
    if rules is None:
        from cycloneml_tpu.analysis.rules import default_rules
        rules = default_rules()

    modules: Dict[str, ModuleInfo] = {}
    for f in collect_files(paths):
        mod = load_module(f, _relpath(f, paths))
        if mod is not None:
            modules[mod.path] = mod
    compute_reachability(modules)

    axes, axis_names = _discover_axes(modules)
    ctx = AnalysisContext(
        modules=modules,
        valid_axes=tuple(valid_axes) if valid_axes is not None else axes,
        axis_constant_names=axis_names)

    findings: List[Finding] = []
    for mod in modules.values():
        for rule in rules:
            for finding in rule.check(mod, ctx):
                suppressed = mod.suppressions.get(finding.line, set())
                if finding.rule in suppressed or "ALL" in suppressed:
                    continue
                findings.append(finding)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
