"""Abstract shape & sharding interpretation — the SPMD array-fact domain.

GSPMD (Xu et al., PAPERS.md) treats sharding as a *propagatable dataflow
fact*; Cousot-style abstract interpretation is the classic machinery for
propagating such facts soundly. This module is that machinery for
graftlint: a small abstract domain of array facts evaluated over each
function body in source order, summarized per function, and propagated
through the PR-7 call graph by the dataflow engine. Four rules consume
it (JX015 sharding-spec consistency, JX016 shape/padding hazards, JX017
cross-mesh program reuse, JX018 unbounded host materialization).

The domain — one :class:`AArray` per abstract value:

* **symbolic dims** (:class:`Sym`): sizes read off ``.shape`` become
  interned symbols (``n, d = x.shape`` names ``x``'s dims), concrete
  ints stay concrete, anything else is ``TOP``. Equality of symbols is
  identity — two reads of the same array's axis 0 agree, two different
  arrays' dims never do (sound for mismatch detection: only *provable*
  conflicts — unequal concrete ints — are reported).
* **dtype tier**: ``narrow`` (bf16/f16 storage) / ``accum`` (fp32/f64)
  / TOP, reusing JX004's classification of cast targets. The tier rides
  along so shape rules and the JX004 dataflow client share one notion
  of the data/accumulator boundary.
* **sharding state**: ``psummed`` — the set of mesh axes a value has
  been reduced over (``psum``/``pmean``/``psum_over_mesh``); a psummed
  value is replicated over those axes *by construction*, which is
  exactly what JX015's out_spec check needs. Joins take the
  intersection (must-analysis: an axis counts only when every path
  reduced over it).
* **mesh-identity token**: program values (``tree_aggregate`` /
  ``shard_map`` results) are tracked with an abstract mesh *epoch*;
  rebuild events (``mesh.reset`` / ``rebuild_mesh`` / a callee whose
  summary rebuilds) advance the epoch, and JX017 flags dispatch of a
  program built under an older epoch.
* **padding** (``padded``): dim indices that carry padding — from
  ``jnp.pad``/``np.pad``, the bucket idiom (``buf = np.zeros((bucket,
  d)); buf[:k] = rows``) and ``.at[:k].set(rows)``. Slicing the dim
  back down (``buf[:k]``) removes the mark.
* **param roots** (``roots``): which of the function's parameters a
  value derives from through shape-preserving ops — the carrier for
  interprocedural facts ("this callee takes an unmasked mean over
  param 2's dim 0", "this helper hands param 0 to ``np.asarray``").

Transfer functions cover the jnp/lax surface the repo actually uses:
constructors, elementwise broadcasting (with concrete-dim conflict
events), matmul/dot, reductions (mean/average recorded as events with
their axes), reshape/transpose/indexing, ``jnp.pad``, ``.astype``,
``.at[...].set``, the psum family, ``shard_map``/``shard_map_compat``
spec bindings (:class:`SpecVal` parses ``P(...)`` literals, resolving
axis constants discovered from ``mesh.py``), the ``tree_aggregate``
builder family, and host materializers (``jax.device_get`` /
``np.asarray`` / ``.tolist``).

One dataflow client (:data:`ANALYSIS_ID` = ``"JXSHAPE"``) serves all
four rules: the engine dedupes clients by ``analysis_id``, so the
fixpoint runs once and each rule reads the converged
:class:`ShapeSummary` facts. Per-function interpretation is gated by a
cheap relevance scan (functions whose own calls touch none of the
interesting surfaces and whose callees all have empty summaries get
:data:`EMPTY_SUMMARY` without a walk) — the full self-run stays within
the lint wall-time budget.

Degradation discipline: facts that *trigger findings* (psummed axes,
mean/materialize param sets) widen toward silence; facts that only
*propagate* (returns_program, rebuilds, reaches_aggregate) widen toward
``True`` so the fixpoint terminates. A wrong summary therefore costs
recall, never precision.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field, replace
from typing import Dict, FrozenSet, List, Optional, Sequence, Set, Tuple

from cycloneml_tpu.analysis.astutil import (FunctionInfo, assigned_names,
                                            call_name, dotted_name,
                                            last_component)
from cycloneml_tpu.analysis.dataflow import (TOP, _ordered_params,
                                             assign_targets)

ANALYSIS_ID = "JXSHAPE"

ACCUM_STRINGS = {"float32", "f32", "float64", "f64"}
ACCUM_DOTTED = {"jnp.float32", "jax.numpy.float32", "np.float32",
                "numpy.float32", "jnp.float64", "jax.numpy.float64",
                "np.float64", "numpy.float64"}

TIER_NARROW, TIER_ACCUM = "narrow", "accum"

# -- call surfaces ------------------------------------------------------------

CONSTRUCTORS = {"zeros", "ones", "empty", "full", "zeros_like", "ones_like",
                "empty_like", "full_like"}
ZERO_ORIGIN = {"zeros", "empty", "zeros_like", "empty_like"}
REDUCERS = {"sum", "max", "min", "prod", "std", "var", "median", "nansum",
            "amax", "amin", "nanmax", "nanmin", "count_nonzero", "all", "any"}
MEAN_CALLS = {"mean", "average", "nanmean"}
PSUM_CALLS = {"psum", "pmean", "pmax", "pmin"}
MATMUL_CALLS = {"dot", "matmul", "vdot"}
ELEMWISE_PREFIXES = ("jnp.", "jax.numpy.", "jax.nn.", "jax.lax.", "lax.",
                    "jax.scipy.", "np.", "numpy.")

# program builders: results are SPMD programs bound to the mesh they were
# built under (the dispatch boundary JX017 polices)
PROGRAM_BUILDERS = {"tree_aggregate", "tree_aggregate_with_state",
                    "tree_aggregate_fn", "shard_map_compat", "shard_map"}
SHARD_MAP_CALLS = {"shard_map", "shard_map_compat"}
AGGREGATE_CALLS = {"tree_aggregate", "tree_aggregate_with_state",
                   "all_gather_hosts", "psum", "pmean", "psum_over_mesh"}

# mesh-rebuild surfaces: the events that invalidate every program built
# under the previous mesh (MeshSupervisor.recover reaches rebuild_mesh
# transitively; `mesh.reset()` is the module-level teardown)
REBUILD_LAST = {"rebuild_mesh"}
REBUILD_DOTTED = {"mesh.reset"}

# host materializers: the full-array device->host sinks JX018 polices
# (jnp.asarray is device-side and NOT one of these)
MATERIALIZER_DOTTED = {"jax.device_get", "device_get"}
NP_MATERIALIZER_LAST = {"asarray", "array"}

# names whose `.shape` unpack binds the dataset row dim (the out-of-core
# scale dim; heuristic complement to the sharded-aggregate-operand rule)
DATASET_DIM_NAMES = {"n", "n_rows", "num_rows", "n_samples", "n_pad"}

_INTERESTING_LAST = (CONSTRUCTORS | MEAN_CALLS | PSUM_CALLS
                     | PROGRAM_BUILDERS | REBUILD_LAST | {"psum_over_mesh"}
                     | {"pad", "tolist", "device_get", "asarray", "array",
                        "all_gather_hosts", "reset"})


# -- dims ---------------------------------------------------------------------

@dataclass(frozen=True)
class Sym:
    """One symbolic dimension. Identity (uid) is equality; the label is
    for messages only (`n`, `x@0`)."""

    uid: int
    label: str

    def __repr__(self):
        return self.label


def dims_equal(a, b) -> bool:
    return a is not TOP and b is not TOP and a == b


def join_dim(a, b):
    return a if dims_equal(a, b) else TOP


# -- abstract values ----------------------------------------------------------

_EMPTY: FrozenSet = frozenset()


@dataclass(frozen=True)
class AArray:
    """Abstract array fact: shape x tier x sharding x provenance."""

    shape: object = TOP                 # tuple[Dim,...] | TOP
    dim0: object = None                 # known leading dim when shape is TOP
    tier: object = TOP                  # "narrow" | "accum" | TOP
    psummed: FrozenSet[str] = _EMPTY    # mesh axes reduced over (must)
    padded: FrozenSet[int] = _EMPTY     # dim indices carrying padding
    roots: FrozenSet[int] = _EMPTY      # param indices (shape-preserving)
    kind: str = "array"                 # "array" | "program"
    origin: str = ""                    # "zeros" for paddable buffers

    def rank(self):
        return len(self.shape) if isinstance(self.shape, tuple) else TOP

    def dim(self, i: int):
        if isinstance(self.shape, tuple):
            return self.shape[i] if 0 <= i < len(self.shape) else TOP
        return self.dim0 if (i == 0 and self.dim0 is not None) else TOP

    def dims_contained(self) -> FrozenSet[Sym]:
        out = set()
        if isinstance(self.shape, tuple):
            out.update(d for d in self.shape if isinstance(d, Sym))
        if isinstance(self.dim0, Sym):
            out.add(self.dim0)
        return frozenset(out)


@dataclass(frozen=True)
class DimVal:
    """A host int holding an array size."""

    dim: object


@dataclass(frozen=True)
class TupleVal:
    items: tuple


@dataclass(frozen=True)
class ShapeVal:
    """The ``x.shape`` object of one abstract array (owner name kept so
    an unpack can refine the array's own dims)."""

    owner: Optional[str]
    arr: AArray


UNKNOWN_ENTRY = object()   # an unresolvable element inside a P(...) spec


@dataclass(frozen=True)
class SpecVal:
    """A parsed ``PartitionSpec`` literal: one entry per tensor dim —
    a frozenset of mesh-axis names, None (replicated), or
    :data:`UNKNOWN_ENTRY`."""

    entries: tuple
    node: object = None

    def axes(self) -> FrozenSet[str]:
        out: Set[str] = set()
        for e in self.entries:
            if isinstance(e, frozenset):
                out |= e
        return frozenset(out)


class _Other:
    """Unknown non-array value (modules, strings, host objects). Distinct
    from ``AArray()`` so a module name never masquerades as an array
    receiver."""

    def __repr__(self):
        return "OTHER"


OTHER = _Other()


def join_avals(a, b):
    """Join two abstract values (branch merge)."""
    if isinstance(a, AArray) and isinstance(b, AArray):
        if isinstance(a.shape, tuple) and isinstance(b.shape, tuple) \
                and len(a.shape) == len(b.shape):
            shape = tuple(join_dim(x, y) for x, y in zip(a.shape, b.shape))
        else:
            shape = TOP
        return AArray(shape=shape,
                      dim0=a.dim0 if dims_equal(a.dim0, b.dim0) else None,
                      tier=a.tier if a.tier == b.tier else TOP,
                      psummed=a.psummed & b.psummed,
                      padded=a.padded | b.padded,
                      roots=a.roots | b.roots,
                      kind=a.kind if a.kind == b.kind else "array",
                      origin=a.origin if a.origin == b.origin else "")
    if isinstance(a, DimVal) and isinstance(b, DimVal):
        return DimVal(join_dim(a.dim, b.dim))
    return OTHER


# -- function summary ---------------------------------------------------------

#: encodes "reduced over every dim" in (param, axis) pairs. None, NOT a
#: negative int: a literal ``axis=-1`` must never alias the sentinel (a
#: helper's last-dim mean is not an all-dims mean)
ALL_AXES = None


@dataclass(frozen=True)
class ShapeSummary:
    """Converged per-function facts (the JXSHAPE dataflow lattice)."""

    #: per-return-element mesh axes the value is psum-reduced over
    #: (must: intersection across return paths); length-1 for single
    #: returns, longer for literal tuple returns
    ret_psummed: tuple = (frozenset(),)
    #: returns an SPMD program bound to the mesh it was built under
    returns_program: bool = False
    #: (transitively) tears down / rebuilds the device mesh
    rebuilds: bool = False
    #: (transitively) dispatches a collective aggregation — the fit path
    reaches_aggregate: bool = False
    #: (param index, axis|ALL_AXES) pairs reduced by an unmasked mean
    unmasked_mean_params: FrozenSet[Tuple[int, int]] = _EMPTY
    #: param indices handed (shape-preserving) to a host materializer
    materializes_params: FrozenSet[int] = _EMPTY


EMPTY_SUMMARY = ShapeSummary()

#: the hard-widening backstop: propagation facts degrade to True (the
#: fixpoint must terminate), finding-triggering facts degrade to silent
TOP_SUMMARY = ShapeSummary(ret_psummed=(frozenset(),), returns_program=True,
                           rebuilds=True, reaches_aggregate=True)


def summary_of(facts, fn) -> ShapeSummary:
    got = facts.get(fn) if facts else None
    return got if isinstance(got, ShapeSummary) else EMPTY_SUMMARY


# -- events -------------------------------------------------------------------

@dataclass
class Event:
    kind: str          # mean | mismatch | materialize | psum | shard_map |
                       # shard_apply | build | agg_args
    node: ast.AST
    aval: object = None
    axes: object = None        # mean: frozenset[int] (empty = all dims)
                               # psum: frozenset[str]
    detail: str = ""
    payload: dict = field(default_factory=dict)


class ShapeState:
    """The interpretation result for one function."""

    def __init__(self):
        self.env: Dict[str, object] = {}
        self.events: List[Event] = []
        self.returns: List[Tuple[ast.AST, object]] = []
        self.dataset_syms: Set[Sym] = set()
        self.dataset_roots: Set[int] = set()


# -- the interpreter ----------------------------------------------------------

class _Interp:
    """Source-order abstract interpretation of ONE function's own body.

    Two passes, TaintTracker-style: pass 1 establishes loop-carried
    bindings, pass 2 re-walks recording events and returns — so a name
    bound late in a loop body still has its fact at an earlier use.
    """

    def __init__(self, fn: FunctionInfo, graph, ctx, facts=None):
        self.fn = fn
        self.graph = graph
        self.ctx = ctx
        self.facts = facts or {}
        self.sites = graph.sites_map(fn)
        self.state = ShapeState()
        self._uid = 0
        self._recording = False
        self._seed_params()
        body = getattr(fn.node, "body", [])
        self._walk(body)
        self._recording = True
        self._walk(body)

    # -- plumbing -------------------------------------------------------------
    def _sym(self, label: str) -> Sym:
        self._uid += 1
        return Sym(self._uid, label)

    def _seed_params(self):
        for i, name in enumerate(_ordered_params(self.fn)):
            if name in ("self", "cls"):
                self.state.env[name] = OTHER
            else:
                self.state.env[name] = AArray(roots=frozenset({i}))

    def _event(self, kind, node, aval=None, axes=None, detail="",
               payload=None):
        if self._recording:
            self.state.events.append(
                Event(kind, node, aval, axes, detail, payload or {}))

    def _axis_names(self, expr) -> object:
        """Mesh-axis names off a collective's axis argument: string
        literals, mesh.py axis constants, tuples of either; TOP when
        unresolvable."""
        consts = getattr(self.ctx, "axis_constants", {}) or {}
        if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
            return frozenset({expr.value})
        if isinstance(expr, ast.Name) and expr.id in consts:
            return frozenset({consts[expr.id]})
        if isinstance(expr, (ast.Tuple, ast.List)):
            out: Set[str] = set()
            for e in expr.elts:
                got = self._axis_names(e)
                if got is TOP:
                    return TOP
                out |= got
            return frozenset(out)
        return TOP

    # -- statement walk -------------------------------------------------------
    def _walk(self, stmts: Sequence[ast.AST]):
        for stmt in stmts:
            self._stmt(stmt)

    def _stmt(self, stmt: ast.AST):
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = getattr(stmt, "value", None)
            if value is None:
                return
            aval = self.eval(value)
            for target in assign_targets(stmt):
                self._bind(target, aval, value)
        elif isinstance(stmt, ast.AugAssign):
            aval = self._binop_join(self.eval_name_or_other(stmt.target),
                                    self.eval(stmt.value), stmt)
            if isinstance(stmt.target, ast.Name):
                self.state.env[stmt.target.id] = aval
        elif isinstance(stmt, ast.Return):
            if stmt.value is not None:
                aval = self.eval(stmt.value)
            else:
                aval = OTHER
            if self._recording:
                self.state.returns.append((stmt, aval))
        elif isinstance(stmt, ast.Expr):
            self.eval(stmt.value)
        elif isinstance(stmt, (ast.For, ast.AsyncFor)):
            self.eval(stmt.iter)
            for n in assigned_names(stmt.target):
                self.state.env[n] = OTHER
            self._walk(stmt.body)
            self._walk(stmt.orelse)
        elif isinstance(stmt, ast.While):
            self.eval(stmt.test)
            self._walk(stmt.body)
            self._walk(stmt.orelse)
        elif isinstance(stmt, ast.If):
            self.eval(stmt.test)
            self._walk(stmt.body)
            self._walk(stmt.orelse)
        elif isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.eval(item.context_expr)
                if item.optional_vars is not None:
                    for n in assigned_names(item.optional_vars):
                        self.state.env[n] = OTHER
            self._walk(stmt.body)
        elif isinstance(stmt, ast.Try):
            self._walk(stmt.body)
            for h in stmt.handlers:
                self._walk(h.body)
            self._walk(stmt.orelse)
            self._walk(stmt.finalbody)
        elif isinstance(stmt, (ast.Raise, ast.Assert)):
            for child in ast.iter_child_nodes(stmt):
                if isinstance(child, ast.expr):
                    self.eval(child)
        elif isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                if isinstance(t, ast.Name):
                    self.state.env.pop(t.id, None)

    def eval_name_or_other(self, expr):
        if isinstance(expr, ast.Name):
            return self.state.env.get(expr.id, OTHER)
        return OTHER

    # -- binding --------------------------------------------------------------
    def _bind(self, target: ast.AST, aval, value_expr: ast.AST):
        if isinstance(target, ast.Name):
            self.state.env[target.id] = aval
            if isinstance(aval, DimVal) and isinstance(aval.dim, Sym) \
                    and target.id in DATASET_DIM_NAMES:
                # `n = x.shape[0]` — the spelled-out row-count binding
                self.state.dataset_syms.add(aval.dim)
            return
        if isinstance(target, (ast.Tuple, ast.List)):
            self._bind_unpack(target, aval)
            return
        if isinstance(target, ast.Subscript):
            # `buf[:k] = rows` — slice-store into a zeros buffer is the
            # bucket-padding idiom: the tail rows stay zero
            base = target.value
            if isinstance(base, ast.Name):
                cur = self.state.env.get(base.id)
                if isinstance(cur, AArray) and cur.origin == "zeros" \
                        and isinstance(target.slice, ast.Slice):
                    self.state.env[base.id] = replace(
                        cur, padded=cur.padded | {0})

    def _bind_unpack(self, target, aval):
        elts = target.elts
        if isinstance(aval, ShapeVal):
            # `n, d = x.shape` — name x's dims after the targets and
            # refine x's own abstract shape
            dims = []
            known = aval.arr.shape if isinstance(aval.arr.shape, tuple) \
                else None
            for i, elt in enumerate(elts):
                if known is not None and i < len(known) \
                        and known[i] is not TOP:
                    d = known[i]
                elif isinstance(elt, ast.Name):
                    d = self._sym(elt.id)
                else:
                    d = self._sym(f"{aval.owner or '?'}@{i}")
                dims.append(d)
                if isinstance(elt, ast.Name):
                    self.state.env[elt.id] = DimVal(d)
                    if elt.id in DATASET_DIM_NAMES and i == 0 \
                            and isinstance(d, Sym):
                        self.state.dataset_syms.add(d)
            if aval.owner is not None:
                arr = self.state.env.get(aval.owner)
                if isinstance(arr, AArray):
                    self.state.env[aval.owner] = replace(
                        arr, shape=tuple(dims), dim0=dims[0])
            return
        if isinstance(aval, TupleVal) and len(aval.items) == len(elts):
            for elt, item in zip(elts, aval.items):
                self._bind(elt, item, target)
            return
        for elt in elts:
            for n in assigned_names(elt):
                self.state.env[n] = OTHER

    # -- expression evaluation ------------------------------------------------
    def eval(self, expr: ast.AST):
        if isinstance(expr, ast.Name):
            return self.state.env.get(expr.id, OTHER)
        if isinstance(expr, ast.Constant):
            if isinstance(expr.value, int) and not isinstance(expr.value,
                                                              bool):
                return DimVal(expr.value)
            return OTHER
        if isinstance(expr, (ast.Tuple, ast.List)):
            return TupleVal(tuple(self.eval(e) for e in expr.elts))
        if isinstance(expr, ast.Attribute):
            return self._attribute(expr)
        if isinstance(expr, ast.Subscript):
            return self._subscript(expr)
        if isinstance(expr, ast.Call):
            return self._call(expr)
        if isinstance(expr, ast.BinOp):
            return self._binop(expr)
        if isinstance(expr, ast.UnaryOp):
            return self.eval(expr.operand)
        if isinstance(expr, ast.IfExp):
            self.eval(expr.test)
            return join_avals(self.eval(expr.body), self.eval(expr.orelse))
        if isinstance(expr, ast.BoolOp):
            for v in expr.values:
                self.eval(v)
            return OTHER
        if isinstance(expr, ast.Compare):
            self.eval(expr.left)
            for c in expr.comparators:
                self.eval(c)
            return OTHER
        if isinstance(expr, ast.Starred):
            return self.eval(expr.value)
        if isinstance(expr, (ast.GeneratorExp, ast.ListComp, ast.SetComp,
                             ast.DictComp, ast.Lambda)):
            return OTHER
        if isinstance(expr, ast.JoinedStr):
            return OTHER
        for child in ast.iter_child_nodes(expr):
            if isinstance(child, ast.expr):
                self.eval(child)
        return OTHER

    def _attribute(self, expr: ast.Attribute):
        if expr.attr == "shape":
            base = self.eval(expr.value)
            owner = expr.value.id if isinstance(expr.value, ast.Name) \
                else None
            if isinstance(base, AArray):
                return ShapeVal(owner, base)
            return ShapeVal(owner, AArray())
        if expr.attr == "T":
            base = self.eval(expr.value)
            if isinstance(base, AArray) and isinstance(base.shape, tuple):
                return AArray(shape=tuple(reversed(base.shape)),
                              tier=base.tier)
            return OTHER
        self.eval(expr.value)
        return OTHER

    def _subscript(self, expr: ast.Subscript):
        base = self.eval(expr.value)
        idx = expr.slice
        if isinstance(base, ShapeVal):
            # x.shape[i] — a dim read; invent + attach a symbol when the
            # shape is still opaque
            if isinstance(idx, ast.Constant) and isinstance(idx.value, int):
                i = idx.value
                d = base.arr.dim(i)
                if d is TOP and base.owner is not None:
                    d = self._sym(f"{base.owner}@{i}")
                    arr = self.state.env.get(base.owner)
                    if isinstance(arr, AArray) and i == 0:
                        self.state.env[base.owner] = replace(arr, dim0=d)
                return DimVal(d)
            return DimVal(TOP)
        if not isinstance(base, AArray):
            self.eval_index(idx)
            return OTHER
        if isinstance(idx, ast.Slice):
            # x[:k] — leading-dim slice; an explicit bound sheds any
            # padding mark (the un-pad read) and renames dim0
            upper = self.eval(idx.upper) if idx.upper is not None else None
            dim0 = upper.dim if isinstance(upper, DimVal) else (
                base.dim(0) if idx.upper is None else TOP)
            shape = base.shape
            if isinstance(shape, tuple) and shape:
                shape = (dim0,) + shape[1:]
            padded = base.padded if idx.upper is None \
                else base.padded - {0}
            # an explicit bound also ends dataset-dim provenance: x[:64]
            # is no longer the param's full extent
            roots = base.roots if idx.upper is None else _EMPTY
            return replace(base, shape=shape, dim0=dim0 if dim0 is not TOP
                           else None, padded=padded, roots=roots)
        if isinstance(idx, ast.Tuple):
            for e in idx.elts:
                self.eval_index(e)
            return AArray(tier=base.tier)
        # scalar index: drop the leading dim
        self.eval_index(idx)
        if isinstance(base.shape, tuple) and base.shape:
            return AArray(shape=base.shape[1:], tier=base.tier)
        return AArray(tier=base.tier)

    def eval_index(self, idx):
        if isinstance(idx, ast.Slice):
            for p in (idx.lower, idx.upper, idx.step):
                if p is not None:
                    self.eval(p)
        elif isinstance(idx, ast.expr):
            self.eval(idx)

    # -- binary ops -----------------------------------------------------------
    def _binop(self, expr: ast.BinOp):
        left = self.eval(expr.left)
        right = self.eval(expr.right)
        if isinstance(expr.op, ast.MatMult):
            return self._matmul(left, right, expr)
        if isinstance(left, DimVal) or isinstance(right, DimVal):
            # int arithmetic on dims: n * d, n + pad — result unknown dim
            if isinstance(left, DimVal) and isinstance(right, DimVal):
                if isinstance(left.dim, int) and isinstance(right.dim, int):
                    try:
                        return DimVal(_int_op(expr.op, left.dim, right.dim))
                    except Exception:
                        return DimVal(TOP)
                return DimVal(TOP)
        return self._binop_join(left, right, expr)

    def _binop_join(self, left, right, node):
        la = left if isinstance(left, AArray) else None
        ra = right if isinstance(right, AArray) else None
        if la is None and ra is None:
            return OTHER
        if la is None or ra is None:
            return la or ra
        # broadcast: align trailing dims; provable conflicts (two unequal
        # concrete ints, neither 1) are shape-mismatch events
        sa, sb = la.shape, ra.shape
        shape = TOP
        if isinstance(sa, tuple) and isinstance(sb, tuple):
            out = []
            for i in range(1, max(len(sa), len(sb)) + 1):
                da = sa[-i] if i <= len(sa) else 1
                db = sb[-i] if i <= len(sb) else 1
                if isinstance(da, int) and isinstance(db, int) \
                        and da != db and 1 not in (da, db):
                    self._event("mismatch", node,
                                detail=f"broadcast of dims {da} and {db}")
                if da == 1:
                    out.append(db)
                elif db == 1:
                    out.append(da)
                else:
                    out.append(join_dim(da, db))
            shape = tuple(reversed(out))
        return AArray(shape=shape,
                      dim0=la.dim0 if dims_equal(la.dim0, ra.dim0) else None,
                      tier=la.tier if la.tier == ra.tier else TOP,
                      psummed=la.psummed & ra.psummed,
                      padded=la.padded | ra.padded,
                      roots=la.roots | ra.roots)

    def _matmul(self, left, right, node):
        la = left if isinstance(left, AArray) else AArray()
        ra = right if isinstance(right, AArray) else AArray()
        sa = la.shape if isinstance(la.shape, tuple) else None
        sb = ra.shape if isinstance(ra.shape, tuple) else None
        if sa and sb:
            inner_a = sa[-1]
            inner_b = sb[-2] if len(sb) >= 2 else sb[0]
            if isinstance(inner_a, int) and isinstance(inner_b, int) \
                    and inner_a != inner_b:
                self._event("mismatch", node,
                            detail=f"matmul inner dims {inner_a} and "
                                   f"{inner_b}")
            if len(sa) == 2 and len(sb) == 2:
                return AArray(shape=(sa[0], sb[1]),
                              padded=la.padded & {0})
            if len(sa) == 2 and len(sb) == 1:
                return AArray(shape=(sa[0],), padded=la.padded & {0})
            if len(sa) == 1 and len(sb) == 2:
                return AArray(shape=(sb[1],))
            if len(sa) == 1 and len(sb) == 1:
                return AArray(shape=())
        return OTHER

    # -- calls ----------------------------------------------------------------
    def _call(self, expr: ast.Call):
        name = call_name(expr) or ""
        base = last_component(name) or ""
        if not base and isinstance(expr.func, ast.Attribute):
            # method on a non-name receiver (`zeros(...).at[:k].set(x)`,
            # `run(x).tolist()`) — dotted_name gives up, the attr is
            # still the dispatch key
            base = expr.func.attr

        # f(...)(...) — an applied shard_map: record the operand ranks
        # against the inner call's specs
        if isinstance(expr.func, ast.Call):
            inner_name = last_component(call_name(expr.func) or "")
            inner = self._call(expr.func)
            arg_avals = [self.eval(a) for a in expr.args
                         if not isinstance(a, ast.Starred)]
            has_star = any(isinstance(a, ast.Starred) for a in expr.args)
            for a in expr.args:
                if isinstance(a, ast.Starred):
                    self.eval(a.value)
            if inner_name in SHARD_MAP_CALLS:
                self._event("shard_apply", expr, payload={
                    "inner": expr.func, "arg_avals": arg_avals,
                    "has_star": has_star})
            return inner if isinstance(inner, AArray) else OTHER

        # P(...) / PartitionSpec(...) literals parse into SpecVals
        if base in ("P", "PartitionSpec"):
            return self._parse_spec(expr)

        # method chains that need the receiver's abstract value
        recv = None
        if isinstance(expr.func, ast.Attribute):
            recv = self.eval(expr.func.value)

        arg_avals = [self.eval(a) if not isinstance(a, ast.Starred)
                     else self.eval(a.value) for a in expr.args]
        kw_avals = {kw.arg: self.eval(kw.value) for kw in expr.keywords
                    if kw.arg is not None}
        for kw in expr.keywords:
            if kw.arg is None:
                self.eval(kw.value)

        # `x.at[:k].set(rows)` — functional update; zeros-origin + slice
        # target marks padding
        if base == "set" and isinstance(expr.func, ast.Attribute) \
                and isinstance(expr.func.value, ast.Subscript):
            at = expr.func.value
            if isinstance(at.value, ast.Attribute) and at.value.attr == "at":
                buf = self.eval(at.value.value)
                if isinstance(buf, AArray):
                    if buf.origin == "zeros" \
                            and isinstance(at.slice, ast.Slice):
                        return replace(buf, padded=buf.padded | {0})
                    return buf
            return OTHER

        if base in SHARD_MAP_CALLS:
            return self._shard_map_call(expr, name)

        if base in PROGRAM_BUILDERS:
            self._event("build", expr, detail=base)
            if base in ("tree_aggregate", "tree_aggregate_with_state") \
                    and len(expr.args) > 2:
                shard_avals = [a for a in arg_avals[2:]
                               if isinstance(a, AArray)]
                self._event("agg_args", expr, payload={"avals": shard_avals})
                for a in shard_avals:
                    d0 = a.dim(0)
                    if isinstance(d0, Sym):
                        self.state.dataset_syms.add(d0)
                    self.state.dataset_roots |= a.roots
            return AArray(kind="program")

        if base == "all_gather_hosts":
            self._event("agg_args", expr, payload={
                "avals": [a for a in arg_avals[2:] if isinstance(a, AArray)]})
            return OTHER

        if base in PSUM_CALLS or base == "psum_over_mesh":
            return self._psum_call(expr, base, arg_avals)

        if base == "tree_map" and expr.args \
                and isinstance(expr.args[0], ast.Lambda):
            return self._tree_map_lambda(expr)

        if base in CONSTRUCTORS and _is_numeric_lib(name):
            return self._constructor(base, expr, arg_avals)

        if base == "pad" and _is_numeric_lib(name):
            return self._pad_call(expr, arg_avals)

        if base in MEAN_CALLS or base in REDUCERS:
            return self._reduction(expr, base, recv, arg_avals, kw_avals)

        if base == "reshape":
            target = recv if isinstance(recv, AArray) else (
                arg_avals[0] if arg_avals and isinstance(arg_avals[0], AArray)
                else None)
            shape_expr = expr.args[-1] if expr.args else None
            dims = self._dims_from_shape_arg(shape_expr)
            return AArray(shape=dims,
                          tier=target.tier if target is not None else TOP)

        if base == "astype" and isinstance(recv, AArray):
            tier = _tier_of_dtype_expr(expr.args[0]) if expr.args else TOP
            return replace(recv, tier=tier if tier is not None else recv.tier)

        if base in MATMUL_CALLS and _is_numeric_lib(name):
            if len(arg_avals) >= 2:
                return self._matmul(arg_avals[0], arg_avals[1], expr)
            if recv is not None and arg_avals:
                return self._matmul(recv, arg_avals[0], expr)
            return OTHER

        if base == "tolist" and isinstance(recv, AArray):
            self._event("materialize", expr, recv, detail=".tolist()")
            return OTHER

        if name in MATERIALIZER_DOTTED or (
                base in NP_MATERIALIZER_LAST
                and name.startswith(("np.", "numpy."))):
            target = arg_avals[0] if arg_avals else OTHER
            if isinstance(target, AArray):
                self._event("materialize", expr, target, detail=name)
                return target
            return OTHER

        # resolved user call: consult callee summaries
        site = self.sites.get(id(expr))
        if site is not None and site.targets:
            return self._user_call(expr, site)

        # generic jnp/np elementwise fallback: one array in, same fact out
        if name.startswith(ELEMWISE_PREFIXES):
            arrays = [a for a in list(arg_avals) + list(kw_avals.values())
                      if isinstance(a, AArray) and a is not OTHER]
            if isinstance(recv, AArray) and recv is not OTHER:
                arrays.insert(0, recv)
            if len(arrays) == 1:
                return replace(arrays[0], psummed=_EMPTY, origin="")
            if len(arrays) > 1:
                out = arrays[0]
                for a in arrays[1:]:
                    out = join_avals(out, a)
                return replace(out, psummed=_EMPTY, origin="") \
                    if isinstance(out, AArray) else OTHER
        return OTHER

    def _user_call(self, expr, site):
        kind = "array"
        psummed = None
        for target in site.targets:
            s = summary_of(self.facts, target)
            if s.returns_program:
                kind = "program"
            first = s.ret_psummed[0] if s.ret_psummed else frozenset()
            psummed = first if psummed is None else (psummed & first)
            # interprocedural mean/materialize: project the callee's
            # param facts onto this site's arguments
            pm = s.unmasked_mean_params
            mm = s.materializes_params
            if pm or mm:
                for pos, arg in site.param_map(target):
                    # the argument was already evaluated (events recorded)
                    # when the call's operands were walked — re-evaluate
                    # silently just to read its abstract value
                    saved, self._recording = self._recording, False
                    aval = self.eval(arg)
                    self._recording = saved
                    if not isinstance(aval, AArray):
                        continue
                    axes = {ax for (p, ax) in pm if p == pos}
                    if axes:
                        # ALL_AXES projects as an empty event-axes set
                        # (the "every dim" spelling mean events use)
                        self._event(
                            "mean", expr, aval,
                            frozenset(a for a in axes if a is not ALL_AXES),
                            detail=f"via {target.qualname}()")
                    if pos in mm:
                        self._event("materialize", expr, aval,
                                    detail=f"via {target.qualname}()")
        return AArray(kind=kind, psummed=psummed or _EMPTY)

    def _psum_call(self, expr, base, arg_avals):
        operand = arg_avals[0] if arg_avals else OTHER
        if base == "psum_over_mesh":
            if len(expr.args) > 1:
                axes = self._axis_names(expr.args[1])
            else:
                valid = set(getattr(self.ctx, "valid_axes", ()) or ())
                axes = frozenset({"data", "replica"} & valid) \
                    or frozenset(valid)
        else:
            axes = self._axis_names(expr.args[1]) if len(expr.args) > 1 \
                else TOP
        axes = axes if axes is not TOP else _EMPTY
        self._event("psum", expr, operand, axes, detail=base)
        if isinstance(operand, AArray):
            return replace(operand, psummed=operand.psummed | axes)
        return AArray(psummed=frozenset(axes))

    def _tree_map_lambda(self, expr):
        lam = expr.args[0]
        operand = self.eval(expr.args[1]) if len(expr.args) > 1 else OTHER
        params = [a.arg for a in lam.args.args]
        saved = {p: self.state.env.get(p) for p in params}
        if params:
            self.state.env[params[0]] = operand
        out = self.eval(lam.body)
        for p, v in saved.items():
            if v is None:
                self.state.env.pop(p, None)
            else:
                self.state.env[p] = v
        return out

    def _constructor(self, base, expr, arg_avals):
        origin = "zeros" if base in ZERO_ORIGIN else base
        if base.endswith("_like"):
            src = arg_avals[0] if arg_avals else OTHER
            if isinstance(src, AArray):
                return AArray(shape=src.shape, dim0=src.dim0, tier=src.tier,
                              origin=origin)
            return AArray(origin=origin)
        dims = self._dims_from_shape_arg(expr.args[0]) if expr.args else TOP
        return AArray(shape=dims, origin=origin)

    def _dims_from_shape_arg(self, shape_expr) -> object:
        if shape_expr is None:
            return TOP
        aval = self.eval(shape_expr)
        if isinstance(aval, DimVal):
            return (aval.dim,)
        if isinstance(aval, TupleVal):
            dims = []
            for item in aval.items:
                if isinstance(item, DimVal):
                    dims.append(item.dim)
                else:
                    dims.append(TOP)
            return tuple(dims)
        return TOP

    def _pad_call(self, expr, arg_avals):
        target = arg_avals[0] if arg_avals else OTHER
        if not isinstance(target, AArray):
            return OTHER
        padded = self._padded_dims(expr.args[1] if len(expr.args) > 1
                                   else None, target)
        return replace(target, padded=target.padded | padded, origin="")

    @staticmethod
    def _padded_dims(width_expr, target) -> FrozenSet[int]:
        """Dims a pad_width literal actually pads; unresolvable entries
        pad conservatively."""
        rank = target.rank()
        all_dims = frozenset(range(rank)) if isinstance(rank, int) \
            else frozenset({0})
        if width_expr is None:
            return all_dims
        if isinstance(width_expr, ast.Constant):
            return all_dims if width_expr.value else frozenset()
        if isinstance(width_expr, (ast.Tuple, ast.List)):
            out: Set[int] = set()
            for i, entry in enumerate(width_expr.elts):
                if isinstance(entry, (ast.Tuple, ast.List)) and all(
                        isinstance(e, ast.Constant) and e.value == 0
                        for e in entry.elts):
                    continue
                out.add(i)
            return frozenset(out)
        return all_dims

    def _reduction(self, expr, base, recv, arg_avals, kw_avals):
        # operand: method receiver, else first positional array
        if isinstance(recv, AArray):
            operand = recv
            axis_expr = expr.args[0] if expr.args else _kwarg(expr, "axis")
        else:
            name = call_name(expr) or ""
            if not name.startswith(ELEMWISE_PREFIXES):
                return OTHER
            operand = arg_avals[0] if arg_avals else OTHER
            axis_expr = expr.args[1] if len(expr.args) > 1 \
                else _kwarg(expr, "axis")
        if not isinstance(operand, AArray):
            return OTHER
        axes = _literal_axes(axis_expr)
        if base in MEAN_CALLS:
            self._event("mean", expr, operand,
                        axes if axes is not TOP else frozenset(),
                        detail=base)
        # result: reduced dims removed when known, provenance dropped
        if axes is TOP or not isinstance(operand.shape, tuple):
            return AArray(tier=operand.tier)
        if not axes:   # full reduction -> scalar
            return AArray(shape=(), tier=operand.tier)
        rank = len(operand.shape)
        norm = {a % rank for a in axes if isinstance(a, int)} \
            if rank else set()
        shape = tuple(d for i, d in enumerate(operand.shape)
                      if i not in norm)
        return AArray(shape=shape, tier=operand.tier)

    def _shard_map_call(self, expr, name):
        args = list(expr.args)
        kws = {kw.arg: kw.value for kw in expr.keywords if kw.arg}
        body = args[0] if args else kws.get("f")
        mesh = args[1] if len(args) > 1 else kws.get("mesh")
        in_specs = args[2] if len(args) > 2 else kws.get("in_specs")
        out_specs = args[3] if len(args) > 3 else kws.get("out_specs")
        for e in (mesh, in_specs, out_specs):
            if e is not None:
                self.eval(e)
        self._event("shard_map", expr, payload={
            "body": body, "mesh": mesh, "in_specs": in_specs,
            "out_specs": out_specs})
        self._event("build", expr, detail=last_component(name) or name)
        return AArray(kind="program")

    def _parse_spec(self, expr: ast.Call) -> SpecVal:
        consts = getattr(self.ctx, "axis_constants", {}) or {}
        return parse_spec(expr, consts)


def parse_spec(expr: ast.Call, consts) -> SpecVal:
    """``P(...)`` / ``PartitionSpec(...)`` literal -> :class:`SpecVal`,
    resolving mesh-axis constants (``DATA_AXIS``) through ``consts``."""
    return SpecVal(tuple(_spec_entry(arg, consts) for arg in expr.args),
                   expr)


def resolve_spec(expr, env, consts) -> object:
    """A SpecVal / TupleVal-of-SpecVals for a spec expression, through
    local name bindings; None when unresolvable structurally."""
    if expr is None:
        return None
    if isinstance(expr, ast.Name):
        got = env.get(expr.id)
        return got if isinstance(got, (SpecVal, TupleVal)) else None
    if isinstance(expr, ast.Call):
        base = last_component(call_name(expr) or "")
        if base in ("P", "PartitionSpec"):
            return parse_spec(expr, consts)
        return None
    if isinstance(expr, (ast.Tuple, ast.List)):
        items = []
        for e in expr.elts:
            got = resolve_spec(e, env, consts)
            if got is None:
                return None
            items.append(got)
        return TupleVal(tuple(items))
    if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Mult):
        # `(row_spec,) * k` — a uniform spec of unknown count
        for side in (expr.left, expr.right):
            got = resolve_spec(side, env, consts)
            if isinstance(got, TupleVal) and len(got.items) == 1:
                return got.items[0]
            if isinstance(got, SpecVal):
                return got
    return None


def iter_spec_literals(expr, env, consts):
    """Every P(...)-shaped SpecVal syntactically reachable from a spec
    expression — the loose sweep for `tuple([row_spec]*n + [P()]*m)`
    style constructions where structural resolution gives up. Name
    references resolve through ``env`` so the bound literal is validated
    too."""
    if expr is None:
        return
    seen: Set[int] = set()
    stack: List[ast.AST] = [expr]
    while stack:
        node = stack.pop()
        if isinstance(node, ast.Call):
            base = last_component(call_name(node) or "")
            if base in ("P", "PartitionSpec") and id(node) not in seen:
                seen.add(id(node))
                yield parse_spec(node, consts)
        if isinstance(node, ast.Name):
            got = env.get(node.id)
            if isinstance(got, SpecVal) and id(got.node) not in seen:
                seen.add(id(got.node))
                yield got
            elif isinstance(got, TupleVal):
                for item in got.items:
                    if isinstance(item, SpecVal) \
                            and id(item.node) not in seen:
                        seen.add(id(item.node))
                        yield item
        stack.extend(ast.iter_child_nodes(node))


def _spec_entry(arg, consts):
    if isinstance(arg, ast.Constant):
        if arg.value is None:
            return None
        if isinstance(arg.value, str):
            return frozenset({arg.value})
        return UNKNOWN_ENTRY
    if isinstance(arg, ast.Name):
        if arg.id in consts:
            return frozenset({consts[arg.id]})
        return UNKNOWN_ENTRY
    if isinstance(arg, (ast.Tuple, ast.List)):
        axes: Set[str] = set()
        for e in arg.elts:
            got = _spec_entry(e, consts)
            if not isinstance(got, frozenset):
                return UNKNOWN_ENTRY
            axes |= got
        return frozenset(axes)
    return UNKNOWN_ENTRY


def _kwarg(call: ast.Call, name: str):
    for kw in call.keywords:
        if kw.arg == name:
            return kw.value
    return None


def _literal_axes(axis_expr) -> object:
    """axis= argument -> frozenset of int axes; empty = ALL dims (no
    axis), TOP = unresolvable."""
    if axis_expr is None:
        return frozenset()
    if isinstance(axis_expr, ast.Constant):
        if axis_expr.value is None:
            return frozenset()
        if isinstance(axis_expr.value, int):
            return frozenset({axis_expr.value})
        return TOP
    if isinstance(axis_expr, (ast.Tuple, ast.List)):
        out: Set[int] = set()
        for e in axis_expr.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.add(e.value)
            else:
                return TOP
        return frozenset(out)
    if isinstance(axis_expr, ast.UnaryOp) \
            and isinstance(axis_expr.op, ast.USub) \
            and isinstance(axis_expr.operand, ast.Constant) \
            and isinstance(axis_expr.operand.value, int):
        return frozenset({-axis_expr.operand.value})
    return TOP


def _int_op(op, a, b):
    import operator
    table = {ast.Add: operator.add, ast.Sub: operator.sub,
             ast.Mult: operator.mul, ast.FloorDiv: operator.floordiv,
             ast.Mod: operator.mod}
    fn = table.get(type(op))
    if fn is None:
        raise ValueError
    return fn(a, b)


def _is_numeric_lib(name: str) -> bool:
    return name.startswith(("jnp.", "jax.numpy.", "np.", "numpy.",
                            "jax.lax.", "lax."))


def _tier_of_dtype_expr(expr) -> Optional[str]:
    # the narrow half of the tier lattice is JX004's (one boundary, one
    # definition); imported lazily — rules/__init__ imports the shape
    # rules which import this module
    from cycloneml_tpu.analysis.rules.jx004_fp64_drift import (
        NARROW_DOTTED, NARROW_STRINGS)
    name = dotted_name(expr)
    if name in NARROW_DOTTED:
        return TIER_NARROW
    if name in ACCUM_DOTTED:
        return TIER_ACCUM
    if isinstance(expr, ast.Constant) and isinstance(expr.value, str):
        if expr.value in NARROW_STRINGS:
            return TIER_NARROW
        if expr.value in ACCUM_STRINGS:
            return TIER_ACCUM
    return None


# -- summarization ------------------------------------------------------------

def _relevant(fn: FunctionInfo) -> bool:
    for name in fn.calls:
        base = last_component(name)
        if base in _INTERESTING_LAST:
            return True
        if name in REBUILD_DOTTED or name in MATERIALIZER_DOTTED:
            return True
    return False


def _has_math(fn: FunctionInfo) -> bool:
    if _relevant(fn):
        return True
    for name in fn.calls:
        if name.startswith(ELEMWISE_PREFIXES):
            return True
    return False


def _own_rebuild(fn: FunctionInfo) -> bool:
    for name in fn.calls:
        if last_component(name) in REBUILD_LAST:
            return True
        if name in REBUILD_DOTTED:
            return True
        if name.endswith(".reset") and "mesh" in name.split(".")[0].lower():
            return True
    return False


def _own_aggregate(fn: FunctionInfo) -> bool:
    return any(last_component(name) in AGGREGATE_CALLS for name in fn.calls)


def compute_summary(fn: FunctionInfo, graph, ctx, facts) -> ShapeSummary:
    """One transfer-function application: interpret the body with the
    callees' current facts and distill the summary lattice element."""
    callee_nontrivial = False
    for site in graph.sites(fn):
        for t in site.targets:
            if summary_of(facts, t) != EMPTY_SUMMARY:
                callee_nontrivial = True
                break
        if callee_nontrivial:
            break
    if not _relevant(fn) and not callee_nontrivial:
        if _own_aggregate(fn) or _own_rebuild(fn):
            return ShapeSummary(rebuilds=_own_rebuild(fn),
                                reaches_aggregate=_own_aggregate(fn))
        return EMPTY_SUMMARY

    interp = _Interp(fn, graph, ctx, facts)
    st = interp.state

    # returns: psummed axes per element, must across return paths
    vectors: List[tuple] = []
    returns_program = False
    for _, aval in st.returns:
        if isinstance(aval, TupleVal):
            vec = tuple(a.psummed if isinstance(a, AArray) else frozenset()
                        for a in aval.items)
            if any(isinstance(a, AArray) and a.kind == "program"
                   for a in aval.items):
                returns_program = True
        elif isinstance(aval, AArray):
            vec = (aval.psummed,)
            if aval.kind == "program":
                returns_program = True
        else:
            vec = (frozenset(),)
        vectors.append(vec)
    if not vectors:
        ret_psummed: tuple = (frozenset(),)
    elif all(len(v) == len(vectors[0]) for v in vectors):
        ret_psummed = tuple(
            frozenset.intersection(*(v[i] for v in vectors))
            for i in range(len(vectors[0])))
    else:
        flat = frozenset.intersection(*(frozenset().union(*v) if v
                                        else frozenset() for v in vectors))
        ret_psummed = (flat,)

    rebuilds = _own_rebuild(fn)
    reaches = _own_aggregate(fn)
    for site in graph.sites(fn):
        for t in site.targets:
            s = summary_of(facts, t)
            rebuilds = rebuilds or s.rebuilds
            reaches = reaches or s.reaches_aggregate

    mean_params: Set[Tuple[int, int]] = set()
    mat_params: Set[int] = set()
    for ev in st.events:
        if ev.kind == "mean" and isinstance(ev.aval, AArray):
            axes = ev.axes if ev.axes else frozenset({ALL_AXES})
            for root in ev.aval.roots:
                for ax in axes:
                    # negative literal axes are dropped: without the
                    # operand's rank they name no concrete dim, and they
                    # must not alias ALL_AXES (a helper's axis=-1 mean
                    # is NOT an all-dims mean)
                    if ax is ALL_AXES or (isinstance(ax, int) and ax >= 0):
                        mean_params.add((root, ax))
        elif ev.kind == "materialize" and isinstance(ev.aval, AArray):
            mat_params |= ev.aval.roots

    return ShapeSummary(ret_psummed=ret_psummed,
                        returns_program=returns_program,
                        rebuilds=rebuilds,
                        reaches_aggregate=reaches,
                        unmasked_mean_params=frozenset(mean_params),
                        materializes_params=frozenset(mat_params))


# -- shared dataflow client + per-ctx state cache -----------------------------

class ShapeRuleBase:
    """Mixin giving a rule the shared JXSHAPE analysis. The engine
    dedupes dataflow clients by ``analysis_id``, so however many shape
    rules are active, the fixpoint runs once."""

    analysis_id = ANALYSIS_ID

    def initial(self, fn, graph, ctx):
        return compute_summary(fn, graph, ctx, None)

    def transfer(self, fn, facts, graph, ctx):
        return compute_summary(fn, graph, ctx, facts)

    def top(self, fn, graph, ctx):
        return TOP_SUMMARY

    # -- converged facts + cached check-time states ---------------------------
    @staticmethod
    def facts(ctx) -> Dict[FunctionInfo, ShapeSummary]:
        if ctx.dataflow is None:
            return {}
        return ctx.dataflow.summaries(ANALYSIS_ID)

    @staticmethod
    def state_of(ctx, fn: FunctionInfo) -> Optional[ShapeState]:
        """The function's final interpretation under the CONVERGED
        summaries, computed once per run and shared by every shape
        rule's check()."""
        cache = getattr(ctx, "_shape_states", None)
        if cache is None or getattr(ctx, "_shape_states_ctx", None) \
                is not ctx:
            cache = {}
            ctx._shape_states = cache
            ctx._shape_states_ctx = ctx
        if fn in cache:
            return cache[fn]
        graph = ctx.callgraph
        if graph is None:
            cache[fn] = None
            return None
        facts = ShapeRuleBase.facts(ctx)
        if not _has_math(fn) and not any(
                summary_of(facts, t) != EMPTY_SUMMARY
                for site in graph.sites(fn) for t in site.targets):
            cache[fn] = None
            return None
        import time as _time
        t0 = _time.perf_counter()
        state = _Interp(fn, graph, ctx, facts).state
        # charge the lazily-built shared interpretation to JXSHAPE, not
        # to whichever rule's check() touched this function first — the
        # engine re-attributes via ctx.shared_time_credit
        credit = getattr(ctx, "shared_time_credit", None)
        if credit is None:
            credit = {}
            ctx.shared_time_credit = credit
        credit[ANALYSIS_ID] = credit.get(ANALYSIS_ID, 0.0) \
            + _time.perf_counter() - t0
        cache[fn] = state
        return state
