"""Shared AST helpers: dotted names, scope-aware function tables, taint.

Everything here is pure ``ast`` — no imports of the analyzed code, so the
analyzer can run over broken or import-cycle-heavy modules (and over test
fixtures that would crash at import time on purpose).
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Set, Tuple

# Attribute reads that are static under tracing: touching them on a tracer
# yields a host value without forcing a device sync, so they launder taint.
STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "sharding", "aval",
                "weak_type"}

# Builtins whose result is a host scalar/bool regardless of the argument —
# they END taint (the sync itself is JX001's business, not the taint pass).
LAUNDER_CALLS = {"len", "isinstance", "hasattr", "callable", "type", "repr",
                 "str", "id", "getattr"}

# Call prefixes that produce traced values inside traced code.
TRACED_PREFIXES = ("jnp.", "jax.numpy.", "jax.nn.", "jax.lax.", "lax.",
                   "jax.scipy.", "jax.random.", "jrandom.")

# jnp/jax calls that answer static METADATA questions (host bools/dtypes,
# never tracers) — `if jnp.issubdtype(dtype, jnp.integer):` is fine.
STATIC_QUERY_CALLS = {"issubdtype", "iinfo", "finfo", "dtype", "result_type",
                      "can_cast", "promote_types", "isdtype", "zeros_like_p"}


def dotted_name(node: ast.AST) -> Optional[str]:
    """``jax.lax.psum`` for an Attribute/Name chain, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def call_name(call: ast.Call) -> Optional[str]:
    return dotted_name(call.func)


def last_component(dotted: Optional[str]) -> Optional[str]:
    return dotted.rsplit(".", 1)[-1] if dotted else None


def assigned_names(target: ast.AST) -> List[str]:
    """Plain names bound by an assignment target (tuples unpacked,
    attribute/subscript targets skipped — those mutate, not bind)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: List[str] = []
        for elt in target.elts:
            out.extend(assigned_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return assigned_names(target.value)
    return []


@dataclass
class FunctionInfo:
    """One function/method/closure in an analyzed module."""

    qualname: str                       # dotted, nesting flattened: a.b.c
    node: ast.AST                       # FunctionDef / AsyncFunctionDef / Lambda
    module_path: str                    # repo-relative posix path
    parent: Optional["FunctionInfo"]    # lexically enclosing function
    class_name: Optional[str] = None    # immediate enclosing class, if any
    params: Set[str] = field(default_factory=set)
    calls: Set[str] = field(default_factory=set)        # dotted callee names
    has_lax_call: bool = False          # jax.lax.* / lax.* call in OWN body
    is_jit_decorated: bool = False
    is_returned_kernel: bool = False    # returned closure doing jnp math
    passed_to_tracer: bool = False      # handed to jit/shard_map/scan/...
    jit_reachable: bool = False         # final verdict (reachability pass)

    @property
    def params_traced(self) -> bool:
        """Are this function's parameters themselves traced values?

        True for direct trace seeds — jitted functions, functions handed
        to tracing entry points, returned jnp-kernel closures, and
        lax-calling functions (their arguments are the traced operands).
        False for helpers that are merely reachable through the call
        graph: those commonly take a MIX of traced arrays and static
        config (`_split_coef(coef, d, fit_intercept)`), and seeding every
        parameter would flag `if fit_intercept:` — pure noise. Values
        assigned from jnp/jax expressions still taint either way.
        """
        return (self.is_jit_decorated or self.passed_to_tracer
                or self.is_returned_kernel or self.has_lax_call)

    def __hash__(self):  # identity hashing: one info per def site
        return id(self)


def iter_own_statements(fn_node: ast.AST):
    """Walk every node of a function body WITHOUT descending into nested
    function/class defs (those get their own FunctionInfo)."""
    stack = list(getattr(fn_node, "body", []))
    while stack:
        node = stack.pop(0)
        yield node
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            continue
        stack.extend(ast.iter_child_nodes(node))


def expr_is_traced_producer(expr: ast.AST) -> bool:
    """Does evaluating ``expr`` call into jnp/jax land (so its value is a
    device array / tracer under a jit trace)?"""
    for node in ast.walk(expr):
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name and (name.startswith(TRACED_PREFIXES)
                         or name in ("jnp", "lax")):
                return True
    return False


class TaintTracker:
    """Forward may-taint analysis over one function body.

    Tainted = "holds a traced value / device array when this function is
    traced". Parameters of a jit-reachable function are traced by
    construction; names assigned from tainted expressions or jnp/jax calls
    become tainted; ``.shape`` / ``len()`` / ``isinstance()`` reads launder.
    Two passes give a cheap fixpoint for names used before a later
    (loop-carried) assignment.
    """

    def __init__(self, fn_node: ast.AST, seed_params: bool = True):
        self.tainted: Set[str] = set()
        if seed_params:
            args = getattr(fn_node, "args", None)
            if args is not None:
                for a in (list(args.posonlyargs) + list(args.args)
                          + list(args.kwonlyargs)):
                    self.tainted.add(a.arg)
                if args.vararg:
                    self.tainted.add(args.vararg.arg)
                if args.kwarg:
                    self.tainted.add(args.kwarg.arg)
        for _ in range(2):
            for stmt in iter_own_statements(fn_node):
                self._visit_stmt(stmt)

    def _visit_stmt(self, stmt: ast.AST) -> None:
        if isinstance(stmt, ast.Assign):
            if self.expr_tainted(stmt.value):
                for t in stmt.targets:
                    self.tainted.update(assigned_names(t))
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            if self.expr_tainted(stmt.value):
                self.tainted.update(assigned_names(stmt.target))
        elif isinstance(stmt, ast.AugAssign):
            if self.expr_tainted(stmt.value):
                self.tainted.update(assigned_names(stmt.target))
        elif isinstance(stmt, ast.For):
            if self.expr_tainted(stmt.iter):
                self.tainted.update(self._loop_tainted_targets(stmt))
        elif isinstance(stmt, ast.withitem) and stmt.optional_vars is not None:
            if self.expr_tainted(stmt.context_expr):
                self.tainted.update(assigned_names(stmt.optional_vars))

    @staticmethod
    def _loop_tainted_targets(stmt: ast.For) -> List[str]:
        """Loop targets that actually receive traced values. Dict KEYS are
        static Python objects under tracing (the dict's structure is fixed
        per trace), so ``for k, v in parts.items():`` taints only ``v``;
        same for the index of ``enumerate()``."""
        target, it = stmt.target, stmt.iter
        pair = (isinstance(target, ast.Tuple) and len(target.elts) == 2)
        if isinstance(it, ast.Call):
            attr = it.func.attr if isinstance(it.func, ast.Attribute) else None
            if attr == "keys":
                return []
            if attr == "items" and pair:
                return assigned_names(target.elts[1])
            if (isinstance(it.func, ast.Name) and it.func.id == "enumerate"
                    and pair):
                return assigned_names(target.elts[1])
        return assigned_names(target)

    def expr_tainted(self, expr: ast.AST) -> bool:
        return self._tainted(expr)

    def _tainted(self, node: ast.AST) -> bool:
        if isinstance(node, ast.Name):
            return node.id in self.tainted
        if isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Attribute):
            if node.attr in STATIC_ATTRS:
                return False
            return self._tainted(node.value)
        if isinstance(node, ast.Call):
            name = call_name(node)
            if name:
                base = last_component(name)
                if base in LAUNDER_CALLS or base in STATIC_QUERY_CALLS:
                    return False
                # host coercions end taint; flagging them is JX001's job
                if name in ("float", "int", "bool"):
                    return False
                if name.startswith(TRACED_PREFIXES):
                    return True
            # method call on a tainted receiver: x.sum(), x.at[i].set(v)
            if isinstance(node.func, ast.Attribute) \
                    and node.func.attr not in STATIC_QUERY_CALLS \
                    and self._tainted(node.func.value):
                return True
            return any(self._tainted(a) for a in node.args) or any(
                self._tainted(kw.value) for kw in node.keywords)
        if isinstance(node, ast.Compare):
            # `x is None` / `x is not None` is a static trace-time branch
            # (a tracer is never None) — the canonical optional-arg pattern.
            if (len(node.ops) == 1
                    and isinstance(node.ops[0], (ast.Is, ast.IsNot))
                    and isinstance(node.comparators[0], ast.Constant)
                    and node.comparators[0].value is None):
                return False
            return self._tainted(node.left) or any(
                self._tainted(c) for c in node.comparators)
        if isinstance(node, ast.Subscript):
            return self._tainted(node.value) or self._tainted(node.slice)
        if isinstance(node, (ast.BinOp,)):
            return self._tainted(node.left) or self._tainted(node.right)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand)
        if isinstance(node, ast.BoolOp):
            return any(self._tainted(v) for v in node.values)
        if isinstance(node, ast.IfExp):
            return (self._tainted(node.body) or self._tainted(node.test)
                    or self._tainted(node.orelse))
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._tainted(e) for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(self._tainted(v) for v in node.values if v is not None)
        if isinstance(node, ast.Starred):
            return self._tainted(node.value)
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return self._tainted(node.elt)
        if isinstance(node, ast.Slice):
            return any(self._tainted(p) for p in
                       (node.lower, node.upper, node.step) if p is not None)
        return False


def collect_suppressions(source_lines: Sequence[str]) -> Dict[int, Set[str]]:
    """Map 1-based line -> set of suppressed rule ids (or {"all"}).

    ``# graftlint: disable=JX001`` inline suppresses that line;
    on a line of its own it suppresses the NEXT line as well (so the
    directive can sit above a long statement). Comma-separated rule lists
    and ``disable=all`` are accepted.
    """
    out: Dict[int, Set[str]] = {}
    for i, line in enumerate(source_lines, start=1):
        marker = "graftlint:"
        pos = line.find(marker)
        if pos < 0 or "#" not in line[:pos]:
            continue
        directive = line[pos + len(marker):].strip()
        if not directive.startswith("disable"):
            continue
        _, _, rules = directive.partition("=")
        ids = {r.strip().upper() for r in rules.split(",") if r.strip()}
        if not ids:
            continue
        out.setdefault(i, set()).update(ids)
        if line[:pos].rstrip().rstrip("#").strip() == "":
            # own-line directive: also covers the following line
            out.setdefault(i + 1, set()).update(ids)
    return out
