"""Registry extractors for the v5 coverage rules (JX020-JX023).

The distributed-runtime subsystems keep three registries the v5 rules
cross-check code against, none of which lives in an importable constant:

* the **fault-point table** — the reST table in ``parallel/faults.py``'s
  module docstring is the authoritative list of injection points (the
  docs, the chaos tests and the sites all reference it);
* the **event registry** — every ``CycloneEvent`` subclass, discovered
  from class bases across the analyzed set;
* the **lifecycle registry** — classes with a stop/close/shutdown
  discipline, discovered from methods that latch a stop flag
  (``self._stop = True`` / ``self._stop.set()``) and from sibling
  methods that test the flag and raise.

Everything here is pure ``ast`` over already-parsed modules, cached per
:class:`~.engine.AnalysisContext` (the jx019 conf-registry pattern): one
extraction pass serves every rule and every module's ``check()``.
"""

from __future__ import annotations

import ast
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from cycloneml_tpu.analysis.astutil import (call_name, dotted_name,
                                            iter_own_statements,
                                            last_component)

# -- fault-point registry ------------------------------------------------------

#: a table row: the backticked point name anchored at column 0, dotted
#: (prose mentions like ````inject()```` carry no dot / carry parens)
_ROW_RE = re.compile(r"^``([A-Za-z0-9_]+(?:\.[A-Za-z0-9_]+)+)``(?:\s|$)")
_DELIM_RE = re.compile(r"^=+\s+=+\s*$")

#: call names that fire an injection point (``faults.inject`` is the
#: public site API; ``fire`` is the injector's internal dispatch)
SITE_CALLS = {"inject", "fire"}


@dataclass
class FaultPoint:
    name: str
    module_path: str
    line: int           # 1-based file line of the table row


@dataclass
class InjectionSite:
    point: str
    node: ast.Call
    module_path: str
    function: str       # enclosing function qualname ("" = module level)


@dataclass
class FaultRegistry:
    points: Dict[str, FaultPoint] = field(default_factory=dict)
    #: the module(s) hosting a table — findings for unfired points anchor
    #: on the table row in its own module
    table_modules: Set[str] = field(default_factory=set)


def _module_docstring(tree: ast.Module) -> Optional[ast.Constant]:
    if tree.body and isinstance(tree.body[0], ast.Expr) \
            and isinstance(tree.body[0].value, ast.Constant) \
            and isinstance(tree.body[0].value.value, str):
        return tree.body[0].value
    return None


def _hosts_fault_table(tree: ast.Module) -> bool:
    """A module owns a fault-point table when it defines the injection
    machinery itself — the public ``inject`` entry or the injector."""
    for stmt in tree.body:
        if isinstance(stmt, ast.FunctionDef) and stmt.name == "inject":
            return True
        if isinstance(stmt, ast.ClassDef) and stmt.name == "FaultInjector":
            return True
    return False


def parse_fault_table(doc: str, first_line: int) -> List[Tuple[str, int]]:
    """``(point, file_line)`` rows of the ====-delimited docstring table.

    Rows are only read between the table delimiters (the opening rule,
    the header rule, the closing rule) so backticked names elsewhere in
    the docstring never register points."""
    out: List[Tuple[str, int]] = []
    delims = 0
    for i, line in enumerate(doc.split("\n")):
        if _DELIM_RE.match(line.strip()):
            delims += 1
            continue
        if not 1 <= delims <= 2:
            continue
        m = _ROW_RE.match(line)
        if m:
            out.append((m.group(1), first_line + i))
    return out


def fault_registry(ctx) -> FaultRegistry:
    """Fault points registered anywhere in the analyzed set (cached)."""
    cached = getattr(ctx, "_fault_registry", None)
    if cached is not None and getattr(ctx, "_fault_registry_ctx", None) is ctx:
        return cached
    reg = FaultRegistry()
    for mod in ctx.modules.values():
        # cheap text gate: tables are rare, backticks + '=' rules rarer
        if not any("====" in ln for ln in mod.source_lines):
            continue
        doc = _module_docstring(mod.tree)
        if doc is None or not _hosts_fault_table(mod.tree):
            continue
        reg.table_modules.add(mod.path)
        for name, line in parse_fault_table(doc.value, doc.lineno):
            reg.points.setdefault(name, FaultPoint(name, mod.path, line))
    ctx._fault_registry = reg
    ctx._fault_registry_ctx = ctx
    return reg


def is_injection_call(node: ast.AST) -> Optional[str]:
    """The dotted point name when ``node`` is ``faults.inject("a.b", ...)``
    / ``inj.fire("a.b", ...)`` — a dotted string literal as the first
    argument; ``fire(point, **info)`` forwarding a variable is not a
    site."""
    if not isinstance(node, ast.Call):
        return None
    base = last_component(call_name(node) or "")
    if base not in SITE_CALLS:
        return None
    if not node.args or not isinstance(node.args[0], ast.Constant) \
            or not isinstance(node.args[0].value, str):
        return None
    point = node.args[0].value
    return point if "." in point else None


def injection_sites(ctx) -> List[InjectionSite]:
    """Every literal injection site in the analyzed set (cached)."""
    cached = getattr(ctx, "_fault_sites", None)
    if cached is not None and getattr(ctx, "_fault_sites_ctx", None) is ctx:
        return cached
    sites: List[InjectionSite] = []
    for mod in ctx.modules.values():
        if not any(".inject(" in ln or ".fire(" in ln or "inject(" in ln
                   for ln in mod.source_lines):
            continue
        owners = _node_owners(mod)
        for node in ast.walk(mod.tree):
            point = is_injection_call(node)
            if point is not None:
                sites.append(InjectionSite(point, node, mod.path,
                                           owners.get(id(node), "")))
    ctx._fault_sites = sites
    ctx._fault_sites_ctx = ctx
    return sites


def _node_owners(mod) -> Dict[int, str]:
    """id(node) -> enclosing function qualname, for finding attribution."""
    out: Dict[int, str] = {}
    for fn in mod.functions:
        for node in iter_own_statements(fn.node):
            out[id(node)] = fn.qualname
    return out


# -- event registry ------------------------------------------------------------

EVENT_BASE = "CycloneEvent"


def event_registry(ctx) -> Dict[str, str]:
    """Event class name -> defining module path: the transitive subclass
    closure of ``CycloneEvent`` across the analyzed set (cached). Empty
    when the base class itself is not in the set — no registry, nothing
    to cross-check."""
    cached = getattr(ctx, "_event_registry", None)
    if cached is not None and getattr(ctx, "_event_registry_ctx", None) is ctx:
        return cached
    bases_of: Dict[str, Set[str]] = {}
    defined_in: Dict[str, str] = {}
    base_defined = False
    # no text pre-gate here: a second-level subclass
    # (``class Ghost(BlocksMigrated)``) lives in a module that never
    # spells the base name — only the closure below can see it
    for mod in ctx.modules.values():
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            if node.name == EVENT_BASE:
                base_defined = True
                continue
            names = {last_component(dotted_name(b)) for b in node.bases}
            bases_of.setdefault(node.name, set()).update(
                n for n in names if n)
            defined_in.setdefault(node.name, mod.path)
    registry: Dict[str, str] = {}
    if base_defined:
        known = {EVENT_BASE}
        changed = True
        while changed:     # transitive: PrecisionFallback(CycloneEvent) ...
            changed = False
            for name, bases in bases_of.items():
                if name not in registry and bases & known:
                    registry[name] = defined_in[name]
                    known.add(name)
                    changed = True
    ctx._event_registry = registry
    ctx._event_registry_ctx = ctx
    return registry


def handled_event_names(ctx) -> Set[str]:
    """Event names that appear as an exact string literal anywhere in the
    analyzed set — the handled set (status-store ``elif`` branches,
    journal filters, webui rollups all dispatch on the literal type
    name; ``to_json`` writes it as ``d["Event"]``)."""
    cached = getattr(ctx, "_event_handled", None)
    if cached is not None and getattr(ctx, "_event_handled_ctx", None) is ctx:
        return cached
    registry = event_registry(ctx)
    handled: Set[str] = set()
    if registry:
        names = set(registry)
        for mod in ctx.modules.values():
            if not any(n in ln for ln in mod.source_lines for n in names):
                continue
            for node in ast.walk(mod.tree):
                if isinstance(node, ast.Constant) \
                        and isinstance(node.value, str) \
                        and node.value in names:
                    handled.add(node.value)
            if handled == names:
                break
    ctx._event_handled = handled
    ctx._event_handled_ctx = ctx
    return handled


# -- lifecycle registry --------------------------------------------------------

STOP_METHOD_NAMES = {"stop", "close", "shutdown"}


@dataclass
class LifecycleClass:
    name: str
    module_path: str
    #: flag attribute -> "bool" (``self._stop = True``) or "event"
    #: (``self._stop.set()``)
    flags: Dict[str, str] = field(default_factory=dict)
    #: methods that latch a stop flag (the teardown entry points)
    stop_methods: Set[str] = field(default_factory=set)
    #: method name -> the flag it tests before raising (dispatch guards)
    guarded: Dict[str, str] = field(default_factory=dict)


def _self_attr(node: ast.AST) -> Optional[str]:
    """``X`` for a ``self.X`` attribute node, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _flag_transitions(method: ast.AST) -> Dict[str, str]:
    """Flags this method latches: ``self.X = True`` -> bool flag,
    ``self.X.set()`` -> event flag."""
    out: Dict[str, str] = {}
    for node in iter_own_statements(method):
        if isinstance(node, ast.Assign) \
                and isinstance(node.value, ast.Constant) \
                and node.value.value is True:
            for t in node.targets:
                attr = _self_attr(t)
                if attr is not None:
                    out[attr] = "bool"
        elif isinstance(node, ast.Call) \
                and isinstance(node.func, ast.Attribute) \
                and node.func.attr == "set" and not node.args:
            attr = _self_attr(node.func.value)
            if attr is not None:
                out[attr] = "event"
    return out


def _guard_flag(method: ast.AST, flags: Dict[str, str]) -> Optional[str]:
    """The stop flag this method tests before raising, if any: an ``if``
    whose test reads ``self.X`` (bool) / ``self.X.is_set()`` (event) and
    whose body raises — the dispatch-after-stop rejection idiom."""
    for node in iter_own_statements(method):
        if not isinstance(node, ast.If):
            continue
        tested: Optional[str] = None
        for sub in ast.walk(node.test):
            attr = _self_attr(sub)
            if attr in flags and flags[attr] == "bool":
                tested = attr
            elif isinstance(sub, ast.Call) \
                    and isinstance(sub.func, ast.Attribute) \
                    and sub.func.attr == "is_set":
                attr = _self_attr(sub.func.value)
                if attr in flags:
                    tested = attr
        if tested is None:
            continue
        if any(isinstance(s, ast.Raise) for s in ast.walk(node)):
            return tested
    return None


def lifecycle_registry(ctx) -> Dict[str, LifecycleClass]:
    """Class name -> lifecycle model, discovered from the stop/close
    discipline across the analyzed set (cached). A class qualifies when
    a stop/close/shutdown method latches a flag; same-named classes in
    different modules keep the first discovery (the resolver's own
    merge policy for ambiguous names)."""
    cached = getattr(ctx, "_lifecycle_registry", None)
    if cached is not None \
            and getattr(ctx, "_lifecycle_registry_ctx", None) is ctx:
        return cached
    registry: Dict[str, LifecycleClass] = {}
    for mod in ctx.modules.values():
        if not any("def stop" in ln or "def close" in ln
                   or "def shutdown" in ln for ln in mod.source_lines):
            continue
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.ClassDef):
                continue
            methods = [s for s in node.body
                       if isinstance(s, (ast.FunctionDef,
                                         ast.AsyncFunctionDef))]
            lc = LifecycleClass(node.name, mod.path)
            for m in methods:
                if m.name in STOP_METHOD_NAMES:
                    latched = _flag_transitions(m)
                    if latched:
                        lc.flags.update(latched)
                        lc.stop_methods.add(m.name)
            if not lc.stop_methods:
                continue
            for m in methods:
                if m.name in lc.stop_methods:
                    continue
                flag = _guard_flag(m, lc.flags)
                if flag is not None:
                    lc.guarded[m.name] = flag
            registry.setdefault(node.name, lc)
    ctx._lifecycle_registry = registry
    ctx._lifecycle_registry_ctx = ctx
    return registry
