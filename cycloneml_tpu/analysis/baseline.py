"""Baseline handling: grandfathered findings that predate a rule.

The baseline is a committed JSON file mapping finding fingerprints
``rule:path:function`` to an allowed occurrence count. Matching on the
enclosing function instead of the line number keeps the baseline stable
across unrelated edits; a refactor that *adds* occurrences inside an
already-baselined function still fails, which is the intent — new hazards
in old code are still new hazards.

Regenerate with::

    python -m cycloneml_tpu.analysis cycloneml_tpu --write-baseline \
        cycloneml_tpu/analysis/baseline.json
"""

from __future__ import annotations

import collections
import json
from typing import Dict, List, Tuple

from cycloneml_tpu.analysis.engine import Finding


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    out: Dict[str, int] = {}
    for entry in data.get("findings", []):
        fp = f"{entry['rule']}:{entry['path']}:{entry.get('function', '')}"
        out[fp] = out.get(fp, 0) + int(entry.get("count", 1))
    return out


def write_baseline(path: str, findings: List[Finding]) -> None:
    counts = collections.Counter(f.fingerprint for f in findings)
    entries = []
    for fp in sorted(counts):
        rule, fpath, function = fp.split(":", 2)
        entries.append({"rule": rule, "path": fpath, "function": function,
                        "count": counts[fp]})
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries}, fh, indent=2,
                  sort_keys=True)
        fh.write("\n")


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, int]) -> Tuple[List[Finding], int]:
    """Return (new findings, number grandfathered). Within a fingerprint,
    the first ``count`` occurrences (by line order) are grandfathered."""
    budget = dict(baseline)
    new: List[Finding] = []
    grandfathered = 0
    for f in findings:
        left = budget.get(f.fingerprint, 0)
        if left > 0:
            budget[f.fingerprint] = left - 1
            grandfathered += 1
        else:
            new.append(f)
    return new, grandfathered
