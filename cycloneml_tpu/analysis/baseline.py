"""Baseline handling: grandfathered findings that predate a rule.

The baseline is a committed JSON file mapping finding fingerprints
``rule:path:function`` to an allowed occurrence count. Matching on the
enclosing function instead of the line number keeps the baseline stable
across unrelated edits; a refactor that *adds* occurrences inside an
already-baselined function still fails, which is the intent — new hazards
in old code are still new hazards.

**The ratchet.** The file also carries ``"ratchet"``: the total number of
grandfathered occurrences the baseline is ALLOWED to hold. Regenerating
may shrink the baseline freely (the ratchet follows it down), but never
grow it past the committed ratchet — technical debt only monotonically
decreases. Growing requires the explicit ``--grow-baseline`` escape
hatch, with the justification in the PR description. The committed
baseline is empty with ratchet 0: every finding so far has been FIXED,
and the ratchet keeps it that way.

Regenerate with::

    python -m cycloneml_tpu.analysis cycloneml_tpu --write-baseline \
        cycloneml_tpu/analysis/baseline.json
"""

from __future__ import annotations

import collections
import json
import os
from typing import Dict, List, Optional, Tuple

from cycloneml_tpu.analysis.engine import Finding


class BaselineRatchetError(ValueError):
    """A regeneration tried to GROW the baseline past its ratchet."""


def load_baseline(path: str) -> Dict[str, int]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    out: Dict[str, int] = {}
    for entry in data.get("findings", []):
        fp = f"{entry['rule']}:{entry['path']}:{entry.get('function', '')}"
        out[fp] = out.get(fp, 0) + int(entry.get("count", 1))
    return out


def load_ratchet(path: str) -> Optional[int]:
    """The committed ratchet, or the entry total for pre-ratchet files
    (a PR touching such a file adopts its current size as the ceiling).
    None when the file does not exist."""
    if not os.path.exists(path):
        return None
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    if "ratchet" in data:
        return int(data["ratchet"])
    return sum(int(e.get("count", 1)) for e in data.get("findings", []))


def check_ratchet(path: str) -> Tuple[int, int]:
    """(total grandfathered occurrences, ratchet) for a baseline file;
    raises :class:`BaselineRatchetError` when the entries exceed the
    ratchet (a hand-edit grew the baseline without the escape hatch)."""
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    total = sum(int(e.get("count", 1)) for e in data.get("findings", []))
    ratchet = int(data.get("ratchet", total))
    if total > ratchet:
        raise BaselineRatchetError(
            f"baseline {path} holds {total} grandfathered occurrence(s) "
            f"but its ratchet is {ratchet} — the baseline may shrink, "
            f"never grow (regenerate with --grow-baseline and justify in "
            f"the PR if this is deliberate)")
    return total, ratchet


def write_baseline(path: str, findings: List[Finding],
                   allow_grow: bool = False) -> None:
    counts = collections.Counter(f.fingerprint for f in findings)
    total = sum(counts.values())
    ratchet = load_ratchet(path)
    if ratchet is not None and total > ratchet and not allow_grow:
        raise BaselineRatchetError(
            f"refusing to grow the baseline: {total} occurrence(s) > "
            f"ratchet {ratchet} ({path}). Fix the findings, or pass "
            f"--grow-baseline and justify the new debt in the PR")
    entries = []
    for fp in sorted(counts):
        rule, fpath, function = fp.split(":", 2)
        entries.append({"rule": rule, "path": fpath, "function": function,
                        "count": counts[fp]})
    # the ratchet follows the baseline DOWN; growing resets it only
    # through the explicit escape hatch
    new_ratchet = (total if ratchet is None or allow_grow
                   else min(ratchet, total))
    with open(path, "w", encoding="utf-8") as fh:
        json.dump({"version": 1, "findings": entries,
                   "ratchet": new_ratchet}, fh, indent=2, sort_keys=True)
        fh.write("\n")


def apply_baseline(findings: List[Finding],
                   baseline: Dict[str, int]) -> Tuple[List[Finding], int]:
    """Return (new findings, number grandfathered). Within a fingerprint,
    the first ``count`` occurrences (by line order) are grandfathered."""
    budget = dict(baseline)
    new: List[Finding] = []
    grandfathered = 0
    for f in findings:
        left = budget.get(f.fingerprint, 0)
        if left > 0:
            budget[f.fingerprint] = left - 1
            grandfathered += 1
        else:
            new.append(f)
    return new, grandfathered
