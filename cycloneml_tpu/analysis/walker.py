"""Terminator-aware source-order block walker (shared CFG-lite).

JX009 (use-after-donate) grew, fixture by fixture, a careful source-order
scan over one function body: branch may-merges where terminated arms
contribute nothing, loop second-iteration reasoning, ``with`` transparency,
``try`` terminating only when every path does, ``break``/``continue``/
``return``/``raise`` distinguished because they reach *different* code.
JX013 (future-obligation leak) needs exactly the same machinery with the
opposite polarity — obligations *pending* instead of buffers *dead* — so
the walker now lives here, once, as a base class with rule hooks.

The abstract state is ``self.state``: a ``name -> AST node`` map (the
hazard site for that name). The contract both rules share:

* ``visit_expr`` (hook) scans an expression in evaluation order and
  mutates ``state`` (JX009: reads checked + donations added; JX013:
  obligation sources added + discharges removed).
* Rebinding a name drops it from ``state`` (``bind``; override to change).
* ``If`` merges branches with a **may-union**; a branch that terminated
  (return/raise/break/continue) contributes nothing to the fall-through.
* Loops snapshot state, run the body once, and hand the rule the result
  via ``on_loop_body_end`` (JX009's "second iteration re-dispatches"
  check); when every body path exits the function, fall-through state is
  the zero-iteration snapshot.
* ``with`` neither catches nor redirects control flow.
* ``try`` terminates only when the no-exception path AND every handler
  do; ``finally`` dominates. Protection is control-flow-accurate: an
  explicit ``raise`` is protected by an enclosing ``try`` with handlers
  OR a ``finally`` (either may yet do the right thing), but a ``return``
  is protected ONLY by a ``finally`` — handlers never run on a clean
  return, so a hazard reaching a ``return`` inside ``try/except`` is as
  real as one outside.
* ``on_exit`` (hook) fires at every unprotected function exit: each
  ``return`` (after its value is visited), each unprotected ``raise``,
  and the end-of-body fall-through — where JX013 reports what is still
  pending. JX009 leaves it empty.

Terminator kinds returned by ``run_block``/``run_stmt``: ``"exit"``
(return/raise), ``"break"``, ``"loop"`` (continue), or None (falls
through). "Weakest terminator wins" when merging: a ``loop`` path means
the next iteration is still reachable, a ``break`` path means post-loop
code is.
"""

from __future__ import annotations

import ast
from typing import Dict, Optional

from cycloneml_tpu.analysis.astutil import assigned_names

NESTED_DEFS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)

#: merge order for competing terminators — weakest (most code still
#: reachable) first
TERMINATOR_ORDER = ("loop", "break", "exit")


def weakest(kinds) -> Optional[str]:
    for kind in TERMINATOR_ORDER:
        if kind in kinds:
            return kind
    return None


class BlockWalker:
    """Subclass, implement ``visit_expr`` (and the hooks you need), then
    call :meth:`walk` with a function body."""

    def __init__(self):
        self.state: Dict[str, ast.AST] = {}
        self._handler_depth = 0   # enclosing trys with except handlers
        self._finally_depth = 0   # enclosing trys with a finally

    def _return_protected(self) -> bool:
        """A clean return runs ONLY enclosing ``finally`` blocks."""
        return self._finally_depth > 0

    def _raise_protected(self) -> bool:
        """A raise may be caught by a handler or cleaned up in finally."""
        return self._handler_depth > 0 or self._finally_depth > 0

    # -- hooks ---------------------------------------------------------------

    def visit_expr(self, expr: ast.AST) -> None:
        """Scan one expression in evaluation order, mutating ``state``."""
        raise NotImplementedError

    def bind(self, target: ast.AST) -> None:
        """An assignment target rebinding names: default drops them."""
        for n in assigned_names(target):
            self.state.pop(n, None)

    def on_loop_body_end(self, stmt: ast.AST, term: Optional[str],
                         entered_with: set) -> None:
        """After one abstract body iteration of ``stmt`` (For/While).
        ``entered_with`` is the set of names in ``state`` when the loop
        was entered; ``term`` is how the body terminated."""

    def on_exit(self, stmt: Optional[ast.AST], kind: str) -> None:
        """An unprotected function exit: ``kind`` is ``"return"``,
        ``"raise"``, or ``"end"`` (fall-through; ``stmt`` is None)."""

    # -- driver --------------------------------------------------------------

    def walk(self, body) -> Optional[str]:
        term = self.run_block(body)
        if not term:
            self.on_exit(None, "end")
        return term

    def run_block(self, body) -> Optional[str]:
        terminated: Optional[str] = None
        for stmt in body:
            if terminated:
                break
            terminated = self.run_stmt(stmt)
        return terminated

    def run_stmt(self, stmt: ast.AST) -> Optional[str]:
        state = self.state
        if isinstance(stmt, NESTED_DEFS):
            return None
        if isinstance(stmt, ast.Assign):
            self.visit_expr(stmt.value)
            for t in stmt.targets:
                self.bind(t)
            return None
        if isinstance(stmt, ast.AnnAssign):
            if stmt.value is not None:
                self.visit_expr(stmt.value)
            self.bind(stmt.target)
            return None
        if isinstance(stmt, ast.AugAssign):
            self.visit_expr(stmt.value)
            # `x += v` READS x before rebinding it
            if isinstance(stmt.target, ast.Name):
                read = ast.copy_location(
                    ast.Name(id=stmt.target.id, ctx=ast.Load()), stmt.target)
                self.visit_expr(read)
            self.bind(stmt.target)
            return None
        if isinstance(stmt, (ast.Expr, ast.Return, ast.Yield)):
            value = getattr(stmt, "value", None)
            if value is not None:
                self.visit_expr(value)
            if isinstance(stmt, ast.Return):
                if not self._return_protected():
                    self.on_exit(stmt, "return")
                return "exit"
            return None
        if isinstance(stmt, (ast.Raise, ast.Break, ast.Continue)):
            if isinstance(stmt, ast.Raise) and stmt.exc is not None:
                self.visit_expr(stmt.exc)
            # continue still reaches the NEXT iteration; return/raise/
            # break leave the loop — and break (unlike return/raise)
            # carries its state into the post-loop code
            if isinstance(stmt, ast.Continue):
                return "loop"
            if isinstance(stmt, ast.Break):
                return "break"
            if not self._raise_protected():
                self.on_exit(stmt, "raise")
            return "exit"
        if isinstance(stmt, ast.If):
            self.visit_expr(stmt.test)
            before = dict(state)
            t_body = self.run_block(stmt.body)
            after_body = dict(state)
            state.clear()
            state.update(before)
            t_else = self.run_block(stmt.orelse)
            after_else = dict(state)
            # may merge; a terminated branch contributes nothing to the
            # fall-through
            state.clear()
            if not t_body:
                state.update(after_body)
            if not t_else:
                state.update(after_else)
            if t_body and t_else:
                return weakest((t_body, t_else))
            return None
        if isinstance(stmt, (ast.For, ast.AsyncFor, ast.While)):
            if isinstance(stmt, (ast.For, ast.AsyncFor)):
                self.visit_expr(stmt.iter)
                self.bind(stmt.target)
            else:
                self.visit_expr(stmt.test)
            before_loop = dict(state)
            entered_with = set(state)
            term = self.run_block(stmt.body)
            self.on_loop_body_end(stmt, term, entered_with)
            if term == "exit":
                # every body path returns/raises: post-loop code is only
                # reachable via the zero-iteration path ("break" paths DO
                # fall into post-loop code and keep theirs)
                state.clear()
                state.update(before_loop)
            self.run_block(stmt.orelse)
            return None
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self.visit_expr(item.context_expr)
                if item.optional_vars is not None:
                    self.bind(item.optional_vars)
            # `with` neither catches nor redirects control flow — a
            # return inside the span idiom still terminates the loop
            return self.run_block(stmt.body)
        if isinstance(stmt, ast.Try):
            has_handlers = bool(stmt.handlers)
            has_finally = bool(stmt.finalbody)
            if has_finally:
                self._finally_depth += 1
            # handlers cover the BODY only; finally covers body, handlers
            # and orelse alike
            if has_handlers:
                self._handler_depth += 1
            t_body = self.run_block(stmt.body)
            if has_handlers:
                self._handler_depth -= 1
            handler_terms = [self.run_block(h.body) for h in stmt.handlers]
            t_orelse = self.run_block(stmt.orelse)
            if has_finally:
                self._finally_depth -= 1
            t_final = self.run_block(stmt.finalbody)
            if t_final:
                return t_final
            # no-exception path terminates via body or orelse; each
            # caught-exception path via its handler — the try terminates
            # only when EVERY path does (weakest kind wins)
            terms = [t_body or t_orelse] + handler_terms
            if all(terms):
                return weakest(terms)
            return None
        if isinstance(stmt, ast.Delete):
            for t in stmt.targets:
                self.bind(t)
            return None
        for child in ast.iter_child_nodes(stmt):
            self.visit_expr(child)
        return None
