"""graftlint CLI.

::

    python -m cycloneml_tpu.analysis <paths...> [options]

Options:
    --json                 machine-readable output
    --baseline FILE        subtract grandfathered findings (exit 0 when
                           everything new is clean)
    --write-baseline FILE  write the current findings as the new baseline
                           and exit 0 (regeneration workflow)
    --rules JX001,JX003    run a subset of the rule pack
    --list-rules           print the rule pack and exit

Exit codes: 0 clean (after baseline), 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import sys

from cycloneml_tpu.analysis import baseline as baseline_mod
from cycloneml_tpu.analysis.engine import analyze_paths, collect_files
from cycloneml_tpu.analysis.report import render_json, render_text
from cycloneml_tpu.analysis.rules import ALL_RULES, default_rules, rules_by_id


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cycloneml_tpu.analysis",
        description="graftlint: AST-based JAX/TPU hazard analyzer")
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument("--baseline", metavar="FILE", default=None)
    parser.add_argument("--write-baseline", metavar="FILE", default=None)
    parser.add_argument("--rules", metavar="IDS", default=None,
                        help="comma-separated rule ids to run")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            doc = (sys.modules[cls.__module__].__doc__ or "").strip()
            first_line = doc.splitlines()[0] if doc else ""
            print(f"{cls.rule_id}  {first_line}")
        return 0
    if not args.paths:
        parser.print_usage(sys.stderr)
        return 2

    if args.rules:
        wanted = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        known = {cls.rule_id for cls in ALL_RULES}
        unknown = [r for r in wanted if r not in known]
        if unknown or not wanted:
            # a typo'd rule id silently not running would be an invisible
            # hole in the gate — fail loudly instead
            print(f"unknown rule id(s): {unknown or args.rules!r}; "
                  f"known: {sorted(known)}", file=sys.stderr)
            return 2
        rules = rules_by_id(wanted)
    else:
        rules = default_rules()

    findings = analyze_paths(args.paths, rules=rules)

    if args.write_baseline:
        baseline_mod.write_baseline(args.write_baseline, findings)
        print(f"wrote {len(findings)} finding(s) to {args.write_baseline}")
        return 0

    grandfathered = 0
    if args.baseline:
        try:
            known = baseline_mod.load_baseline(args.baseline)
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        findings, grandfathered = baseline_mod.apply_baseline(findings, known)

    out = (render_json(findings, grandfathered) if args.as_json
           else render_text(findings, grandfathered,
                            len(collect_files(args.paths))))
    print(out, end="" if args.as_json else "\n")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
