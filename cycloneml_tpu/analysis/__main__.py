"""graftlint CLI.

::

    python -m cycloneml_tpu.analysis <paths...> [options]
    python -m cycloneml_tpu.analysis --changed          # incremental mode

Options:
    --json                 machine-readable output
    --sarif                SARIF 2.1.0 output (CI/code-review inline
                           rendering)
    --baseline FILE        subtract grandfathered findings (exit 0 when
                           everything new is clean)
    --write-baseline FILE  write the current findings as the new baseline
                           and exit 0 (regeneration workflow; refuses to
                           GROW the baseline past its ratchet)
    --grow-baseline        escape hatch: allow --write-baseline to grow
                           the baseline (justify in the PR description)
    --changed [BASE]       analyze the full tree for call-graph facts but
                           CHECK/report only files changed per git
                           (worktree+index vs HEAD, plus BASE...HEAD when
                           a ref is given); paths default to cycloneml_tpu
    --cache FILE           parse-cache pickle (default for --changed:
                           .graftlint-cache.pkl; full runs use a cache
                           only when --cache or CYCLONE_LINT_CACHE names
                           one). The CYCLONE_LINT_CACHE env var relocates
                           the cache — CI jobs point it at their restored
                           cache directory
    --no-cache             disable the parse cache
    --rules JX001,JX003    run a subset of the rule pack
    --list-rules           print the rule pack and exit

Exit codes: 0 clean (after baseline), 1 findings, 2 usage error.
"""

from __future__ import annotations

import argparse
import os
import sys

from cycloneml_tpu.analysis import baseline as baseline_mod
from cycloneml_tpu.analysis.engine import analyze_paths, collect_files
from cycloneml_tpu.analysis.report import (render_json, render_sarif,
                                           render_text)
from cycloneml_tpu.analysis.rules import ALL_RULES, default_rules, rules_by_id


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m cycloneml_tpu.analysis",
        description="graftlint: AST + interprocedural-dataflow JAX/TPU "
                    "hazard analyzer")
    parser.add_argument("paths", nargs="*", help="files or directories")
    parser.add_argument("--json", action="store_true", dest="as_json")
    parser.add_argument("--sarif", action="store_true", dest="as_sarif")
    parser.add_argument("--baseline", metavar="FILE", default=None)
    parser.add_argument("--write-baseline", metavar="FILE", default=None)
    parser.add_argument("--grow-baseline", action="store_true")
    parser.add_argument("--changed", nargs="?", const="", default=None,
                        metavar="BASE")
    parser.add_argument("--cache", metavar="FILE", default=None)
    parser.add_argument("--no-cache", action="store_true")
    parser.add_argument("--rules", metavar="IDS", default=None,
                        help="comma-separated rule ids to run")
    parser.add_argument("--list-rules", action="store_true")
    args = parser.parse_args(argv)

    if args.list_rules:
        for cls in ALL_RULES:
            doc = (sys.modules[cls.__module__].__doc__ or "").strip()
            first_line = doc.splitlines()[0] if doc else ""
            print(f"{cls.rule_id}  {first_line}")
        return 0
    if args.as_json and args.as_sarif:
        print("--json and --sarif are mutually exclusive", file=sys.stderr)
        return 2
    if args.changed is not None and args.write_baseline:
        # a git-scoped run only carries the changed files' findings —
        # writing those as the baseline would silently drop every
        # grandfathered entry for unchanged files (and ratchet down past
        # what the full gate still reports)
        print("--write-baseline needs a full-scope run; drop --changed",
              file=sys.stderr)
        return 2
    paths = args.paths
    if not paths:
        if args.changed is None:
            parser.print_usage(sys.stderr)
            return 2
        paths = ["cycloneml_tpu"]   # the tree the gate lints

    if args.rules:
        wanted = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        known = {cls.rule_id for cls in ALL_RULES}
        unknown = [r for r in wanted if r not in known]
        if unknown or not wanted:
            # a typo'd rule id silently not running would be an invisible
            # hole in the gate — fail loudly instead
            print(f"unknown rule id(s): {unknown or args.rules!r}; "
                  f"known: {sorted(known)}", file=sys.stderr)
            return 2
        rules = rules_by_id(wanted)
    else:
        rules = default_rules()

    env_cache = os.environ.get("CYCLONE_LINT_CACHE") or None
    only_paths = None
    cache = None
    if args.changed is not None:
        from cycloneml_tpu.analysis.incremental import (DEFAULT_CACHE,
                                                        ParseCache,
                                                        changed_report_set,
                                                        git_changed_files,
                                                        git_toplevel)
        # the default/relative roots are repo-root-relative by convention;
        # from a subdirectory they would resolve to nothing and the gate
        # would silently lint zero files — anchor them to the toplevel,
        # and treat a root that still doesn't exist as a usage error
        top = git_toplevel()
        if top is not None:
            paths = [os.path.join(top, p)
                     if not os.path.exists(p)
                     and os.path.exists(os.path.join(top, p)) else p
                     for p in paths]
        missing = [p for p in paths if not os.path.exists(p)]
        if missing:
            print(f"--changed: analyzed path(s) do not exist: {missing}",
                  file=sys.stderr)
            return 2
        try:
            changed = git_changed_files(base=args.changed or None)
        except ValueError as e:
            print(str(e), file=sys.stderr)
            return 2
        if changed is None:
            print("--changed: git unavailable, falling back to a full run",
                  file=sys.stderr)
        else:
            only_paths = changed_report_set(paths, changed)
            if not only_paths:
                print("0 changed file(s) under the analyzed paths; "
                      "nothing to lint")
                return 0
        if not args.no_cache:
            cache = ParseCache(args.cache or env_cache or DEFAULT_CACHE)
    elif (args.cache or env_cache) and not args.no_cache:
        # full-scope runs reuse the parse cache too when one is named —
        # CI restores it across jobs via CYCLONE_LINT_CACHE
        from cycloneml_tpu.analysis.incremental import ParseCache
        cache = ParseCache(args.cache or env_cache)

    timings: dict = {}
    findings = analyze_paths(
        paths, rules=rules, only_paths=only_paths,
        module_loader=cache.load_module if cache is not None else None,
        timings=timings)
    if cache is not None:
        cache.save()

    if args.write_baseline:
        try:
            baseline_mod.write_baseline(args.write_baseline, findings,
                                        allow_grow=args.grow_baseline)
        except baseline_mod.BaselineRatchetError as e:
            print(str(e), file=sys.stderr)
            return 2
        print(f"wrote {len(findings)} finding(s) to {args.write_baseline}")
        return 0

    grandfathered = 0
    if args.baseline:
        try:
            # the ratchet is enforced on the READ path too: a hand-edited
            # grown baseline must fail the gate it exists to protect, not
            # silently grandfather new debt
            baseline_mod.check_ratchet(args.baseline)
            known = baseline_mod.load_baseline(args.baseline)
        except baseline_mod.BaselineRatchetError as e:
            print(str(e), file=sys.stderr)
            return 2
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        findings, grandfathered = baseline_mod.apply_baseline(findings, known)

    if args.as_sarif:
        out = render_sarif(findings, grandfathered, timings=timings)
    elif args.as_json:
        out = render_json(findings, grandfathered, timings=timings)
    else:
        scanned = (len(only_paths) if only_paths is not None
                   else len(collect_files(paths)))
        out = render_text(findings, grandfathered, scanned,
                          timings=timings)
    print(out, end="" if (args.as_json or args.as_sarif) else "\n")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
