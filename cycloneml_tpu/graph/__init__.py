from cycloneml_tpu.graph.graph import Graph
from cycloneml_tpu.graph.pregel import pregel

__all__ = ["Graph", "pregel"]
