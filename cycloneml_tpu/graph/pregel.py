"""Pregel BSP loop.

Re-design of GraphX's Pregel (ref: graphx/.../Pregel.scala:59, loop at :115).
The reference iterates: aggregateMessages → joinVertices(vprog) → next active
set, materializing a new message RDD per superstep. Here each superstep is
two compiled shard_map programs (message merge + receipt counts) and a jitted
vertex program; the host loop only reads one scalar (number of active
vertices) per superstep — the same role DAGScheduler's per-iteration job
played, at per-step instead of per-task granularity.

Semantics preserved: initial message delivered to every vertex; a vertex runs
``vprog`` only when it received a message; only vertices that received a
message in superstep t send in t+1; termination when no messages remain or
``max_iter`` is hit.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np


def pregel(graph, vertex_attrs, initial_msg, vprog: Callable,
           send_to_dst: Optional[Callable] = None,
           send_to_src: Optional[Callable] = None,
           merge: str = "sum", max_iter: int = 20):
    """Run Pregel; returns final vertex attrs (device array / pytree).

    - ``vprog(attr, msg, has_msg) -> attr`` — vectorized over all vertices;
      applied only where ``has_msg`` (masking handled here).
    - ``send_*(src_attr, dst_attr, edge_attr, src_active, dst_active) ->
      (msgs, send_mask)`` — per-edge; masked sends get the merge identity.
    - ``merge`` ∈ {sum, min, max}.
    """
    import jax
    import jax.numpy as jnp

    fill = {"sum": 0.0, "min": np.inf, "max": -np.inf}[merge]

    def _wrap(user_fn):
        if user_fn is None:
            return None

        def fn(sa, da, e):
            (s_attr, s_act), (d_attr, d_act) = sa, da
            msgs, mask = user_fn(s_attr, d_attr, e, s_act, d_act)
            m = mask.reshape(mask.shape + (1,) * (msgs.ndim - mask.ndim))
            return jnp.where(m > 0, msgs, jnp.asarray(fill, msgs.dtype))
        return fn

    def _cnt(user_fn):
        if user_fn is None:
            return None

        def fn(sa, da, e):
            (s_attr, s_act), (d_attr, d_act) = sa, da
            _, mask = user_fn(s_attr, d_attr, e, s_act, d_act)
            return mask.astype(jnp.float32)
        return fn

    msg_prog = graph.message_program(_wrap(send_to_dst), _wrap(send_to_src), merge)
    cnt_prog = graph.message_program(_cnt(send_to_dst), _cnt(send_to_src), "sum")

    @jax.jit
    def apply_vprog(attrs, msgs, has):
        new = vprog(attrs, msgs, has)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(
                has.reshape(has.shape + (1,) * (a.ndim - has.ndim)), b, a),
            attrs, new)

    n = graph.n_vertices
    attrs = jax.tree_util.tree_map(jnp.asarray, vertex_attrs)
    # superstep 0: everyone gets the initial message
    init = jnp.broadcast_to(jnp.asarray(initial_msg),
                            (n,) + np.shape(np.asarray(initial_msg)))
    attrs = apply_vprog(attrs, init, jnp.ones(n, dtype=bool))
    active = jnp.ones(n, dtype=jnp.float32)

    for _ in range(max_iter):
        state = (attrs, active)
        counts = cnt_prog(state)
        has = counts > 0
        if not bool(jnp.any(has)):
            break
        msgs = msg_prog(state)
        attrs = apply_vprog(attrs, msgs, has)
        active = has.astype(jnp.float32)
    return attrs
