"""Pregel BSP loop.

Re-design of GraphX's Pregel (ref: graphx/.../Pregel.scala:59, loop at :115).
The reference iterates: aggregateMessages → joinVertices(vprog) → next active
set, materializing a new message RDD per superstep. Here each superstep is
ONE compiled shard_map edge pass (message merge; for sum-merge a receipt
count rides along as an extra channel, for min/max-merge receipt is detected
against the merge identity) plus a jitted vertex program; the host loop reads
one scalar per superstep.

Semantics preserved: initial message delivered to every vertex; a vertex runs
``vprog`` only when it received a message; only vertices that received a
message in superstep t send in t+1; termination when no messages remain or
``max_iter`` is hit.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from cycloneml_tpu.graph.graph import merge_identity


def pregel(graph, vertex_attrs, initial_msg, vprog: Callable,
           send_to_dst: Optional[Callable] = None,
           send_to_src: Optional[Callable] = None,
           merge: str = "sum", max_iter: int = 20):
    """Run Pregel; returns final vertex attrs (device array / pytree).

    - ``vprog(attr, msg, has_msg) -> attr`` — vectorized over all vertices;
      applied only where ``has_msg`` (masking handled here).
    - ``send_*(src_attr, dst_attr, edge_attr, src_active, dst_active) ->
      (msgs, send_mask)`` — per-edge; masked sends get the merge identity.
      (min/max caveat: a deliberately-sent message exactly equal to the merge
      identity is indistinguishable from no message.)
    - ``merge`` ∈ {sum, min, max}.
    """
    import jax
    import jax.numpy as jnp

    if send_to_dst is None and send_to_src is None:
        raise ValueError("need at least one send function")

    shape_box = []  # trailing message shape, captured at trace time

    def _wrap(user_fn):
        if user_fn is None:
            return None

        def fn(sa, da, e):
            (s_attr, s_act), (d_attr, d_act) = sa, da
            msgs, mask = user_fn(s_attr, d_attr, e, s_act, d_act)
            if not shape_box:
                shape_box.append(msgs.shape[1:])
            ident = merge_identity(msgs.dtype, merge)
            m = mask.reshape(mask.shape + (1,) * (msgs.ndim - mask.ndim))
            masked = jnp.where(m > 0, msgs, ident)
            if merge != "sum":
                return masked
            # receipt count rides as an extra channel: one edge pass total
            flat = masked.reshape((masked.shape[0], -1))
            cnt = (mask > 0).astype(flat.dtype)[:, None]
            return jnp.concatenate([flat, cnt], axis=1)
        return fn

    prog = graph.message_program(_wrap(send_to_dst), _wrap(send_to_src), merge)

    @jax.jit
    def apply_vprog(attrs, msgs, has):
        new = vprog(attrs, msgs, has)
        return jax.tree_util.tree_map(
            lambda a, b: jnp.where(
                has.reshape(has.shape + (1,) * (a.ndim - has.ndim)), b, a),
            attrs, new)

    n = graph.n_vertices
    attrs = jax.tree_util.tree_map(jnp.asarray, vertex_attrs)
    # superstep 0: everyone gets the initial message
    init = jnp.broadcast_to(jnp.asarray(initial_msg),
                            (n,) + np.shape(np.asarray(initial_msg)))
    attrs = apply_vprog(attrs, init, jnp.ones(n, dtype=bool))
    active = jnp.ones(n, dtype=jnp.float32)

    for _ in range(max_iter):
        merged = prog((attrs, active))
        if merge == "sum":
            has = merged[:, -1] > 0
            msgs = merged[:, :-1].reshape((n,) + shape_box[0])
        else:
            cmp = merged != merge_identity(merged.dtype, merge)
            has = cmp.reshape(n, -1).any(axis=1)
            msgs = merged
        if not bool(jnp.any(has)):
            break
        attrs = apply_vprog(attrs, msgs, has)
        active = has.astype(jnp.float32)
    return attrs
