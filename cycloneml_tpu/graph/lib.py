"""Graph algorithm library.

Re-designs of graphx/lib (ref: graphx/src/main/scala/org/apache/spark/graphx/
lib/): PageRank, ConnectedComponents, StronglyConnectedComponents,
LabelPropagation, ShortestPaths, TriangleCount, SVDPlusPlus. Each algorithm
compiles its message program(s) once and iterates a host loop reading only a
convergence scalar — the Pregel pattern without per-superstep RDD
materialization. Closure-based algorithms (SCC, triangles) instead use the
dense adjacency form: transitive closure and triangle counting are pure MXU
matmul chains, which beats edge-iteration on TPU for graphs that fit O(n²)
HBM.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import numpy as np

from cycloneml_tpu.graph.graph import Graph


def pagerank(graph: Graph, num_iter: int = 20, reset_prob: float = 0.15,
             tol: Optional[float] = None,
             personalized_src: Optional[int] = None) -> np.ndarray:
    """PageRank (ref lib/PageRank.scala — run/runUntilConvergence/
    runWithOptions personalized). Returns per-vertex ranks (Spark semantics:
    ranks sum ≈ n, each init 1.0; rank = resetProb + (1−resetProb)·Σ
    incoming rank/outDegree)."""
    import jax
    import jax.numpy as jnp

    n = graph.n_vertices
    out_deg = jnp.asarray(graph.out_degrees())
    inv_deg = jnp.where(out_deg > 0, 1.0 / jnp.maximum(out_deg, 1.0), 0.0)
    prog = graph.message_program(
        to_dst=lambda sa, da, e: sa, merge="sum")

    if personalized_src is None:
        reset = jnp.full((n,), reset_prob, dtype=jnp.float32)
    else:
        reset = jnp.zeros((n,), dtype=jnp.float32).at[personalized_src].set(reset_prob)

    ranks = jnp.ones((n,), dtype=jnp.float32)
    for _ in range(num_iter):
        contrib = prog(ranks * inv_deg)
        new = reset + (1.0 - reset_prob) * contrib
        if tol is not None and float(jnp.max(jnp.abs(new - ranks))) < tol:
            ranks = new
            break
        ranks = new
    return np.asarray(ranks)


def connected_components(graph: Graph, max_iter: int = 100) -> np.ndarray:
    """Connected components: each vertex labeled with the smallest vertex id
    in its component, edges treated as undirected (ref
    lib/ConnectedComponents.scala — Pregel with min-merge)."""
    import jax.numpy as jnp

    prog = graph.message_program(
        to_dst=lambda sa, da, e: sa, to_src=lambda sa, da, e: da, merge="min")
    labels = jnp.arange(graph.n_vertices, dtype=jnp.int32)
    for _ in range(max_iter):
        msg = prog(labels)
        new = jnp.minimum(labels, msg)
        if bool(jnp.all(new == labels)):
            break
        labels = new
    return np.asarray(labels).astype(np.int64)


def label_propagation(graph: Graph, max_iter: int = 5) -> np.ndarray:
    """Community detection by label propagation (ref
    lib/LabelPropagation.scala): each vertex adopts the most frequent label
    among neighbors; ties break to the smallest label (deterministic, where
    the reference's hashmap order is not). Dense (n_vertices)-wide histogram
    messages — one segment-sum per superstep."""
    import jax
    import jax.numpy as jnp

    n = graph.n_vertices
    onehot = lambda lab: jax.nn.one_hot(lab, n, dtype=jnp.float32)
    prog = graph.message_program(
        to_dst=lambda sa, da, e: onehot(sa),
        to_src=lambda sa, da, e: onehot(da), merge="sum")
    labels = jnp.arange(n, dtype=jnp.int32)
    for _ in range(max_iter):
        counts = prog(labels)  # (n, n) label histogram per vertex
        total = counts.sum(axis=1)
        best = jnp.argmax(counts, axis=1).astype(jnp.int32)  # first max = min label
        labels = jnp.where(total > 0, best, labels)
    return np.asarray(labels).astype(np.int64)


def shortest_paths(graph: Graph, landmarks: Sequence[int],
                   max_iter: int = 0) -> np.ndarray:
    """Hop-count shortest path distances to landmark vertices following edge
    direction (ref lib/ShortestPaths.scala — messages flow dst→src with
    incremented maps). Returns (n_vertices, n_landmarks); unreachable = inf."""
    import jax.numpy as jnp

    n = graph.n_vertices
    lm = np.asarray(list(landmarks), dtype=np.int64)
    dist = np.full((n, len(lm)), np.inf, dtype=np.float32)
    dist[lm, np.arange(len(lm))] = 0.0
    dist = jnp.asarray(dist)
    prog = graph.message_program(
        to_src=lambda sa, da, e: da + 1.0, merge="min")
    for _ in range(max_iter or n):
        new = jnp.minimum(dist, prog(dist))
        if bool(jnp.all(new == dist)):
            break
        dist = new
    return np.asarray(dist)


def triangle_count(graph: Graph) -> np.ndarray:
    """Per-vertex triangle counts (ref lib/TriangleCount.scala — the
    reference canonicalizes then intersects neighbor sets per edge; on TPU
    the count is diag(A³)/2 for the symmetrized loop-free adjacency: two
    MXU matmuls)."""
    import jax
    import jax.numpy as jnp

    a = graph.adjacency(symmetric=True)

    @jax.jit
    def tri(a):
        return jnp.sum(jnp.dot(a, a, precision=jax.lax.Precision.HIGHEST) * a,
                       axis=1) / 2.0

    return np.asarray(tri(a)).astype(np.int64)


def strongly_connected_components(graph: Graph) -> np.ndarray:
    """SCC labels (smallest vertex id per component). The reference
    (lib/StronglyConnectedComponents.scala) runs iterative trim + forward/
    backward Pregel coloring; the TPU form computes the boolean transitive
    closure by log₂(n) squarings of (I ∨ A) — matmul chains on the MXU —
    then labels v with min{j : v⇝j ∧ j⇝v}."""
    import jax
    import jax.numpy as jnp

    n = graph.n_vertices
    a = np.zeros((n, n), dtype=np.float32)
    a[graph._h_src, graph._h_dst] = 1.0
    np.fill_diagonal(a, 1.0)

    @jax.jit
    def square(r):
        rr = jnp.dot(r, r, precision=jax.lax.Precision.HIGHEST)
        return jnp.minimum(rr + r, 1.0) > 0

    r = jnp.asarray(a) > 0
    steps = max(1, int(np.ceil(np.log2(max(n, 2)))))
    for _ in range(steps):
        r = square(r.astype(jnp.float32))
    m = jnp.logical_and(r, r.T)
    labels = jnp.argmax(m, axis=1)  # first True = smallest mutual-reach id
    return np.asarray(labels).astype(np.int64)


def svd_plus_plus(graph: Graph, rank: int = 8, max_iter: int = 10,
                  min_val: float = 0.0, max_val: float = 5.0,
                  gamma1: float = 0.007, gamma2: float = 0.007,
                  gamma6: float = 0.005, gamma7: float = 0.015,
                  seed: int = 0) -> Dict[str, np.ndarray]:
    """SVD++ collaborative filtering on a bipartite rating graph
    (ref lib/SVDPlusPlus.scala; Koren KDD'08). Edges are (user → item) with
    rating attrs. The reference does per-edge stochastic updates inside
    Pregel supersteps; here each epoch is a *batch* gradient step built from
    four message programs (neighbor-factor sums, error back-propagation to
    p/q/y and biases) — deterministic and MXU-batched. Returns factors,
    biases, mean and final training RMSE."""
    import jax
    import jax.numpy as jnp

    n = graph.n_vertices
    rng = np.random.RandomState(seed)
    mu = float(np.average(graph._h_attr))
    out_deg = graph.out_degrees()
    norm_u = np.where(out_deg > 0, 1.0 / np.sqrt(np.maximum(out_deg, 1.0)), 0.0)

    # neighbor y-sum per user: Σ_{j∈N(u)} y_j
    nsum_prog = graph.message_program(
        to_src=lambda sa, da, e: da, merge="sum")

    def _err(sa, da, e):
        pe, q, b = sa["pe"], da["q"], sa["b"] + da["b"]
        pred = mu + b + jnp.sum(pe * q, axis=1)
        pred = jnp.clip(pred, min_val, max_val)
        return e - pred

    grad_q = graph.message_program(
        to_dst=lambda sa, da, e: _err(sa, da, e)[:, None] * sa["pe"], merge="sum")
    grad_p = graph.message_program(
        to_src=lambda sa, da, e: _err(sa, da, e)[:, None] * da["q"], merge="sum")
    grad_b_u = graph.message_program(to_src=lambda sa, da, e: _err(sa, da, e),
                                     merge="sum")
    grad_b_i = graph.message_program(to_dst=lambda sa, da, e: _err(sa, da, e),
                                     merge="sum")
    # y gradient: for each edge (u,j), y_j += norm_u * acc_u where
    # acc_u = Σ_i err(u,i)·q_i (== the p-gradient message)
    grad_y = graph.message_program(
        to_dst=lambda sa, da, e: sa["acc"] * sa["nrm"][:, None], merge="sum")
    sq_err = graph.message_program(
        to_src=lambda sa, da, e: _err(sa, da, e) ** 2, merge="sum")

    p = jnp.asarray(rng.randn(n, rank).astype(np.float32) * 0.1)
    q = jnp.asarray(rng.randn(n, rank).astype(np.float32) * 0.1)
    y = jnp.asarray(rng.randn(n, rank).astype(np.float32) * 0.1)
    b = jnp.zeros((n,), dtype=jnp.float32)
    nrm = jnp.asarray(norm_u.astype(np.float32))

    for _ in range(max_iter):
        nsum = nsum_prog(y)
        pe = p + nrm[:, None] * nsum
        state = {"pe": pe, "q": q, "b": b}
        acc = grad_p(state)
        p = p + gamma2 * (acc - gamma7 * p)
        q = q + gamma2 * (grad_q(state) - gamma7 * q)
        y = y + gamma2 * (grad_y({"pe": pe, "q": q, "b": b, "acc": acc,
                                  "nrm": nrm}) - gamma7 * y)
        b = b + gamma1 * ((grad_b_u(state) + grad_b_i(state)) - gamma6 * b)

    nsum = nsum_prog(y)
    pe = p + nrm[:, None] * nsum
    total_sq = float(jnp.sum(sq_err({"pe": pe, "q": q, "b": b})))
    rmse = float(np.sqrt(total_sq / max(graph.n_edges, 1)))
    return {"p": np.asarray(p), "q": np.asarray(q), "y": np.asarray(y),
            "bias": np.asarray(b), "mean": mu, "rmse": rmse}
