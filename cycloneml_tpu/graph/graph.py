"""Property graphs on the device mesh.

Re-design of GraphX (ref: graphx/src/main/scala/org/apache/spark/graphx/ —
Graph, VertexRDD.scala:55, EdgeRDD.scala:39, impl/GraphImpl.scala:35). The
reference stores a vertex-cut partitioning: edges are hash-partitioned and a
routing table ships vertex attributes to every partition that references
them. The TPU-native layout keeps the same split but exploits the mesh:

- **Edges** are the sharded axis: ``(src, dst, attr, valid)`` arrays padded to
  equal-size shards and row-sharded over ``(replica, data)`` — the analog of
  GraphX's ``EdgePartition`` (ref impl/EdgePartition.scala).
- **Vertex state** is replicated (the degenerate-but-fast routing table: every
  device sees all vertex attributes; gathers are local HBM reads).
- ``aggregate_messages`` — the core primitive (ref Graph.aggregateMessages /
  GraphImpl.aggregateMessagesWithActiveSet) — compiles to one shard_map
  program: per-edge message computation, ``segment_{sum,min,max}`` into a
  dense vertex vector per shard, then a hierarchical ``psum``/``pmin``/
  ``pmax`` over ICI-then-DCN. No shuffle, no routing-table RPC.

Vertex ids are dense ``[0, n)`` indices; ``Graph.from_edges`` remaps arbitrary
int64 ids and keeps the mapping for user-facing results.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence, Tuple

import numpy as np

from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS
from cycloneml_tpu.parallel.collectives import shard_map_compat

_EDGE_ROWS_MULTIPLE = 8


def _pad_edges(arrs: Sequence[np.ndarray], n_shards: int):
    """Pad 1-D edge arrays to a shard-divisible length; returns padded arrays
    plus a float validity mask (padding edges carry valid=0, src=dst=0)."""
    e = arrs[0].shape[0]
    m = n_shards * _EDGE_ROWS_MULTIPLE
    target = max(((e + m - 1) // m) * m, m)
    out = []
    for a in arrs:
        pad = np.zeros((target,) + a.shape[1:], dtype=a.dtype)
        pad[:e] = a
        out.append(pad)
    valid = np.zeros(target, dtype=np.float32)
    valid[:e] = 1.0
    return out, valid


class Graph:
    """Immutable property graph over the mesh (ref graphx/Graph.scala)."""

    def __init__(self, ctx, src: np.ndarray, dst: np.ndarray,
                 edge_attr: Optional[np.ndarray] = None,
                 n_vertices: Optional[int] = None,
                 vertex_ids: Optional[np.ndarray] = None):
        self.ctx = ctx
        rt = ctx.mesh_runtime
        src = np.asarray(src, dtype=np.int32)
        dst = np.asarray(dst, dtype=np.int32)
        self.n_edges = int(src.shape[0])
        if n_vertices is None:
            n_vertices = int(max(src.max(initial=-1), dst.max(initial=-1)) + 1)
        self.n_vertices = n_vertices
        # external-id mapping (identity when built from dense indices)
        self.vertex_ids = (np.arange(n_vertices, dtype=np.int64)
                           if vertex_ids is None else np.asarray(vertex_ids))
        if edge_attr is None:
            edge_attr = np.ones(self.n_edges, dtype=np.float32)
        edge_attr = np.asarray(edge_attr, dtype=np.float32)
        # host copies for structural ops (reverse/subgraph re-shard from here)
        self._h_src, self._h_dst, self._h_attr = src, dst, edge_attr
        (src_p, dst_p, attr_p), valid = _pad_edges(
            [src, dst, edge_attr], rt.data_parallelism)
        self.src = rt.device_put_sharded_rows(src_p)
        self.dst = rt.device_put_sharded_rows(dst_p)
        self.edge_attr = rt.device_put_sharded_rows(attr_p)
        self.valid = rt.device_put_sharded_rows(valid)

    # -- construction ----------------------------------------------------------
    @classmethod
    def from_edges(cls, ctx, edges: Sequence[Tuple[int, int]],
                   edge_attr: Optional[np.ndarray] = None) -> "Graph":
        """Build from (srcId, dstId) pairs with arbitrary int ids
        (ref Graph.fromEdgeTuples)."""
        arr = np.asarray(list(edges), dtype=np.int64)
        if arr.size == 0:
            raise ValueError("empty edge list")
        ids = np.unique(arr)
        remap = {v: i for i, v in enumerate(ids.tolist())}
        src = np.array([remap[s] for s in arr[:, 0]], dtype=np.int32)
        dst = np.array([remap[d] for d in arr[:, 1]], dtype=np.int32)
        return cls(ctx, src, dst, edge_attr, n_vertices=len(ids), vertex_ids=ids)

    # -- structural operators (host-side edge rewrites, ref Graph.scala) -------
    def reverse(self) -> "Graph":
        return Graph(self.ctx, self._h_dst, self._h_src, self._h_attr,
                     self.n_vertices, self.vertex_ids)

    def subgraph(self, edge_pred: Callable[[int, int, float], bool]) -> "Graph":
        keep = np.array([edge_pred(int(s), int(d), float(a)) for s, d, a in
                         zip(self._h_src, self._h_dst, self._h_attr)], dtype=bool)
        return Graph(self.ctx, self._h_src[keep], self._h_dst[keep],
                     self._h_attr[keep], self.n_vertices, self.vertex_ids)

    def map_edges(self, f: Callable[[np.ndarray], np.ndarray]) -> "Graph":
        return Graph(self.ctx, self._h_src, self._h_dst, f(self._h_attr),
                     self.n_vertices, self.vertex_ids)

    def undirected(self) -> "Graph":
        """Symmetrize: add reversed edges, drop duplicates and self-loops."""
        pairs = np.stack([np.concatenate([self._h_src, self._h_dst]),
                          np.concatenate([self._h_dst, self._h_src])], axis=1)
        attr = np.concatenate([self._h_attr, self._h_attr])
        keep = pairs[:, 0] != pairs[:, 1]
        pairs, attr = pairs[keep], attr[keep]
        _, idx = np.unique(pairs, axis=0, return_index=True)
        return Graph(self.ctx, pairs[idx, 0], pairs[idx, 1], attr[idx],
                     self.n_vertices, self.vertex_ids)

    # -- the core primitive ----------------------------------------------------
    def message_program(self, to_dst: Optional[Callable] = None,
                        to_src: Optional[Callable] = None,
                        merge: str = "sum", n_extras: int = 0):
        """Compile an aggregate-messages program (ref GraphX
        ``aggregateMessages``; GraphImpl.scala:35 ships vertex attrs via
        routing tables — here they're replicated and gathered locally).

        ``to_dst``/``to_src``: ``fn(src_attr_e, dst_attr_e, edge_attr_e,
        *extras) -> msgs`` computed per edge; messages are merged into a dense
        ``(n_vertices, ...)`` array with ``merge`` ∈ {sum,min,max}. Returns a
        jitted callable ``(vertex_attrs, *extras) -> merged``; vertices that
        receive no message hold the merge identity (0 / +inf / −inf).
        """
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        rt = self.ctx.mesh_runtime
        n = self.n_vertices
        seg = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
               "max": jax.ops.segment_max}[merge]
        xreduce = {"sum": jax.lax.psum, "min": jax.lax.pmin,
                   "max": jax.lax.pmax}[merge]

        def combine(a, b):
            if merge == "sum":
                return a + b
            return jnp.minimum(a, b) if merge == "min" else jnp.maximum(a, b)

        def local(src, dst, eattr, valid, vattr, *extras):
            out = None
            for fn, idx in ((to_dst, dst), (to_src, src)):
                if fn is None:
                    continue
                msgs = fn(_gather(vattr, src), _gather(vattr, dst), eattr, *extras)
                mask = valid.reshape((-1,) + (1,) * (msgs.ndim - 1)) > 0
                msgs = jnp.where(mask, msgs, merge_identity(msgs.dtype, merge))
                c = seg(msgs, idx, num_segments=n)
                out = c if out is None else combine(out, c)
            for ax in (DATA_AXIS, REPLICA_AXIS):
                out = jax.tree_util.tree_map(lambda t: xreduce(t, ax), out)
            return out

        row = P((REPLICA_AXIS, DATA_AXIS))
        f = shard_map_compat(local, rt.mesh,
                             (row, row, row, row) + (P(),) * (1 + n_extras), P())
        return jax.jit(lambda vattr, *ex: f(self.src, self.dst, self.edge_attr,
                                            self.valid, vattr, *ex))

    def aggregate_messages(self, vertex_attrs, to_dst=None, to_src=None,
                           merge: str = "sum", extras: Tuple = ()):
        """One-shot aggregate (compiles and runs; loops should use
        :meth:`message_program` once and iterate)."""
        prog = self.message_program(to_dst, to_src, merge, len(extras))
        return prog(vertex_attrs, *extras)

    # -- degrees (ref GraphOps.{in,out}Degrees) --------------------------------
    def _degrees(self, to_dst, to_src) -> np.ndarray:
        import jax.numpy as jnp
        one = (lambda s, d, e: jnp.ones_like(e))
        out = self.aggregate_messages(
            jnp.zeros(self.n_vertices, dtype=np.float32),
            to_dst=one if to_dst else None, to_src=one if to_src else None)
        return np.asarray(out)

    def in_degrees(self) -> np.ndarray:
        return self._degrees(True, False)

    def out_degrees(self) -> np.ndarray:
        return self._degrees(False, True)

    def degrees(self) -> np.ndarray:
        return self._degrees(True, True)

    # -- dense adjacency (for closure-based algorithms; MXU-friendly) ----------
    def adjacency(self, symmetric: bool = False):
        """Dense boolean adjacency as float32 device array. O(n²) memory — the
        deliberate trade for algorithms that become pure matmuls on the MXU
        (triangle counting, transitive closure); fine for n up to ~16k."""
        import jax.numpy as jnp
        a = np.zeros((self.n_vertices, self.n_vertices), dtype=np.float32)
        a[self._h_src, self._h_dst] = 1.0
        if symmetric:
            a = np.maximum(a, a.T)
        np.fill_diagonal(a, 0.0)
        return jnp.asarray(a)


def merge_identity(dtype, merge: str):
    """The merge op's identity element in the message dtype — integer label
    dtypes get iinfo bounds so vertex ids above 2^24 stay exact (float32
    labels would collapse distinct large ids)."""
    import jax.numpy as jnp
    if merge == "sum":
        return jnp.asarray(0, dtype)
    if jnp.issubdtype(jnp.dtype(dtype), jnp.integer):
        info = jnp.iinfo(dtype)
        return jnp.asarray(info.max if merge == "min" else info.min, dtype)
    return jnp.asarray(np.inf if merge == "min" else -np.inf, dtype)


def _gather(vattr, idx):
    """Gather per-edge vertex attributes from replicated vertex state (pytree
    of arrays with leading vertex dim)."""
    import jax
    return jax.tree_util.tree_map(lambda t: t[idx], vattr)
