"""Sparse instance datasets — the Criteo-class ingest path.

SURVEY §7 hard-parts: XLA needs static shapes, so the reference's per-row
SparseVector branches (ref: mllib-local BLAS.scala dot/axpy on SparseVector
around :91) cannot port. The layout chosen here is **ELL blocks**: every row
keeps exactly ``k_max`` (column, value) slots, short rows padded with
(0, 0.0). For categorical/one-hot workloads (Criteo: ~39 active features per
row regardless of the 10^6-dim hashed space) k_max is small and uniform, so
ELL wastes almost nothing and every tensor stays statically shaped and
row-shardable over the mesh exactly like the dense tier.

Aggregators then read features with gathers (``coef[indices] * values``) and
write gradients with segment-sums — MXU-free but VPU/HBM-friendly, and ~d/k
times less memory traffic than densifying. Feature hashing
(``hash_features``) caps the dimension the way the reference's HashingTF
does (ref: ml/feature/HashingTF.scala), which is how Criteo-scale vocab fits
a replicated coefficient vector; shard it over the ``model`` axis when it
outgrows one device (SURVEY §5.7a).
"""

from __future__ import annotations

import os
from typing import Callable, Optional, Tuple

import numpy as np

from cycloneml_tpu.parallel import collectives
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


def _rows_to_pairs(rows, n_features: Optional[int] = None):
    """Normalize [(indices, values)] rows / SparseVectors to array pairs,
    inferring the feature dimension — the ONE row parser shared by the
    pure-ELL and hybrid builders."""
    pairs = []
    d = n_features or 0
    for r in rows:
        if hasattr(r, "indices"):  # SparseVector
            idx, val = np.asarray(r.indices), np.asarray(r.values)
            d = max(d, getattr(r, "size", 0))
        else:
            idx, val = np.asarray(r[0]), np.asarray(r[1])
        if idx.size:
            d = max(d, int(idx.max()) + 1)
        pairs.append((idx, val))
    return pairs, d


def rows_to_ell(rows, n_features: Optional[int] = None,
                k_max: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Convert [(indices, values)] rows (or SparseVectors) to ELL arrays.

    Returns (indices (n, k_max) int32, values (n, k_max) f32, n_features).
    Rows longer than ``k_max`` raise — truncation would silently corrupt
    gradients (use ``SparseInstanceDataset.from_rows_hybrid`` for
    arbitrary row lengths).
    """
    pairs, d = _rows_to_pairs(rows, n_features)
    k = max((p[0].size for p in pairs), default=1)
    if k_max is not None:
        if k > k_max:
            raise ValueError(f"row has {k} nonzeros > k_max={k_max}")
        k = k_max
    k = max(k, 1)
    n = len(pairs)
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=np.float32)
    for i, (idx, val) in enumerate(pairs):
        indices[i, : idx.size] = idx
        values[i, : idx.size] = val
    return indices, values, d


def _csr_to_ell(row_nnz: np.ndarray, flat_idx: np.ndarray,
                flat_val: np.ndarray, k: int
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized CSR chunk → ELL (n, k) arrays; rows padded with (0, 0.0)."""
    n = len(row_nnz)
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=np.float32)
    if n == 0 or len(flat_idx) == 0:
        return indices, values
    offsets = np.concatenate([[0], np.cumsum(row_nnz[:-1], dtype=np.int64)])
    cols = np.arange(k)[None, :]
    mask = cols < row_nnz[:, None]
    pos = offsets[:, None] + cols
    indices[mask] = flat_idx[pos[mask]]
    values[mask] = flat_val[pos[mask]]
    return indices, values


def hash_features(indices: np.ndarray, values: np.ndarray,
                  num_features: int) -> Tuple[np.ndarray, np.ndarray]:
    """Hashing-trick remap of column ids into [0, num_features)
    (ref: HashingTF.scala — same murmur-style bucketing role; collisions
    sum, which the padding (0,0.0) slots survive because their value is 0)."""
    hashed = (indices.astype(np.int64) * 2654435761 % 2**31) % num_features
    return hashed.astype(np.int32), values


class SparseInstanceDataset:
    """Row-sharded ELL blocks on the mesh: indices/values (n_pad, k), y/w
    (n_pad,), padding rows carrying w=0 (the same neutrality invariant as
    the dense tier).

    Optionally HYBRID (ELL + COO): rows wider than the ELL width keep their
    first k slots in ELL and spill the excess into per-shard COO arrays
    (local row id, column, value) — the standard ELL+COO sparse format.
    Margins then add a per-row segment-sum of the COO tail to the ELL
    gather, so arbitrary row lengths (tf-idf text, power-law graphs) work
    without feature hashing and without widening every row to the longest
    one (which is what pure ELL would cost).
    """

    def __init__(self, ctx, indices, values, y, w, n_rows: int,
                 n_features: int, coo_row=None, coo_idx=None, coo_val=None):
        self.ctx = ctx
        self.indices = indices
        self.values = values
        self.y = y
        self.w = w
        self.n_rows = n_rows
        self.n_features = n_features
        # hybrid overflow tail (all three set, or none): row ids are LOCAL
        # to the shard, so each shard's COO slice aggregates into its own
        # row block
        self.coo_row = coo_row
        self.coo_idx = coo_idx
        self.coo_val = coo_val

    @property
    def is_hybrid(self) -> bool:
        return self.coo_row is not None

    @classmethod
    def from_ell(cls, ctx, indices: np.ndarray, values: np.ndarray,
                 y: Optional[np.ndarray] = None,
                 w: Optional[np.ndarray] = None,
                 n_features: Optional[int] = None) -> "SparseInstanceDataset":
        from cycloneml_tpu.dataset.instance import blockify_arrays
        n, k = indices.shape
        d = n_features or (int(indices.max()) + 1 if indices.size else 1)
        rt = ctx.mesh_runtime
        # reuse the dense padder: treat indices/values as the 2-D payloads
        idx_p, y_p, w_p, n_true = blockify_arrays(
            indices.astype(np.float64), y, w, rt.data_parallelism,
            dtype=np.float64)
        val_p, _, _, _ = blockify_arrays(values, None, None,
                                         rt.data_parallelism,
                                         dtype=np.float32)
        return cls(ctx,
                   rt.device_put_sharded_rows(idx_p.astype(np.int32)),
                   rt.device_put_sharded_rows(val_p),
                   rt.device_put_sharded_rows(y_p.astype(np.float32)),
                   rt.device_put_sharded_rows(w_p.astype(np.float32)),
                   n_true, d)

    @classmethod
    def from_rows(cls, ctx, rows, y=None, w=None,
                  n_features: Optional[int] = None,
                  hash_dim: Optional[int] = None) -> "SparseInstanceDataset":
        indices, values, d = rows_to_ell(rows, n_features)
        if hash_dim is not None:
            indices, values = hash_features(indices, values, hash_dim)
            d = hash_dim
        return cls.from_ell(ctx, indices, values, y, w, n_features=d)

    @classmethod
    def from_libsvm_stream(cls, ctx, path: str,
                           n_features: Optional[int] = None,
                           hash_dim: Optional[int] = None,
                           k_max: Optional[int] = None,
                           chunk_rows: int = 65536,
                           n_threads: int = 0,
                           n_readers: int = 1,
                           collect_labels: Optional[list] = None
                           ) -> "SparseInstanceDataset":
        """Bounded-memory sharded ingest: stream a libsvm file chunk-by-chunk
        onto the mesh without ever materializing the dataset in driver RAM.

        Each CSR chunk from the native scanner (``stream_libsvm_chunks``) is
        packed to ELL and ``device_put`` directly onto one mesh device
        round-robin; the driver only ever holds one chunk. At EOF the
        per-device chunk lists are concatenated ON DEVICE and stitched into
        global row-sharded arrays with
        ``jax.make_array_from_single_device_arrays`` — the streamed twin of
        ``from_ell`` (ref: HadoopRDD.scala:87 partition streaming feeding
        MLUtils.loadLibSVMFile, MLUtils.scala:77; SURVEY §7 'host ingest
        throughput at Criteo-1TB scale').

        Row order is chunk-round-robin over devices, a permutation of file
        order (training rows are exchangeable; padding rows carry w=0). The
        ELL width starts at the first chunk's widest row and widens on device
        if a later chunk needs more (``k_max`` pins it and rejects overflow).

        ``collect_labels``: pass an empty list to receive per-device lists of
        f64 label chunks in DATASET row order (labels would otherwise only be
        readable back from the device tier as f32).

        ``n_readers > 1`` splits the FILE into byte ranges parsed by
        concurrent reader threads (the HadoopRDD split analog —
        HadoopRDD.scala:87; ctypes releases the GIL during the native
        parse, so readers genuinely overlap with each other and with the
        driver's pack/placement work). Chunks interleave across readers, a
        permutation of file order — the same exchangeability contract the
        round-robin placement already states.
        """
        import jax
        import jax.numpy as jnp
        from cycloneml_tpu.native.host import (native_available,
                                               stream_libsvm_chunks)

        rt = ctx.mesh_runtime
        if rt.mesh.devices.shape[2] != 1:
            raise ValueError(
                "from_libsvm_stream shards rows over (replica, data) and "
                "requires model_parallelism == 1")
        devices = list(rt.mesh.devices.reshape(-1))
        n_dev = len(devices)

        k = k_max or 1
        per_dev: list = [[] for _ in range(n_dev)]  # [(idx, val, y, w)]
        if collect_labels is not None:
            collect_labels.extend([] for _ in range(n_dev))
        n_true = 0
        max_feature = 0
        ci = 0

        def chunk_source():
            if n_readers <= 1 or not native_available():
                yield from stream_libsvm_chunks(
                    path, chunk_rows=chunk_rows, n_threads=n_threads)
                return
            import queue
            import threading as _th
            size = os.path.getsize(path)
            bounds = [(i * size // n_readers, (i + 1) * size // n_readers)
                      for i in range(n_readers)]
            per_reader_threads = max(
                1, (n_threads or (os.cpu_count() or 1)) // n_readers)
            q: "queue.Queue" = queue.Queue(maxsize=2 * n_readers)
            stop = _th.Event()

            def put_or_stop(item) -> bool:
                while not stop.is_set():
                    try:
                        q.put(item, timeout=0.5)
                        return True
                    except queue.Full:
                        continue
                return False  # consumer gone: drop, do not block forever

            def run(rng):
                try:
                    for ch in stream_libsvm_chunks(
                            path, chunk_rows=chunk_rows,
                            n_threads=per_reader_threads, byte_range=rng):
                        if not put_or_stop(("chunk", ch)):
                            return
                except Exception as e:  # surfaced in the consumer
                    put_or_stop(("error", e))
                finally:
                    put_or_stop(("done", None))

            threads = [_th.Thread(target=run, args=(b,), daemon=True)
                       for b in bounds]
            for t in threads:
                t.start()
            done = 0
            try:
                while done < len(threads):
                    kind, payload = q.get()
                    if kind == "done":
                        done += 1
                    elif kind == "error":
                        raise payload
                    else:
                        yield payload
            finally:
                stop.set()  # a consumer error must not strand readers

        for cy, cnnz, cfi, cfv, mf in chunk_source():
            max_feature = max(max_feature, mf)
            if (hash_dim is None and n_features is not None
                    and max_feature > n_features):
                # fail on the offending chunk, not after streaming (and
                # device-placing) the rest of a multi-GB file
                raise ValueError(
                    f"observed feature index {max_feature - 1} >= declared "
                    f"n_features={n_features}; pass "
                    f"n_features>={max_feature} or hash_dim to fold indices")
            ck = max(int(cnnz.max()) if len(cnnz) else 1, 1)
            if k_max is not None and ck > k_max:
                raise ValueError(f"row has {ck} nonzeros > k_max={k_max}")
            if ck > k:
                # widen everything already placed — on device, no host copy
                grow = ck - k
                per_dev = [[(jnp.pad(i_, ((0, 0), (0, grow))),
                             jnp.pad(v_, ((0, 0), (0, grow))), y_, w_)
                            for (i_, v_, y_, w_) in chunks]
                           for chunks in per_dev]
                k = ck
            idx, val = _csr_to_ell(cnnz, cfi, cfv, k)
            if hash_dim is not None:
                idx, val = hash_features(idx, val, hash_dim)
            n_rows = len(cy)
            n_true += n_rows
            # exact-size chunks: shard equalization pads ONCE at the end, so
            # a small file never blows up to n_dev × chunk_rows rows
            dev = devices[ci % n_dev]
            if collect_labels is not None:
                collect_labels[ci % n_dev].append(np.asarray(cy, np.float64))
            per_dev[ci % n_dev].append((
                jax.device_put(idx, dev),
                jax.device_put(val, dev),
                jax.device_put(cy.astype(np.float32), dev),
                jax.device_put(np.ones(n_rows, dtype=np.float32), dev)))
            ci += 1

        # per-device concat, then pad every shard to the widest one (w=0)
        dev_totals = [sum(int(c[2].shape[0]) for c in chunks)
                      for chunks in per_dev]
        shard_rows = max(max(dev_totals), 1)
        shards = []
        for di in range(n_dev):
            chunks = per_dev[di]
            parts = []
            for j, trailing in ((0, (k,)), (1, (k,)), (2, ()), (3, ())):
                if chunks:
                    a = (jnp.concatenate([c[j] for c in chunks])
                         if len(chunks) > 1 else chunks[0][j])
                else:
                    dt = np.int32 if j == 0 else np.float32
                    a = jax.device_put(
                        np.zeros((0,) + trailing, dt), devices[di])
                pad = shard_rows - a.shape[0]
                if pad:
                    a = jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1))
                parts.append(a)
            shards.append(tuple(parts))

        n_pad = shard_rows * n_dev
        global_arrays = []
        for j, trailing in ((0, (k,)), (1, (k,)), (2, ()), (3, ())):
            sharding = rt.data_sharding(extra_axes=len(trailing))
            global_arrays.append(jax.make_array_from_single_device_arrays(
                (n_pad,) + trailing, sharding, [s[j] for s in shards]))

        d = hash_dim or n_features or max(max_feature, 1)
        return cls(ctx, global_arrays[0], global_arrays[1],
                   global_arrays[2], global_arrays[3], n_true, d)

    @classmethod
    def from_rows_hybrid(cls, ctx, rows, y=None, w=None,
                         n_features: Optional[int] = None,
                         k_ell: int = 16) -> "SparseInstanceDataset":
        """Build the ELL+COO hybrid: each row's first ``k_ell`` nonzeros go
        to ELL, the excess to a per-shard COO tail with SHARD-LOCAL row ids
        (entries must land on the shard that owns their row). COO slices
        pad to a uniform per-shard length with (row 0, col 0, val 0.0)
        entries — value 0 keeps them exactly neutral."""
        from cycloneml_tpu.dataset.instance import blockify_arrays
        rt = ctx.mesh_runtime
        shards = rt.data_parallelism

        pairs, d = _rows_to_pairs(rows, n_features)
        n = len(pairs)
        k = max(1, min(k_ell, max((p[0].size for p in pairs), default=1)))

        # pad row count exactly like the dense tier so shard row blocks line
        # up with blockify's layout for y/w
        _, y_p, w_p, n_true = blockify_arrays(
            np.zeros((n, 1)), y, w, shards, dtype=np.float32)
        n_pad = len(y_p)
        rows_per_shard = n_pad // shards

        indices = np.zeros((n_pad, k), dtype=np.int32)
        values = np.zeros((n_pad, k), dtype=np.float32)
        per_shard_coo: list = [[] for _ in range(shards)]
        for i, (idx, val) in enumerate(pairs):
            m = min(idx.size, k)
            indices[i, :m] = idx[:m]
            values[i, :m] = val[:m]
            if idx.size > k:
                shard, local = divmod(i, rows_per_shard)
                for j in range(k, idx.size):
                    per_shard_coo[shard].append(
                        (local, int(idx[j]), float(val[j])))
        tail = max((len(c) for c in per_shard_coo), default=0)
        tail = max(tail, 1)
        coo_row = np.zeros((shards * tail,), dtype=np.int32)
        coo_idx = np.zeros((shards * tail,), dtype=np.int32)
        coo_val = np.zeros((shards * tail,), dtype=np.float32)
        for s, entries in enumerate(per_shard_coo):
            for j, (lr, ci, cv) in enumerate(entries):
                coo_row[s * tail + j] = lr
                coo_idx[s * tail + j] = ci
                coo_val[s * tail + j] = cv

        return cls(ctx,
                   rt.device_put_sharded_rows(indices),
                   rt.device_put_sharded_rows(values),
                   rt.device_put_sharded_rows(y_p.astype(np.float32)),
                   rt.device_put_sharded_rows(w_p.astype(np.float32)),
                   n_true, d,
                   coo_row=rt.device_put_sharded_rows(coo_row),
                   coo_idx=rt.device_put_sharded_rows(coo_idx),
                   coo_val=rt.device_put_sharded_rows(coo_val))

    @classmethod
    def from_scipy(cls, ctx, csr, y=None, w=None,
                   hash_dim: Optional[int] = None) -> "SparseInstanceDataset":
        """From a scipy.sparse CSR matrix."""
        csr = csr.tocsr()
        rows = [(csr.indices[csr.indptr[i]:csr.indptr[i + 1]],
                 csr.data[csr.indptr[i]:csr.indptr[i + 1]])
                for i in range(csr.shape[0])]
        return cls.from_rows(ctx, rows, y, w, n_features=csr.shape[1],
                             hash_dim=hash_dim)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.n_rows, self.n_features)

    @property
    def k_max(self) -> int:
        return self.indices.shape[1]

    def tree_aggregate_fn(self, fn: Callable, auto_psum: bool = True):
        """Compile ``fn(idx_shard, val_shard, [coo_row, coo_idx, coo_val,]
        y_shard, w_shard, *extras)`` into a mesh-wide psum aggregation —
        the sparse twin of ``InstanceDataset.tree_aggregate_fn``. Hybrid
        datasets pass their COO tail as three extra row-sharded arrays
        (use the ``*_hybrid`` aggregators)."""
        rt = self.ctx.mesh_runtime
        if self.is_hybrid:
            arrays = (self.indices, self.values, self.coo_row,
                      self.coo_idx, self.coo_val, self.y, self.w)
        else:
            arrays = (self.indices, self.values, self.y, self.w)
        compiled = collectives.tree_aggregate(fn, rt, *arrays,
                                              auto_psum=auto_psum)

        def call(*extras):
            return compiled(*arrays, *extras)

        call.compiled = compiled
        call.arrays = lambda: arrays
        return call

    def to_dense(self) -> np.ndarray:
        """Materialize (unpadded) dense rows — tests/debug only.

        Selects rows by the w>0 invariant rather than position: streamed
        ingest (``from_libsvm_stream``) interleaves padding chunks across
        shards, so valid rows are not necessarily a prefix. (A dataset built
        with EXPLICIT zero row weights will drop those rows here too.)
        """
        mask = np.asarray(self.w) > 0
        idx = np.asarray(self.indices)
        val = np.asarray(self.values)
        full = np.zeros((idx.shape[0], self.n_features))
        for i in range(idx.shape[0]):
            np.add.at(full[i], idx[i], val[i])
        if self.is_hybrid:
            rt = self.ctx.mesh_runtime
            shards = rt.data_parallelism
            rows_per_shard = idx.shape[0] // shards
            crow = np.asarray(self.coo_row)
            cidx = np.asarray(self.coo_idx)
            cval = np.asarray(self.coo_val)
            per_shard = len(crow) // shards
            for s in range(shards):
                sl = slice(s * per_shard, (s + 1) * per_shard)
                np.add.at(full,
                          (s * rows_per_shard + crow[sl], cidx[sl]),
                          cval[sl])
        return full[mask]


def read_libsvm_sparse(ctx, path: str, n_features: Optional[int] = None,
                       hash_dim: Optional[int] = None,
                       chunk_rows: int = 65536
                       ) -> Tuple[SparseInstanceDataset, np.ndarray]:
    """libsvm → ELL without densifying (the dense reader is
    ``dataset.io.read_libsvm``; this one keeps Criteo-scale width sparse).

    Routes through the streamed, sharded ingest (``from_libsvm_stream``):
    the file is scanned by the multithreaded C++ parser in bounded-memory
    chunks placed directly on the mesh — never a per-line Python loop, never
    a whole-file driver array. The returned labels are the one O(n) driver
    artifact (8 bytes/row), kept at full f64 parse precision, in the
    dataset's row order (chunk-round-robin over shards — a permutation of
    file order once the file spans multiple chunks).
    """
    labels: list = []
    ds = SparseInstanceDataset.from_libsvm_stream(
        ctx, path, n_features=n_features, hash_dim=hash_dim,
        chunk_rows=chunk_rows, collect_labels=labels)
    parts = [c for dev_chunks in labels for c in dev_chunks]
    y = (np.concatenate(parts) if parts else np.zeros(0))
    return ds, y


_scale_gather = None


def _get_scale_gather():
    """Module-level cached jit: a fresh lambda per call would recompile the
    gather-scale program on every fit (see loss._get_scale_rows)."""
    global _scale_gather
    if _scale_gather is None:
        import jax
        import jax.numpy as jnp
        _scale_gather = jax.jit(lambda v, i, s: v * jnp.take(s, i, axis=0))
    return _scale_gather


def sparse_feature_std(ds: SparseInstanceDataset) -> np.ndarray:
    """Per-feature std over a sparse dataset, implicit zeros included —
    the unbiased weighted formula the dense Summarizer uses
    (MultivariateOnlineSummarizer.variance), computed from one psum pass
    of per-feature weighted sums/squares."""
    from cycloneml_tpu.ml.optim.sparse_aggregators import (
        sparse_summary, sparse_summary_hybrid)
    summ = (sparse_summary_hybrid if ds.is_hybrid else sparse_summary)
    out = ds.tree_aggregate_fn(summ(ds.n_features))(
        np.zeros(1, dtype=np.float32))
    w = float(out["weight_sum"])
    s1 = np.asarray(out["sum"], dtype=np.float64)
    s2 = np.asarray(out["sum_sq"], dtype=np.float64)
    denom = w - float(out["weight_sq_sum"]) / max(w, 1e-300)
    mean = s1 / max(w, 1e-300)
    if denom <= 0:
        return np.zeros_like(mean)
    var = np.maximum((s2 - w * mean * mean) / denom, 0.0)
    return np.sqrt(var)


def standardize_sparse_dataset(ds: SparseInstanceDataset,
                               features_std: np.ndarray
                               ) -> Tuple[SparseInstanceDataset, np.ndarray]:
    """Scale stored values by 1/std WITHOUT centering (the reference's
    sparse standardization keeps sparsity for exactly this reason,
    LogisticRegression.scala:968 note); zero-variance features scale to 0.
    Device-side: values gather their feature's scale by column id."""
    import jax
    import jax.numpy as jnp

    inv_std = np.where(features_std > 0, 1.0 / np.where(
        features_std > 0, features_std, 1.0), 0.0)
    inv = jnp.asarray(inv_std, dtype=jnp.float32)
    scale = _get_scale_gather()
    values = scale(ds.values, ds.indices, inv)
    coo_val = (scale(ds.coo_val, ds.coo_idx, inv)
               if ds.is_hybrid else None)
    return SparseInstanceDataset(
        ds.ctx, ds.indices, values, ds.y, ds.w, ds.n_rows, ds.n_features,
        coo_row=ds.coo_row, coo_idx=ds.coo_idx, coo_val=coo_val), inv_std
