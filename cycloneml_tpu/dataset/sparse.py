"""Sparse instance datasets — the Criteo-class ingest path.

SURVEY §7 hard-parts: XLA needs static shapes, so the reference's per-row
SparseVector branches (ref: mllib-local BLAS.scala dot/axpy on SparseVector
around :91) cannot port. The layout chosen here is **ELL blocks**: every row
keeps exactly ``k_max`` (column, value) slots, short rows padded with
(0, 0.0). For categorical/one-hot workloads (Criteo: ~39 active features per
row regardless of the 10^6-dim hashed space) k_max is small and uniform, so
ELL wastes almost nothing and every tensor stays statically shaped and
row-shardable over the mesh exactly like the dense tier.

Aggregators then read features with gathers (``coef[indices] * values``) and
write gradients with segment-sums — MXU-free but VPU/HBM-friendly, and ~d/k
times less memory traffic than densifying. Feature hashing
(``hash_features``) caps the dimension the way the reference's HashingTF
does (ref: ml/feature/HashingTF.scala), which is how Criteo-scale vocab fits
a replicated coefficient vector; shard it over the ``model`` axis when it
outgrows one device (SURVEY §5.7a).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from cycloneml_tpu.parallel import collectives
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


def rows_to_ell(rows, n_features: Optional[int] = None,
                k_max: Optional[int] = None
                ) -> Tuple[np.ndarray, np.ndarray, int]:
    """Convert [(indices, values)] rows (or SparseVectors) to ELL arrays.

    Returns (indices (n, k_max) int32, values (n, k_max) f32, n_features).
    Rows longer than ``k_max`` raise — truncation would silently corrupt
    gradients.
    """
    pairs = []
    d = n_features or 0
    for r in rows:
        if hasattr(r, "indices"):  # SparseVector
            idx, val = np.asarray(r.indices), np.asarray(r.values)
            d = max(d, getattr(r, "size", 0))
        else:
            idx, val = np.asarray(r[0]), np.asarray(r[1])
        if idx.size:
            d = max(d, int(idx.max()) + 1)
        pairs.append((idx, val))
    k = max((p[0].size for p in pairs), default=1)
    if k_max is not None:
        if k > k_max:
            raise ValueError(f"row has {k} nonzeros > k_max={k_max}")
        k = k_max
    k = max(k, 1)
    n = len(pairs)
    indices = np.zeros((n, k), dtype=np.int32)
    values = np.zeros((n, k), dtype=np.float32)
    for i, (idx, val) in enumerate(pairs):
        indices[i, : idx.size] = idx
        values[i, : idx.size] = val
    return indices, values, d


def hash_features(indices: np.ndarray, values: np.ndarray,
                  num_features: int) -> Tuple[np.ndarray, np.ndarray]:
    """Hashing-trick remap of column ids into [0, num_features)
    (ref: HashingTF.scala — same murmur-style bucketing role; collisions
    sum, which the padding (0,0.0) slots survive because their value is 0)."""
    hashed = (indices.astype(np.int64) * 2654435761 % 2**31) % num_features
    return hashed.astype(np.int32), values


class SparseInstanceDataset:
    """Row-sharded ELL blocks on the mesh: indices/values (n_pad, k), y/w
    (n_pad,), padding rows carrying w=0 (the same neutrality invariant as
    the dense tier)."""

    def __init__(self, ctx, indices, values, y, w, n_rows: int,
                 n_features: int):
        self.ctx = ctx
        self.indices = indices
        self.values = values
        self.y = y
        self.w = w
        self.n_rows = n_rows
        self.n_features = n_features

    @classmethod
    def from_ell(cls, ctx, indices: np.ndarray, values: np.ndarray,
                 y: Optional[np.ndarray] = None,
                 w: Optional[np.ndarray] = None,
                 n_features: Optional[int] = None) -> "SparseInstanceDataset":
        from cycloneml_tpu.dataset.instance import blockify_arrays
        n, k = indices.shape
        d = n_features or (int(indices.max()) + 1 if indices.size else 1)
        rt = ctx.mesh_runtime
        # reuse the dense padder: treat indices/values as the 2-D payloads
        idx_p, y_p, w_p, n_true = blockify_arrays(
            indices.astype(np.float64), y, w, rt.data_parallelism,
            dtype=np.float64)
        val_p, _, _, _ = blockify_arrays(values, None, None,
                                         rt.data_parallelism,
                                         dtype=np.float32)
        return cls(ctx,
                   rt.device_put_sharded_rows(idx_p.astype(np.int32)),
                   rt.device_put_sharded_rows(val_p),
                   rt.device_put_sharded_rows(y_p.astype(np.float32)),
                   rt.device_put_sharded_rows(w_p.astype(np.float32)),
                   n_true, d)

    @classmethod
    def from_rows(cls, ctx, rows, y=None, w=None,
                  n_features: Optional[int] = None,
                  hash_dim: Optional[int] = None) -> "SparseInstanceDataset":
        indices, values, d = rows_to_ell(rows, n_features)
        if hash_dim is not None:
            indices, values = hash_features(indices, values, hash_dim)
            d = hash_dim
        return cls.from_ell(ctx, indices, values, y, w, n_features=d)

    @classmethod
    def from_scipy(cls, ctx, csr, y=None, w=None,
                   hash_dim: Optional[int] = None) -> "SparseInstanceDataset":
        """From a scipy.sparse CSR matrix."""
        csr = csr.tocsr()
        rows = [(csr.indices[csr.indptr[i]:csr.indptr[i + 1]],
                 csr.data[csr.indptr[i]:csr.indptr[i + 1]])
                for i in range(csr.shape[0])]
        return cls.from_rows(ctx, rows, y, w, n_features=csr.shape[1],
                             hash_dim=hash_dim)

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.n_rows, self.n_features)

    @property
    def k_max(self) -> int:
        return self.indices.shape[1]

    def tree_aggregate_fn(self, fn: Callable, auto_psum: bool = True):
        """Compile ``fn(idx_shard, val_shard, y_shard, w_shard, *extras)``
        into a mesh-wide psum aggregation — the sparse twin of
        ``InstanceDataset.tree_aggregate_fn``."""
        rt = self.ctx.mesh_runtime
        compiled = collectives.tree_aggregate(
            fn, rt, self.indices, self.values, self.y, self.w,
            auto_psum=auto_psum)
        ds = self

        def call(*extras):
            return compiled(ds.indices, ds.values, ds.y, ds.w, *extras)

        call.compiled = compiled
        call.arrays = lambda: (ds.indices, ds.values, ds.y, ds.w)
        return call

    def to_dense(self) -> np.ndarray:
        """Materialize (unpadded) dense rows — tests/debug only."""
        idx = np.asarray(self.indices)[: self.n_rows]
        val = np.asarray(self.values)[: self.n_rows]
        out = np.zeros((self.n_rows, self.n_features))
        for i in range(self.n_rows):
            np.add.at(out[i], idx[i], val[i])
        return out


def read_libsvm_sparse(ctx, path: str, n_features: Optional[int] = None,
                       hash_dim: Optional[int] = None
                       ) -> Tuple[SparseInstanceDataset, np.ndarray]:
    """libsvm → ELL without densifying (the dense reader is
    ``dataset.io.read_libsvm``; this one keeps Criteo-scale width sparse)."""
    labels = []
    rows = []
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.split("#", 1)[0].strip()
            if not line:
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            idx = np.array([int(p.split(":")[0]) - 1 for p in parts[1:]],
                           dtype=np.int64)
            val = np.array([float(p.split(":")[1]) for p in parts[1:]],
                           dtype=np.float32)
            rows.append((idx, val))
    y = np.asarray(labels)
    ds = SparseInstanceDataset.from_rows(ctx, rows, y=y,
                                         n_features=n_features,
                                         hash_dim=hash_dim)
    return ds, y
