from cycloneml_tpu.dataset.dataset import PartitionedDataset, InstanceDataset
from cycloneml_tpu.dataset.instance import Instance, blockify_arrays

__all__ = ["PartitionedDataset", "InstanceDataset", "Instance", "blockify_arrays"]
