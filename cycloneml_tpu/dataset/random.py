"""Random dataset generators.

Re-design of ``mllib/random`` (ref: mllib/src/main/scala/org/apache/spark/
mllib/random/RandomRDDs.scala + RandomDataGenerator.scala). The reference
materializes random numbers partition-by-partition on executors with
per-partition XORShift seeds; here each mesh shard generates its rows
directly **on device** inside one shard_map program, with a
``fold_in(seed, shard_index)`` key per shard — same per-partition
reproducibility contract (ref RandomRDDs seed params), zero host↔device
transfer.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS
from cycloneml_tpu.parallel.collectives import shard_map_compat


def _shard_generate(ctx, n_rows: int, seed: int, local_fn: Callable,
                    n_out: int):
    """Shared per-shard generation scaffolding: pad the row count to the
    blockify invariant, run ``local_fn(key, per_shard_rows)`` (key =
    ``fold_in(seed, shard_index)``) on every shard inside one shard_map
    program, and return ``(outputs, w_mask, total_rows, dtype)`` where
    ``w_mask`` zeroes the padding rows."""
    import jax
    from jax.sharding import PartitionSpec as P
    from cycloneml_tpu.dataset.instance import compute_dtype

    rt = ctx.mesh_runtime
    nd = rt.data_parallelism
    d_size = rt.mesh.devices.shape[1]
    per = max(((n_rows + nd - 1) // nd + 7) // 8 * 8, 8)
    total = per * nd
    dt = compute_dtype()

    def local(tok):
        idx = (jax.lax.axis_index(REPLICA_AXIS) * d_size
               + jax.lax.axis_index(DATA_AXIS))
        key = jax.random.fold_in(jax.random.PRNGKey(seed), idx)
        return local_fn(key, per)

    row = P((REPLICA_AXIS, DATA_AXIS))
    tok = rt.device_put_sharded_rows(np.zeros(nd, dtype=np.float32))
    out_spec = row if n_out == 1 else (row,) * n_out
    out = jax.jit(shard_map_compat(local, rt.mesh, (row,), out_spec))(tok)
    w = np.zeros(total, dtype=dt)
    w[:n_rows] = 1.0
    return out, w, total, dt


def _generate(ctx, n_rows: int, n_cols: int, seed: int,
              sampler: Callable) -> InstanceDataset:
    """Run ``sampler(key, shape)`` per shard; returns an InstanceDataset with
    padding rows masked out via w=0 (the blockify invariant). X lands in the
    data-tier dtype (generated at f32 then narrowed ON DEVICE — no host
    round trip); y/w stay at accumulator width."""
    from cycloneml_tpu.dataset.instance import data_dtype

    xdt = data_dtype(getattr(ctx, "conf", None))
    x, w, total, dt = _shard_generate(
        ctx, n_rows, seed,
        lambda key, per: sampler(key, (per, n_cols)).astype(xdt), n_out=1)
    rt = ctx.mesh_runtime
    return InstanceDataset(ctx, x, rt.device_put_sharded_rows(np.zeros(total, dtype=dt)),
                           rt.device_put_sharded_rows(w), n_rows, n_cols)


def generate_classification(ctx, n_rows: int, n_cols: int, seed: int = 0,
                            noise: float = 1.0) -> InstanceDataset:
    """Labeled synthetic binary-classification dataset, generated entirely
    on device (the benchmark/scale-test feeder; ref RandomRDDs +
    LogisticRegressionDataGenerator, mllib/util/LogisticRegressionDataGenerator.scala:33).

    Each shard draws its feature rows from ``fold_in(seed, shard)`` and
    labels them with a shared ground-truth weight vector drawn from
    ``fold_in(seed, 2**31 - 1)``: ``y = 1[x·beta + noise·eps > 0]``. Zero
    host→device transfer of X; only the (n,) labels are read back once so
    estimators get their host label histogram for free."""
    import jax
    import jax.numpy as jnp

    def local(key, per):
        kx, ke = jax.random.split(key)
        beta = jax.random.normal(
            jax.random.fold_in(jax.random.PRNGKey(seed), 2 ** 31 - 1),
            (n_cols,), dtype=jnp.float32)
        x = jax.random.normal(kx, (per, n_cols), dtype=jnp.float32)
        margin = x @ beta + noise * jax.random.normal(ke, (per,),
                                                      dtype=jnp.float32)
        return x.astype(xdt), (margin > 0).astype(dt)

    from cycloneml_tpu.dataset.instance import compute_dtype, data_dtype
    dt = compute_dtype()
    xdt = data_dtype(getattr(ctx, "conf", None))
    (x, y), w, total, dt = _shard_generate(ctx, n_rows, seed, local, n_out=2)
    rt = ctx.mesh_runtime
    ds = InstanceDataset(ctx, x, y, rt.device_put_sharded_rows(w),
                         n_rows, n_cols)
    # one small readback: estimators consult the host label histogram each
    # fit — (n,) not (n, d), so this stays cheap even through a TPU relay
    return ds.attach_host_labels(np.asarray(y).astype(np.float64),
                                 w.astype(np.float64))


def generate_regression(ctx, n_rows: int, n_cols: int, seed: int = 0,
                        noise: float = 0.1) -> InstanceDataset:
    """Labeled synthetic linear-regression dataset generated entirely on
    device (ref mllib/util/LinearDataGenerator.scala:120 — the epsilon-shape
    BASELINE config-2 feeder): ``y = x·beta + noise·eps`` with a shared
    ground-truth ``beta ~ N(0,1)`` drawn from ``fold_in(seed, 2^31-1)``."""
    import jax
    import jax.numpy as jnp

    from cycloneml_tpu.dataset.instance import compute_dtype, data_dtype
    dt = compute_dtype()
    xdt = data_dtype(getattr(ctx, "conf", None))

    def local(key, per):
        kx, ke = jax.random.split(key)
        beta = jax.random.normal(
            jax.random.fold_in(jax.random.PRNGKey(seed), 2 ** 31 - 1),
            (n_cols,), dtype=jnp.float32)
        x = jax.random.normal(kx, (per, n_cols), dtype=jnp.float32)
        y = x @ beta + noise * jax.random.normal(ke, (per,),
                                                 dtype=jnp.float32)
        return x.astype(xdt), y.astype(dt)

    (x, y), w, total, dt = _shard_generate(ctx, n_rows, seed, local, n_out=2)
    rt = ctx.mesh_runtime
    ds = InstanceDataset(ctx, x, y, rt.device_put_sharded_rows(w),
                         n_rows, n_cols)
    return ds.attach_host_labels(np.asarray(y).astype(np.float64),
                                 w.astype(np.float64))


class RandomDatasets:
    """Static factory surface mirroring RandomRDDs (vector variants; the
    scalar variants are n_cols=1)."""

    classification = staticmethod(generate_classification)
    regression = staticmethod(generate_regression)

    @staticmethod
    def normal(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
               mean: float = 0.0, std: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.normal(k, s) * std + mean)

    @staticmethod
    def uniform(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
                low: float = 0.0, high: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.uniform(k, s, minval=low, maxval=high))

    @staticmethod
    def log_normal(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
                   mean: float = 0.0, std: float = 1.0) -> InstanceDataset:
        import jax
        import jax.numpy as jnp
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jnp.exp(jax.random.normal(k, s) * std + mean))

    @staticmethod
    def poisson(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
                lam: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.poisson(k, lam, s).astype("float32"))

    @staticmethod
    def exponential(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
                    mean: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.exponential(k, s) * mean)

    @staticmethod
    def gamma(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
              shape: float = 1.0, scale: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.gamma(k, shape, s) * scale)
