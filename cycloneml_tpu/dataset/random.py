"""Random dataset generators.

Re-design of ``mllib/random`` (ref: mllib/src/main/scala/org/apache/spark/
mllib/random/RandomRDDs.scala + RandomDataGenerator.scala). The reference
materializes random numbers partition-by-partition on executors with
per-partition XORShift seeds; here each mesh shard generates its rows
directly **on device** inside one shard_map program, with a
``fold_in(seed, shard_index)`` key per shard — same per-partition
reproducibility contract (ref RandomRDDs seed params), zero host↔device
transfer.
"""

from __future__ import annotations

from typing import Callable, Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS
from cycloneml_tpu.parallel.collectives import shard_map_compat


def _generate(ctx, n_rows: int, n_cols: int, seed: int,
              sampler: Callable) -> InstanceDataset:
    """Run ``sampler(key, shape)`` per shard; returns an InstanceDataset with
    padding rows masked out via w=0 (the blockify invariant)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from cycloneml_tpu.dataset.instance import compute_dtype

    rt = ctx.mesh_runtime
    nd = rt.data_parallelism
    d_size = rt.mesh.devices.shape[1]
    per = max(((n_rows + nd - 1) // nd + 7) // 8 * 8, 8)
    total = per * nd
    dt = compute_dtype()

    def local(tok):
        idx = jax.lax.axis_index(REPLICA_AXIS) * d_size + jax.lax.axis_index(DATA_AXIS)
        key = jax.random.fold_in(jax.random.PRNGKey(seed), idx)
        return sampler(key, (per, n_cols)).astype(dt)

    row = P((REPLICA_AXIS, DATA_AXIS))
    tok = rt.device_put_sharded_rows(np.zeros(nd, dtype=np.float32))
    x = jax.jit(shard_map_compat(local, rt.mesh, (row,), row))(tok)
    w = np.zeros(total, dtype=dt)
    w[:n_rows] = 1.0
    return InstanceDataset(ctx, x, rt.device_put_sharded_rows(np.zeros(total, dtype=dt)),
                           rt.device_put_sharded_rows(w), n_rows, n_cols)


class RandomDatasets:
    """Static factory surface mirroring RandomRDDs (vector variants; the
    scalar variants are n_cols=1)."""

    @staticmethod
    def normal(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
               mean: float = 0.0, std: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.normal(k, s) * std + mean)

    @staticmethod
    def uniform(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
                low: float = 0.0, high: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.uniform(k, s, minval=low, maxval=high))

    @staticmethod
    def log_normal(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
                   mean: float = 0.0, std: float = 1.0) -> InstanceDataset:
        import jax
        import jax.numpy as jnp
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jnp.exp(jax.random.normal(k, s) * std + mean))

    @staticmethod
    def poisson(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
                lam: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.poisson(k, lam, s).astype("float32"))

    @staticmethod
    def exponential(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
                    mean: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.exponential(k, s) * mean)

    @staticmethod
    def gamma(ctx, n_rows: int, n_cols: int = 1, seed: int = 0,
              shape: float = 1.0, scale: float = 1.0) -> InstanceDataset:
        import jax
        return _generate(ctx, n_rows, n_cols, seed,
                         lambda k, s: jax.random.gamma(k, shape, s) * scale)
