"""MLFrame — a lightweight named-column frame for the estimator API.

The reference's ``ml.*`` API runs on Spark SQL DataFrames with column params
(featuresCol/labelCol/predictionCol...). Rebuilding Catalyst is out of scope
for the ML north star (SURVEY §7 step 10); what estimators actually need is a
typed, named-column, row-aligned container that can hand its numeric columns
to the device tier. ``MLFrame`` is exactly that: a dict of numpy columns
(1-D scalars or 2-D vector columns) with select/withColumn semantics, plus a
bridge to ``InstanceDataset``.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.instance import rows_to_dense
from cycloneml_tpu.linalg.vectors import DenseVector, SparseVector, Vector


class MLFrame:
    """Immutable named-column table. Columns are numpy arrays sharing row
    count; vector columns are 2-D (n, d)."""

    def __init__(self, ctx, columns: Dict[str, np.ndarray]):
        self.ctx = ctx
        self._cols: Dict[str, np.ndarray] = {}
        n = None
        for name, col in columns.items():
            arr = self._coerce(col)
            if n is None:
                n = arr.shape[0]
            elif arr.shape[0] != n:
                raise ValueError(
                    f"column {name!r} has {arr.shape[0]} rows, expected {n}")
            self._cols[name] = arr
        self.n_rows = n or 0
        self._ds_cache: Dict[tuple, InstanceDataset] = {}

    @staticmethod
    def _coerce(col) -> np.ndarray:
        if isinstance(col, np.ndarray):
            # copy: the frame is documented immutable and caches its device
            # placement, so it must not alias a WRITABLE caller-owned buffer
            # the caller may mutate (stale cached device data, silently).
            # Already-read-only arrays (columns of another frame flowing
            # through select/with_column) are safe to alias — nobody can
            # write them.
            arr = col if not col.flags.writeable else col.copy()
        elif len(col) and isinstance(col[0], Vector):
            arr = rows_to_dense(col)
        else:
            arr = np.asarray(col)
        # and in-place writes through frame["col"] must raise, not corrupt
        arr.flags.writeable = False
        return arr

    # -- construction ---------------------------------------------------------
    @classmethod
    def from_rows(cls, ctx, rows: Sequence, schema: Sequence[str]) -> "MLFrame":
        cols: Dict[str, list] = {name: [] for name in schema}
        for row in rows:
            for name, v in zip(schema, row):
                cols[name].append(v)
        return cls(ctx, {k: cls._coerce(v) for k, v in cols.items()})

    @classmethod
    def from_instance_dataset(cls, ds: InstanceDataset,
                              features_col: str = "features",
                              label_col: str = "label",
                              weight_col: Optional[str] = None) -> "MLFrame":
        x, y, w = ds.to_numpy()
        cols = {features_col: x, label_col: y}
        if weight_col:
            cols[weight_col] = w
        return cls(ds.ctx, cols)

    # -- column ops ------------------------------------------------------------
    @property
    def columns(self) -> List[str]:
        return list(self._cols)

    def __contains__(self, name: str) -> bool:
        return name in self._cols

    def __getitem__(self, name: str) -> np.ndarray:
        if name not in self._cols:
            raise KeyError(f"column {name!r} not in {self.columns}")
        return self._cols[name]

    def col(self, name: str) -> np.ndarray:
        return self[name]

    def with_column(self, name: str, values) -> "MLFrame":
        cols = dict(self._cols)
        cols[name] = self._coerce(values)
        return MLFrame(self.ctx, cols)

    def select(self, *names: str) -> "MLFrame":
        return MLFrame(self.ctx, {n: self[n] for n in names})

    def drop(self, *names: str) -> "MLFrame":
        return MLFrame(self.ctx, {k: v for k, v in self._cols.items()
                                  if k not in names})

    def with_column_renamed(self, old: str, new: str) -> "MLFrame":
        cols = {}
        for k, v in self._cols.items():
            cols[new if k == old else k] = v
        return MLFrame(self.ctx, cols)

    def filter_rows(self, mask: np.ndarray) -> "MLFrame":
        return MLFrame(self.ctx, {k: v[mask] for k, v in self._cols.items()})

    def sample(self, fraction: float, seed: int = 0) -> "MLFrame":
        rng = np.random.RandomState(seed)
        mask = rng.rand(self.n_rows) < fraction
        return self.filter_rows(mask)

    def random_split(self, weights: Sequence[float], seed: int = 0) -> List["MLFrame"]:
        rng = np.random.RandomState(seed)
        total = float(sum(weights))
        u = rng.rand(self.n_rows)
        bounds = np.cumsum([w / total for w in weights])
        out = []
        lo = 0.0
        for hi in bounds:
            out.append(self.filter_rows((u >= lo) & (u < hi)))
            lo = hi
        return out

    def limit(self, n: int) -> "MLFrame":
        return MLFrame(self.ctx, {k: v[:n] for k, v in self._cols.items()})

    def count(self) -> int:
        return self.n_rows

    def collect(self) -> List[tuple]:
        names = self.columns
        return [tuple(self._cols[c][i] for c in names) for i in range(self.n_rows)]

    def head(self, n: int = 5):
        return self.limit(n).collect()

    # -- bridge to device tier ------------------------------------------------
    def to_instance_dataset(self, features_col: str = "features",
                            label_col: Optional[str] = "label",
                            weight_col: Optional[str] = None,
                            dtype=None,
                            fp8_capable: bool = False) -> InstanceDataset:
        if dtype is None:
            # the design matrix lands in the DATA tier (bf16 by default
            # off-x64); labels/weights stay at accumulator width inside
            # InstanceDataset.from_numpy. fp8_capable is the second
            # rung's opt-in: only estimators that fold the per-column
            # dequant scales into their aggregator read may see e4m3
            # codes — everyone else gets bf16 under the fp8 tiers
            from cycloneml_tpu.dataset.instance import data_dtype
            dtype = data_dtype(getattr(self.ctx, "conf", None),
                               fp8_capable=fp8_capable)
        # cached per column selection: the frame is immutable, so repeated
        # fits on the same frame (grid search, CV, warmed benchmarks) reuse
        # one device placement instead of re-paying the host→device transfer
        # each time — the analog of the reference persisting its instance
        # blocks once (LogisticRegression.scala:968 MEMORY_AND_DISK).
        # Keyed on the dtype NAME: the fp8 extension dtypes share numpy's
        # '|V1' struct str, and a quantized dataset must never be handed
        # to a caller that asked for the bf16 rung
        key = (features_col, label_col, weight_col, str(np.dtype(dtype)))
        ds = self._ds_cache.get(key)
        if ds is not None:
            return ds
        x = self[features_col]
        if x.ndim == 1:
            x = x[:, None]
        # explicit column names must exist — a typo'd labelCol silently
        # training on zero labels is worse than an error
        y = self[label_col] if label_col else None
        w = self[weight_col] if weight_col else None
        ds = InstanceDataset.from_numpy(self.ctx, x, y, w, dtype=dtype)
        # frame-cached datasets are exactly the long-lived training blocks
        # the reference persists (MEMORY_AND_DISK): register them with the
        # context's storage tiers so conf budgets can demote cold frames
        ds.persist()
        self._ds_cache[key] = ds
        return ds

    def __repr__(self) -> str:
        shapes = {k: v.shape for k, v in self._cols.items()}
        return f"MLFrame({self.n_rows} rows, {shapes})"
