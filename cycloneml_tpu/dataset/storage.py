"""Storage tiers with eviction — the BlockManager memory-store analog.

Ref: core/.../storage/BlockManager.scala + memory/StorageMemoryPool: the
reference caches RDD blocks in a bounded memory store and evicts LRU
blocks to disk (or drops them) under pressure. Here the cached unit is a
whole ``InstanceDataset`` (the physical block of the numeric tier) and the
tiers map to the platform:

- DEVICE: arrays live in HBM (the default placement)
- HOST: ``persist_host()`` — numpy in driver RAM, HBM released
- DISK: npz spill file; re-placed on the mesh transparently at next access

``StorageManager`` tracks registered datasets with per-tier byte budgets
and evicts least-recently-used datasets down a tier when a budget is
exceeded — ``MEMORY_AND_DISK`` semantics (data is never dropped; eviction
always lands in a durable tier, matching this framework's
checkpoint-based recovery story).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Dict, Optional

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class StorageLevel:
    DEVICE = "DEVICE"
    HOST = "HOST"
    DISK = "DISK"


_ORDER = [StorageLevel.DEVICE, StorageLevel.HOST, StorageLevel.DISK]


def _spill_file(path: str) -> str:
    """The on-disk name persist_disk writes for a spill ``path``."""
    return path if path.endswith(".npz") else path + ".npz"


def _unlink_spill(path: Optional[str]) -> None:
    if path:
        try:
            os.unlink(_spill_file(path))
        except OSError:
            pass


def _cleanup_entry(mgr_ref, key: int) -> None:
    """weakref.finalize hook: a GC'd managed dataset drops its entry and
    its spill file (ContextCleaner analog — module-level so the finalizer
    itself never pins the manager or the dataset)."""
    mgr = mgr_ref()
    if mgr is None:
        return
    with mgr._lock:
        e = mgr._entries.pop(key, None)
    _unlink_spill(e["path"] if e else None)


class StorageManager:
    """Bounded multi-tier dataset cache with LRU demotion.

    ``device_budget``/``host_budget`` are byte budgets for the DEVICE and
    HOST tiers (None = unbounded). Exceeding a budget demotes the least
    recently used dataset to the next tier; DISK is unbounded.
    """

    def __init__(self, device_budget: Optional[int] = None,
                 host_budget: Optional[int] = None,
                 spill_dir: Optional[str] = None):
        self.device_budget = device_budget
        self.host_budget = host_budget
        self._spill_dir = spill_dir or tempfile.mkdtemp(prefix="cyclone-store-")
        self._lock = threading.RLock()
        # id(ds) -> {ds (weakref), level, bytes, last_used, path}; entries
        # hold their dataset WEAKLY: the manager accounts for blocks, it
        # does not extend their lifetime (the reference's ContextCleaner
        # drops BlockManager entries for GC'd RDDs the same way)
        self._entries: Dict[int, dict] = {}

    # -- public surface ------------------------------------------------------
    def persist(self, ds, level: str = StorageLevel.DEVICE):
        """Register a dataset under management at ``level``; may trigger
        evictions of older datasets to keep budgets. Lazy restores through
        ``ds.x`` notify the manager, so accounting tracks the normal read
        path — not just explicit ``touch()`` calls."""
        if level not in _ORDER:
            raise ValueError(f"unknown storage level {level!r}")
        import weakref
        with self._lock:
            key = id(ds)
            entry = {"ds": weakref.ref(ds), "level": level,
                     "bytes": ds.padded_bytes(),
                     "last_used": time.monotonic(), "path": None}
            self._entries[key] = entry
            ref = weakref.ref(self)
            ds._storage_cb = lambda d: (ref() and ref()._on_restore(d))
            weakref.finalize(ds, _cleanup_entry, ref, key)
            self._apply_level(entry, level)
            self._enforce()
        return ds

    def _on_restore(self, ds) -> None:
        """A managed dataset re-placed itself on device via its property
        access: relabel, drop the now-redundant host copy, re-enforce."""
        with self._lock:
            e = self._entries.get(id(ds))
            if e is None:
                return
            e["level"] = StorageLevel.DEVICE
            e["last_used"] = time.monotonic()
            ds._host = None  # device copy is authoritative again
            self._enforce()

    def touch(self, ds) -> None:
        """Record an access without moving data."""
        with self._lock:
            e = self._entries.get(id(ds))
            if e is None:
                return
            e["last_used"] = time.monotonic()
            if ds._x is not None:
                e["level"] = StorageLevel.DEVICE
            self._enforce()

    def migrate_device_to_host(self):
        """Pull every live DEVICE-tier dataset to the host tier under the
        manager's lock (the decommission hop — ref
        BlockManagerDecommissioner.scala:40 pushing a draining executor's
        cached blocks out). Raises on the first failure WITHOUT touching
        the rest: the caller must not tear the mesh down when a dataset
        could not leave it — a DEVICE-only dataset has no other copy and
        no lineage, so losing its devices loses the data."""
        migrated = []
        moved_bytes = 0
        with self._lock:
            for e in self._entries.values():
                ds = e["ds"]()
                if ds is None or e["level"] != StorageLevel.DEVICE \
                        or not hasattr(ds, "persist_host"):
                    continue
                try:
                    ds.persist_host()
                except Exception as exc:
                    raise RuntimeError(
                        f"decommission aborted: dataset {id(ds):#x} could "
                        f"not be migrated off the device tier ({exc!r}); "
                        "the mesh is untouched — free host memory or "
                        "checkpoint the dataset and retry") from exc
                e["level"] = StorageLevel.HOST
                migrated.append(ds)
                moved_bytes += e["bytes"]
        return migrated, moved_bytes

    def unpersist(self, ds) -> None:
        """Stop managing ``ds``. Data is NEVER dropped: a DISK-tier dataset
        is pulled back to the host tier before its spill file is removed."""
        with self._lock:
            e = self._entries.pop(id(ds), None)
            ds._storage_cb = None
            if e is None:
                return
            if e["level"] == StorageLevel.DISK and e["path"]:
                z = __import__("numpy").load(_spill_file(e["path"]))
                ds._host = (z["x"], z["y"], z["w"])
                ds._disk_path = None
            _unlink_spill(e["path"])

    def level_of(self, ds) -> Optional[str]:
        with self._lock:   # evict/spill rewrite entries concurrently
            e = self._entries.get(id(ds))
            return e["level"] if e else None

    def usage(self) -> Dict[str, int]:
        with self._lock:
            self._prune()
            out = {lvl: 0 for lvl in _ORDER}
            for e in self._entries.values():
                out[e["level"]] += e["bytes"]
            return out

    # -- mechanics -----------------------------------------------------------
    def _prune(self) -> None:
        dead = [k for k, e in self._entries.items() if e["ds"]() is None]
        for k in dead:
            _unlink_spill(self._entries.pop(k)["path"])

    def _apply_level(self, e: dict, level: str) -> None:
        ds = e["ds"]()
        if ds is None:
            return
        if level == StorageLevel.DEVICE:
            ds.x  # property access re-places evicted arrays on the mesh
        elif level == StorageLevel.HOST:
            if ds._x is not None:
                ds.persist_host()
        elif level == StorageLevel.DISK:
            if e["path"] is None:
                e["path"] = os.path.join(
                    self._spill_dir, f"block-{id(ds)}")
            # persist_disk writes from the HOST tuple when present — a
            # HOST->DISK demotion never round-trips through device HBM
            ds.persist_disk(e["path"])
        e["level"] = level

    @staticmethod
    def _shares_arrays(ds) -> bool:
        """True when ``ds`` shares device arrays with a live relative
        (``derive()`` lineage): demoting it would delete buffers the
        relative still serves, so such entries are not eviction
        candidates until the sharing side dies."""
        p = getattr(ds, "_array_parent", None)
        if p is not None and p() is not None:
            return True
        kids = getattr(ds, "_derived_children", None)
        return bool(kids) and len(kids) > 0

    def _enforce(self) -> None:
        self._prune()
        for level, budget in ((StorageLevel.DEVICE, self.device_budget),
                              (StorageLevel.HOST, self.host_budget)):
            if budget is None:
                continue
            while True:
                entries = [e for e in self._entries.values()
                           if e["level"] == level]
                used = sum(e["bytes"] for e in entries)
                # the most-recently-used entry is never evicted: it may be
                # the dataset an in-flight property access just restored —
                # demoting it mid-access would hand the caller None arrays
                # (an over-budget SINGLE block stays put, like the
                # reference keeping a block larger than the store)
                candidates = [e for e in sorted(
                    entries, key=lambda e: e["last_used"])[:-1]
                    if e["ds"]() is not None
                    and not self._shares_arrays(e["ds"]())]
                if used <= budget or not candidates:
                    if used > budget:
                        logger.warning(
                            "storage: %s over budget (%d > %d) with no "
                            "evictable entry", level, used, budget)
                    break
                victim = candidates[0]
                nxt = _ORDER[_ORDER.index(level) + 1]
                logger.info("storage: evicting %d bytes %s -> %s",
                            victim["bytes"], level, nxt)
                self._apply_level(victim, nxt)


    def close(self) -> None:
        """Release every spill file and the spill directory (context
        shutdown). Managed datasets are left wherever they are — a
        DISK-tier dataset still referenced keeps its data only if the
        caller restored it first, which is why unpersist() promotes."""
        import shutil
        with self._lock:
            for e in self._entries.values():
                _unlink_spill(e["path"])
            self._entries = {}
        shutil.rmtree(self._spill_dir, ignore_errors=True)
