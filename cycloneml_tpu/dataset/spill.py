"""Out-of-core host-tier aggregation.

Analog of the reference's spillable collections
(ref: core/.../util/collection/ExternalAppendOnlyMap.scala:55 — an
append-only map that sorts and spills to disk past a memory threshold, then
hash-merges the spilled runs with the in-memory map). The host tier's
``group_by_key`` routes every pair through :class:`ExternalAppendOnlyMap`,
bounding the aggregation's working set (the host tier's input/output
partitions themselves remain in-memory lists — the spill removes the
grouping-map blowup, not the partition materialization).

Spill files are sequences of independently-compressed chunks (the native
zstd/lz4 codec, ref CompressionCodec.scala:63), each a pickled run of
``(key, values)`` entries sorted by a PYTHONHASHSEED-independent key hash —
reading back streams one chunk at a time, and the k-way heap merge keeps
one entry per run in memory.
"""

from __future__ import annotations

import heapq
import os
import pickle
import struct
import tempfile
from typing import Any, Iterator, List, Optional, Tuple


def stable_hash(key: Any) -> int:
    """Deterministic partitioner hash: identical across processes and runs
    (the builtin ``hash`` is salted per-process by PYTHONHASHSEED for
    str/bytes, which would scatter one key to different partitions on
    different hosts — the reference's Partitioner contract requires
    cross-executor agreement).

    Equal keys MUST hash equal (1 == 1.0 == True must co-partition), so
    numerics use Python's own numeric hash — which is salt-free and equal
    across equal values — while str/bytes/tuples/frozensets get a salt-free
    CRC64-style digest (crc32 over the bytes and their length; this is a
    partitioner, not a cryptographic hash — speed matters on the per-record
    shuffle path). Other types fall back to their ``__hash__``:
    deterministic exactly when the type's own hash is (a custom value-based
    __hash__ qualifies; the default id() hash does not)."""
    import zlib
    if isinstance(key, str):
        b = key.encode("utf-8")
    elif isinstance(key, (bytes, bytearray)):
        b = bytes(key)
    elif isinstance(key, tuple):
        h = 1099511628211
        for k in key:
            h = (h * 31 + stable_hash(k)) & 0x7FFFFFFFFFFFFFFF
        return h
    elif isinstance(key, frozenset):
        # order-independent: sum of element hashes (commutative), salt-free
        return (sum(stable_hash(k) for k in key) + len(key)) \
            & 0x7FFFFFFFFFFFFFFF
    else:
        # numerics (incl. numpy scalars and bool) + custom-hash objects
        return hash(key) & 0x7FFFFFFFFFFFFFFF
    return (zlib.crc32(b) | (zlib.crc32(b[::-1]) << 32)) \
        & 0x7FFFFFFFFFFFFFFF


_CHUNK_ENTRIES = 4096


def read_frame(fh) -> "bytes | None":
    """Read one [u32 len][payload] frame; returns the raw payload (b"" for
    a zero-length frame) or None at clean EOF. The ONE definition of the
    spill/exchange frame format — file runs, disk partitions, and the wire
    protocol all read through here."""
    hdr = fh.read(4)
    if len(hdr) < 4:
        return None
    (n,) = struct.unpack("<I", hdr)
    return fh.read(n) if n else b""


def iter_frames(fh) -> Iterator[Any]:
    """Yield the decoded records of every frame in a chunked spill stream."""
    from cycloneml_tpu.native.host import CompressionCodec
    while True:
        blob = read_frame(fh)
        if blob is None:
            return
        yield from pickle.loads(CompressionCodec.decompress(blob))


class _SpillFile:
    """One sorted run: [u32 length][compressed pickled chunk]..."""

    def __init__(self, path: str, codec):
        self.path = path
        self.codec = codec

    @classmethod
    def write(cls, entries: List[Tuple[int, Any, list]], spill_dir: str,
              codec) -> "_SpillFile":
        fd, path = tempfile.mkstemp(prefix="spill-", suffix=".run",
                                    dir=spill_dir)
        with os.fdopen(fd, "wb") as fh:
            for i in range(0, len(entries), _CHUNK_ENTRIES):
                blob = codec.compress(
                    pickle.dumps(entries[i:i + _CHUNK_ENTRIES],
                                 protocol=pickle.HIGHEST_PROTOCOL))
                fh.write(struct.pack("<I", len(blob)))
                fh.write(blob)
        return cls(path, codec)

    def __iter__(self) -> Iterator[Tuple[int, Any, list]]:
        with open(self.path, "rb") as fh:
            yield from iter_frames(fh)

    def delete(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass


class ExternalAppendOnlyMap:
    """Append-only (key -> list of values) map that spills sorted runs to
    disk past ``row_budget`` inserted values, then streams a k-way merge.

    ``items()`` yields ``(key, [values])`` exactly once per key with values
    from every run concatenated (insertion order within a run preserved;
    runs concatenate in spill order, memory last — the reference's merge
    order too). Peak memory during the merge is one chunk per run.
    """

    def __init__(self, row_budget: int = 1 << 20,
                 spill_dir: Optional[str] = None, codec: str = "zstd"):
        from cycloneml_tpu.native.host import CompressionCodec
        self.row_budget = max(int(row_budget), 1)
        self._spill_dir = spill_dir or tempfile.gettempdir()
        self._codec = CompressionCodec(codec)
        self._map: dict = {}
        self._rows = 0
        self._spills: List[_SpillFile] = []
        self.spill_count = 0

    def insert(self, key: Any, value: Any) -> None:
        self._map.setdefault(key, []).append(value)
        self._rows += 1
        if self._rows >= self.row_budget:
            self._spill()

    def insert_all(self, pairs) -> None:
        for k, v in pairs:
            self.insert(k, v)

    def _sorted_entries(self) -> List[Tuple[int, Any, list]]:
        return sorted(((stable_hash(k), k, vs) for k, vs in self._map.items()),
                      key=lambda e: (e[0], repr(e[1])))

    def _spill(self) -> None:
        if not self._map:
            return
        self._spills.append(_SpillFile.write(
            self._sorted_entries(), self._spill_dir, self._codec))
        self.spill_count += 1
        self._map = {}
        self._rows = 0

    def items(self) -> Iterator[Tuple[Any, list]]:
        """Stream merged (key, values) groups; consumes the map. Spill
        files are removed even if the iterator is abandoned or the merge
        raises (generator finalization runs the finally)."""
        if not self._spills:
            yield from self._map.items()
            self._map = {}
            return
        try:
            runs: List[Iterator] = [iter(s) for s in self._spills]
            runs.append(iter(self._sorted_entries()))
            self._map = {}
            merged = heapq.merge(*runs, key=lambda e: (e[0], repr(e[1])))
            cur_key, cur_vals, have = None, None, False
            for h, k, vs in merged:
                if have and k == cur_key:
                    cur_vals.extend(vs)
                else:
                    if have:
                        yield cur_key, cur_vals
                    cur_key, cur_vals, have = k, list(vs), True
            if have:
                yield cur_key, cur_vals
        finally:
            self.close()

    def close(self) -> None:
        """Delete any remaining spill files."""
        for s in self._spills:
            s.delete()
        self._spills = []

    def __del__(self):  # a dropped, never-drained map must not leak /tmp
        try:
            self.close()
        except Exception:
            pass

    def __len__(self) -> int:
        return len(self._map)


class SpilledPartition:
    """A disk-backed partition: a sequence of records stored as
    independently-compressed pickled chunks (same on-disk shape as a spill
    run, minus the sort). Iterating streams one chunk at a time; ``len`` is
    O(1). This is the storage the host tier's shuffle outputs use past the
    row budget — the analog of the reference's shuffle block files
    (ref ShuffleBlockResolver; ExternalSorter.scala:93 writes the same
    chunked spill shape).
    """

    def __init__(self, path: str, n_rows: int, owned: bool = False):
        self.path = path
        self.n_rows = n_rows
        # owned partitions are temp shuffle outputs: deleted on GC so lazy
        # re-materialization cannot leak /tmp; checkpoint copies are not
        # owned (their files belong to the checkpoint directory)
        self._owned = owned

    @classmethod
    def writer(cls, spill_dir: Optional[str] = None,
               codec: str = "zstd") -> "_PartitionWriter":
        return _PartitionWriter(spill_dir or tempfile.gettempdir(), codec)

    def __len__(self) -> int:
        return self.n_rows

    def __iter__(self) -> Iterator[Any]:
        with open(self.path, "rb") as fh:
            yield from iter_frames(fh)

    def __getitem__(self, idx):
        """List-style indexing for the take()/head() paths (streams, then
        stops); scalar access is O(position) — this is shuffle storage, not
        a random-access store."""
        import itertools
        if isinstance(idx, slice):
            start, stop, step = idx.indices(self.n_rows)
            if step < 0:  # rare path; correctness over streaming
                return list(self)[idx]
            return list(itertools.islice(iter(self), start, stop, step))
        if idx < 0:
            idx += self.n_rows
        if not 0 <= idx < self.n_rows:
            raise IndexError(idx)
        return next(itertools.islice(iter(self), idx, None))

    def delete(self) -> None:
        try:
            os.unlink(self.path)
        except OSError:
            pass

    def __del__(self):
        if getattr(self, "_owned", False):
            self.delete()


class _PartitionWriter:
    """Buffered append-side of a SpilledPartition."""

    def __init__(self, spill_dir: str, codec: str):
        from cycloneml_tpu.native.host import CompressionCodec
        fd, self._path = tempfile.mkstemp(prefix="part-", suffix=".blk",
                                          dir=spill_dir)
        self._fh = os.fdopen(fd, "wb")
        self._codec = CompressionCodec(codec)
        self._buf: list = []
        self._rows = 0

    def append(self, record: Any) -> None:
        self._buf.append(record)
        self._rows += 1
        if len(self._buf) >= _CHUNK_ENTRIES:
            self._flush()

    def extend(self, records) -> None:
        for r in records:
            self.append(r)

    def _flush(self) -> None:
        if not self._buf:
            return
        blob = self._codec.compress(
            pickle.dumps(self._buf, protocol=pickle.HIGHEST_PROTOCOL))
        self._fh.write(struct.pack("<I", len(blob)))
        self._fh.write(blob)
        self._buf = []

    def finish(self) -> SpilledPartition:
        self._flush()
        self._fh.close()
        return SpilledPartition(self._path, self._rows, owned=True)

    def abort(self) -> None:
        try:
            self._fh.close()
            os.unlink(self._path)
        except OSError:
            pass


def materialize_grouped(groups, row_budget: int):
    """Materialize a ``(key, [values])`` stream as ONE output partition:
    a plain list while the cumulative VALUE count stays within
    ``row_budget``, switching to a disk-backed :class:`SpilledPartition`
    the moment it exceeds it — the shuffle OUTPUT-spill contract shared by
    the in-process ``group_by_key`` and the cross-process exchange (one
    hot key with budget+ values must spill too)."""
    head = []
    rows = 0
    for kv in groups:
        head.append(kv)
        rows += len(kv[1])
        if rows > row_budget:
            w = SpilledPartition.writer()
            w.extend(head)
            w.extend(groups)
            return w.finish()
    return head
