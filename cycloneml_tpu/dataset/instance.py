"""Instance blocking — the physical unit of ML data.

The reference stacks rows into per-partition matrices so aggregators can use
level-2/3 BLAS (ref: ml/feature/Instance.scala:39 InstanceBlock,
blokifyWithMaxMemUsage:146,182). On TPU the same idea is carried further:
the whole dataset becomes dense device arrays ``(rows, features)`` row-sharded
over the mesh, padded with zero-weight rows so every shard is equal-sized and
shapes stay static for XLA. Zero weight makes padding exactly neutral in all
weighted aggregators — the invariant every estimator relies on.

Sparse handling (SURVEY §7 hard-parts): XLA requires static shapes, so sparse
rows are densified block-wise at ingest (scipy CSR → dense numpy → device).
For very wide sparse data a hashed/feature-sub-block path can be added at
this boundary without touching estimators.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple, Union

import numpy as np

from cycloneml_tpu.linalg.vectors import DenseVector, SparseVector, Vector


def compute_dtype():
    """The ACCUMULATOR float dtype (``cyclone.compute.dtype`` tier): float64
    only when jax x64 is enabled (CPU parity tests); on TPU the MXU path is
    float32 and requesting f64 would silently canonicalize anyway — this
    makes the choice explicit. Labels, weights, optimizer state and every
    psum accumulator live here; the design matrix lives in the (possibly
    narrower) data tier — see :func:`data_dtype`."""
    try:
        import jax
        return np.float64 if jax.config.jax_enable_x64 else np.float32
    except Exception:
        return np.float32


def data_dtype(conf=None, fp8_capable: bool = False):
    """The DATA-tier storage dtype (``cyclone.data.dtype``): what a
    materialized design matrix is stored as. Default ('auto') is bfloat16 —
    the sweeps are bandwidth-bound, so X's width IS the fit's speed — except
    under jax x64 (the parity/test config), where auto resolves to float64
    so golden suites see pre-tier numerics. Aggregators/kernels upcast to
    :func:`compute_dtype` INSIDE the kernel; nothing re-materializes X
    wider than this. ``conf`` defaults to the active context's.

    ``fp8_capable`` is the SECOND precision rung's opt-in: the 'float8'
    and 'auto8' tiers resolve to ``float8_e4m3fn`` only for callers that
    declare they understand quantized storage (per-column scales on the
    dataset, dequant folded into the aggregator read — LogisticRegression
    and the LinearRegression l-bfgs path). Everything else automatically
    gets the bf16 rung under those tiers: an estimator that would read
    raw e4m3 codes as values must never see them.
    """
    from cycloneml_tpu.conf import DATA_DTYPE
    name = "auto"
    if conf is None:
        try:
            from cycloneml_tpu import context as _c
            if _c._active_context is not None:
                conf = _c._active_context.conf
        except Exception:
            conf = None
    if conf is not None:
        name = str(conf.get(DATA_DTYPE))
    if name == "auto":
        if compute_dtype() is np.float64:
            return np.float64  # x64 parity runs keep the full-width tier
        import ml_dtypes
        return ml_dtypes.bfloat16
    if name == "auto8":
        # the fp8 twin of 'auto': parity (x64) runs stay full-width, and
        # non-capable consumers land on the bf16 rung
        if compute_dtype() is np.float64:
            return np.float64
        import ml_dtypes
        return ml_dtypes.float8_e4m3fn if fp8_capable else ml_dtypes.bfloat16
    if name == "float8":
        # forced (test/measurement) form: fp8 even under x64 for capable
        # callers; non-capable consumers get forced bf16, mirroring how
        # 'bfloat16' forces the narrow tier through parity configs
        import ml_dtypes
        return ml_dtypes.float8_e4m3fn if fp8_capable else ml_dtypes.bfloat16
    if name == "bfloat16":
        import ml_dtypes
        return ml_dtypes.bfloat16
    return np.dtype(name).type


def is_narrow_dtype(dt) -> bool:
    """True for sub-float32 storage dtypes (bf16/f16/fp8) — the tier
    boundary where fp32 accumulation becomes mandatory (Micikevicius et
    al. 2018)."""
    try:
        return np.dtype(dt).itemsize < 4
    except TypeError:
        return False


#: largest finite float8_e4m3fn value. The e4m3fn encoding has NO inf —
#: casting past ±448 produces NaN — so every fp8 materialization scales
#: into this range first (see quantize_fp8).
FP8_MAX = 448.0

#: envelope-probe threshold (see fp8_probe_ok): per-column
#: absmax/std above this predicts that e4m3's 3 mantissa bits inject
#: more than ~2 sigma of rounding noise per standardized element, which
#: breaks the documented coefficient envelope — the fit falls back to
#: the bf16 rung instead.
FP8_PROBE_RATIO = 32.0


def is_fp8_dtype(dt) -> bool:
    """True for the 1-byte float8 storage dtypes (e4m3fn / e5m2)."""
    try:
        return str(np.dtype(dt)).startswith("float8")
    except TypeError:
        return False


def quantize_fp8(x: np.ndarray, dtype=None, scale: Optional[np.ndarray] = None):
    """Quantize a host design matrix to fp8 with PER-COLUMN scales.

    Returns ``(x8, scale, probe_ratio)`` where ``x8[i, j] ~=
    x[i, j] / scale[j]`` as ``float8_e4m3fn``, ``scale`` is float64 at
    the accumulator tier — ``scale[j] = absmax_j / FP8_MAX`` (1.0 for
    all-zero columns), so every stored code is finite by construction
    (the e4m3fn overflow value is NaN, not a saturate) — and
    ``probe_ratio`` is the per-column ``absmax_j / std_j`` of the RAW
    data, the envelope probe's condition heuristic. It must be captured
    HERE: once quantized, a near-constant offset column collapses to one
    code and its post-quantization std can no longer witness the damage.
    Dequantization never materializes: the per-column scale folds into
    the replicated (d,) vectors every consumer already carries —
    ``inv_std`` for the scaled aggregators, the kernel-side ``scale``
    operand for gramian/kmeans — so HBM only ever sees the 1-byte codes.

    Pass ``scale`` to quantize against an EXTERNALLY fixed per-column
    scale — the out-of-core shard store requantizes every shard with ONE
    set-level scale (one geometry, one dequant fold, one program per
    epoch), so the per-block absmax must not win. Codes beyond the
    provided scale's range would overflow to NaN (e4m3fn has no inf), so
    a set-level scale must dominate every block's absmax.
    """
    import ml_dtypes
    if dtype is None:
        dtype = ml_dtypes.float8_e4m3fn
    xf = np.asarray(x, dtype=np.float64)
    if xf.shape[0]:
        absmax = np.max(np.abs(xf), axis=0)
        std = np.std(xf, axis=0)
    else:
        absmax = np.zeros(xf.shape[1])
        std = np.zeros(xf.shape[1])
    if scale is None:
        scale = np.where(absmax > 0, absmax / FP8_MAX, 1.0)
    else:
        scale = np.asarray(scale, dtype=np.float64)
    probe_ratio = np.where(std > 0, absmax / np.where(std > 0, std, 1.0),
                           0.0)
    x8 = (xf / scale[None, :]).astype(dtype)
    return x8, scale, probe_ratio


def fp8_probe_ok(stats, w_max: Optional[float] = None,
                 probe_ratio: Optional[np.ndarray] = None) -> Optional[str]:
    """The cheap pre-fit envelope probe: decide from already-harvested
    statistics whether e4m3 storage will hold the documented accuracy
    envelope, WITHOUT another data pass.

    Two heuristics, both about where the 3-bit mantissa breaks:

    - **scale spread**: after standardization the per-element rounding
      noise is ~``2^-4 * absmax_j / std_j`` sigmas; columns whose absmax
      dwarfs their std (near-constant offsets, wild outliers) push that
      past any useful envelope. The ratio comes from the
      materialization-time RAW moments when available (``probe_ratio``
      from :func:`quantize_fp8` — post-quantization stats cannot witness
      a collapsed column), else from the Summarizer moments in
      ``stats``. Columns with zero variance are exempt — standardization
      drops them entirely.
    - **multiplier overflow**: the backward sweep quantizes the per-row
      multiplier ``w * residual`` to e4m3 in-kernel; weights beyond the
      e4m3 range would overflow to NaN mid-fit.

    Returns ``None`` when fp8 is safe, else a human-readable reason (the
    ``PrecisionFallback`` event carries it verbatim).
    """
    if probe_ratio is not None:
        ratio = np.asarray(probe_ratio, dtype=np.float64)
        live = ratio > 0
    else:
        std = np.asarray(stats.std, dtype=np.float64)
        absmax = np.maximum(np.abs(np.asarray(stats.max)),
                            np.abs(np.asarray(stats.min)))
        live = std > 0
        ratio = np.where(live, absmax / np.where(live, std, 1.0), 0.0)
    if live.any():
        worst = float(ratio[live].max())
        if worst > FP8_PROBE_RATIO:
            j = int(np.argmax(np.where(live, ratio, -np.inf)))
            return (f"column {j} has absmax/std {worst:.1f} > "
                    f"{FP8_PROBE_RATIO:g}: e4m3 rounding would exceed the "
                    f"documented envelope after standardization")
    if w_max is not None and w_max > FP8_MAX:
        return (f"max instance weight {w_max:.1f} > {FP8_MAX:g}: the "
                f"backward multiplier would overflow e4m3's finite range")
    return None


@dataclass
class Instance:
    """One labeled weighted row (ref Instance.scala case class Instance)."""

    label: float
    weight: float
    features: Vector


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def blockify_arrays(
    x: np.ndarray,
    y: Optional[np.ndarray],
    w: Optional[np.ndarray],
    n_shards: int,
    rows_multiple: int = 8,
    dtype=np.float32,
    yw_dtype=None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Pad (x, y, w) to a shard-divisible row count with zero-weight rows.

    Returns (x_pad, y_pad, w_pad, n_true). Row count is padded to a multiple
    of ``n_shards * rows_multiple`` (sublane-friendly shards). ``dtype`` is
    the DATA tier (X only); ``y``/``w`` are blockified in ``yw_dtype``
    (default :func:`compute_dtype`) — the (n,) vectors are noise next to X,
    and keeping them at accumulator width keeps weight sums, label moments
    and the optimizers' state dtype exact across tiers.
    """
    n = x.shape[0]
    if yw_dtype is None:
        yw_dtype = compute_dtype()
    if y is None:
        y = np.zeros(n, dtype=yw_dtype)
    if w is None:
        w = np.ones(n, dtype=yw_dtype)
    target = max(_round_up(n, n_shards * rows_multiple), n_shards * rows_multiple)
    pad = target - n
    x_pad = np.zeros((target, x.shape[1]), dtype=dtype)
    x_pad[:n] = x
    y_pad = np.zeros(target, dtype=yw_dtype)
    y_pad[:n] = y
    w_pad = np.zeros(target, dtype=yw_dtype)
    w_pad[:n] = w
    return x_pad, y_pad, w_pad, n


def rows_to_dense(features: Sequence[Vector], n_features: Optional[int] = None) -> np.ndarray:
    """Stack a sequence of (possibly sparse) vectors into a dense matrix."""
    if n_features is None:
        n_features = max(f.size for f in features)
    out = np.zeros((len(features), n_features), dtype=np.float64)
    for i, f in enumerate(features):
        if isinstance(f, SparseVector):
            out[i, f.indices] = f.values
        else:
            out[i, : f.size] = f.to_array()
    return out
