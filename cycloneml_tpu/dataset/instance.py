"""Instance blocking — the physical unit of ML data.

The reference stacks rows into per-partition matrices so aggregators can use
level-2/3 BLAS (ref: ml/feature/Instance.scala:39 InstanceBlock,
blokifyWithMaxMemUsage:146,182). On TPU the same idea is carried further:
the whole dataset becomes dense device arrays ``(rows, features)`` row-sharded
over the mesh, padded with zero-weight rows so every shard is equal-sized and
shapes stay static for XLA. Zero weight makes padding exactly neutral in all
weighted aggregators — the invariant every estimator relies on.

Sparse handling (SURVEY §7 hard-parts): XLA requires static shapes, so sparse
rows are densified block-wise at ingest (scipy CSR → dense numpy → device).
For very wide sparse data a hashed/feature-sub-block path can be added at
this boundary without touching estimators.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Optional, Sequence, Tuple, Union

import numpy as np

from cycloneml_tpu.linalg.vectors import DenseVector, SparseVector, Vector


def compute_dtype():
    """The ACCUMULATOR float dtype (``cyclone.compute.dtype`` tier): float64
    only when jax x64 is enabled (CPU parity tests); on TPU the MXU path is
    float32 and requesting f64 would silently canonicalize anyway — this
    makes the choice explicit. Labels, weights, optimizer state and every
    psum accumulator live here; the design matrix lives in the (possibly
    narrower) data tier — see :func:`data_dtype`."""
    try:
        import jax
        return np.float64 if jax.config.jax_enable_x64 else np.float32
    except Exception:
        return np.float32


def data_dtype(conf=None):
    """The DATA-tier storage dtype (``cyclone.data.dtype``): what a
    materialized design matrix is stored as. Default ('auto') is bfloat16 —
    the sweeps are bandwidth-bound, so X's width IS the fit's speed — except
    under jax x64 (the parity/test config), where auto resolves to float64
    so golden suites see pre-tier numerics. Aggregators/kernels upcast to
    :func:`compute_dtype` INSIDE the kernel; nothing re-materializes X
    wider than this. ``conf`` defaults to the active context's."""
    from cycloneml_tpu.conf import DATA_DTYPE
    name = "auto"
    if conf is None:
        try:
            from cycloneml_tpu import context as _c
            if _c._active_context is not None:
                conf = _c._active_context.conf
        except Exception:
            conf = None
    if conf is not None:
        name = str(conf.get(DATA_DTYPE))
    if name == "auto":
        if compute_dtype() is np.float64:
            return np.float64  # x64 parity runs keep the full-width tier
        import ml_dtypes
        return ml_dtypes.bfloat16
    if name == "bfloat16":
        import ml_dtypes
        return ml_dtypes.bfloat16
    return np.dtype(name).type


def is_narrow_dtype(dt) -> bool:
    """True for sub-float32 storage dtypes (bf16/f16) — the tier boundary
    where fp32 accumulation becomes mandatory (Micikevicius et al. 2018)."""
    try:
        return np.dtype(dt).itemsize < 4
    except TypeError:
        return False


@dataclass
class Instance:
    """One labeled weighted row (ref Instance.scala case class Instance)."""

    label: float
    weight: float
    features: Vector


def _round_up(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


def blockify_arrays(
    x: np.ndarray,
    y: Optional[np.ndarray],
    w: Optional[np.ndarray],
    n_shards: int,
    rows_multiple: int = 8,
    dtype=np.float32,
    yw_dtype=None,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, int]:
    """Pad (x, y, w) to a shard-divisible row count with zero-weight rows.

    Returns (x_pad, y_pad, w_pad, n_true). Row count is padded to a multiple
    of ``n_shards * rows_multiple`` (sublane-friendly shards). ``dtype`` is
    the DATA tier (X only); ``y``/``w`` are blockified in ``yw_dtype``
    (default :func:`compute_dtype`) — the (n,) vectors are noise next to X,
    and keeping them at accumulator width keeps weight sums, label moments
    and the optimizers' state dtype exact across tiers.
    """
    n = x.shape[0]
    if yw_dtype is None:
        yw_dtype = compute_dtype()
    if y is None:
        y = np.zeros(n, dtype=yw_dtype)
    if w is None:
        w = np.ones(n, dtype=yw_dtype)
    target = max(_round_up(n, n_shards * rows_multiple), n_shards * rows_multiple)
    pad = target - n
    x_pad = np.zeros((target, x.shape[1]), dtype=dtype)
    x_pad[:n] = x
    y_pad = np.zeros(target, dtype=yw_dtype)
    y_pad[:n] = y
    w_pad = np.zeros(target, dtype=yw_dtype)
    w_pad[:n] = w
    return x_pad, y_pad, w_pad, n


def rows_to_dense(features: Sequence[Vector], n_features: Optional[int] = None) -> np.ndarray:
    """Stack a sequence of (possibly sparse) vectors into a dense matrix."""
    if n_features is None:
        n_features = max(f.size for f in features)
    out = np.zeros((len(features), n_features), dtype=np.float64)
    for i, f in enumerate(features):
        if isinstance(f, SparseVector):
            out[i, f.indices] = f.values
        else:
            out[i, : f.size] = f.to_array()
    return out
