"""Distributed dataset abstractions.

Two tiers replace the reference's RDD (ref: core/src/main/scala/org/apache/
spark/rdd/RDD.scala:83):

- ``PartitionedDataset`` — host-resident partitioned collection with the RDD
  functional surface (map/filter/mapPartitions/reduce/treeAggregate/collect,
  lazy lineage, caching, checkpoint). Control-plane work (ETL-ish, object
  data) runs in host threads; this is deliberately thin — the numeric path
  does not live here.

- ``InstanceDataset`` — the numeric tier: dense device arrays (X, y, w)
  row-sharded over the mesh (the InstanceBlock physical layout, ref:
  ml/feature/Instance.scala:39). Aggregations are jit-compiled shard_map
  programs whose psums replace treeAggregate (ref RDD.scala:1223); persist
  maps to device/host placement; checkpoint writes npz shards.
"""

from __future__ import annotations

import concurrent.futures as cf
import functools

import os
from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from cycloneml_tpu.dataset.instance import blockify_arrays, rows_to_dense
from cycloneml_tpu.linalg.vectors import Vector
from cycloneml_tpu.parallel import collectives
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

_POOL: Optional[cf.ThreadPoolExecutor] = None


def _pool() -> cf.ThreadPoolExecutor:
    global _POOL
    if _POOL is None:
        _POOL = cf.ThreadPoolExecutor(max_workers=os.cpu_count() or 8,
                                      thread_name_prefix="cyclone-task")
    return _POOL


class PartitionedDataset:
    """Host-tier RDD analog: lazy, lineage-based, partitioned."""

    def __init__(self, ctx, partitions_fn: Callable[[], List[List[Any]]],
                 num_partitions: int, name: str = ""):
        self.ctx = ctx
        self._compute = partitions_fn
        self.num_partitions = num_partitions
        self.name = name or "dataset"
        self._cached: Optional[List[List[Any]]] = None
        self._checkpoint_path: Optional[str] = None

    # -- construction ---------------------------------------------------------
    @classmethod
    def from_sequence(cls, ctx, data: List[Any], num_partitions: int) -> "PartitionedDataset":
        data = list(data)
        n = max(1, num_partitions)

        def compute():
            size = (len(data) + n - 1) // n if data else 0
            return [data[i * size:(i + 1) * size] for i in range(n)]

        return cls(ctx, compute, n, "parallelize")

    # -- materialization ------------------------------------------------------
    def _partitions(self) -> List[List[Any]]:
        if self._cached is not None:
            return self._cached
        if self._checkpoint_path is not None:
            import pickle
            with open(self._checkpoint_path, "rb") as fh:
                return pickle.load(fh)
        return self._compute()

    def cache(self) -> "PartitionedDataset":
        return self.persist()

    def persist(self) -> "PartitionedDataset":
        if self._cached is None:
            self._cached = self._partitions()
        return self

    def unpersist(self) -> "PartitionedDataset":
        self._cached = None
        return self

    def checkpoint(self) -> "PartitionedDataset":
        """Truncate lineage by writing partitions to the checkpoint dir
        (ref: RDD.scala:1631, ReliableCheckpointRDD.scala:147)."""
        import pickle
        d = self.ctx.checkpoint_dir
        if not d:
            raise RuntimeError("checkpoint dir not set; call set_checkpoint_dir")
        os.makedirs(d, exist_ok=True)
        path = os.path.join(d, f"{self.name}-{id(self)}.pkl")
        parts = []
        for i, p in enumerate(self._partitions()):
            from cycloneml_tpu.dataset.spill import SpilledPartition
            if isinstance(p, SpilledPartition):
                # a /tmp path reference would not survive tmp cleanup —
                # the checkpoint must own a durable copy of the data
                import shutil
                dst = os.path.join(d, f"{self.name}-{id(self)}-p{i}.blk")
                shutil.copyfile(p.path, dst)
                parts.append(SpilledPartition(dst, p.n_rows))
            else:
                parts.append(p)
        with open(path, "wb") as fh:
            pickle.dump(parts, fh)
        self._checkpoint_path = path
        self._compute = lambda: None  # lineage truncated
        return self

    # -- transformations (lazy) -----------------------------------------------
    def _derive(self, fn: Callable[[List[List[Any]]], List[List[Any]]],
                name: str, num_partitions: Optional[int] = None) -> "PartitionedDataset":
        parent = self
        box: List["PartitionedDataset"] = []

        def compute():
            parts = fn(parent._partitions())
            # partition-count metadata follows what fn actually produced
            # (AQE coalescing and exchange ownership decide counts at
            # materialization, not at derive time)
            if box:
                box[0].num_partitions = len(parts)
            return parts

        # `is None`, not falsy-or: a rank owning ZERO exchange buckets
        # legitimately derives a 0-partition dataset
        ds = PartitionedDataset(
            self.ctx, compute,
            self.num_partitions if num_partitions is None else num_partitions,
            name)
        box.append(ds)
        return ds

    def map(self, f: Callable) -> "PartitionedDataset":
        return self._derive(lambda ps: [[f(x) for x in p] for p in ps], "map")

    def filter(self, f: Callable) -> "PartitionedDataset":
        return self._derive(lambda ps: [[x for x in p if f(x)] for p in ps], "filter")

    def flat_map(self, f: Callable) -> "PartitionedDataset":
        return self._derive(
            lambda ps: [[y for x in p for y in f(x)] for p in ps], "flatMap")

    def map_partitions(self, f: Callable[[Iterable], Iterable]) -> "PartitionedDataset":
        return self._derive(lambda ps: [list(f(iter(p))) for p in ps], "mapPartitions")

    def map_partitions_with_index(self, f: Callable[[int, Iterable], Iterable]) -> "PartitionedDataset":
        return self._derive(
            lambda ps: [list(f(i, iter(p))) for i, p in enumerate(ps)],
            "mapPartitionsWithIndex")

    def zip_with_index(self) -> "PartitionedDataset":
        def fn(ps):
            out, i = [], 0
            for p in ps:
                out.append([(x, i + j) for j, x in enumerate(p)])
                i += len(p)
            return out
        return self._derive(fn, "zipWithIndex")

    def repartition(self, n: int) -> "PartitionedDataset":
        def fn(ps):
            flat = [x for p in ps for x in p]
            size = (len(flat) + n - 1) // n if flat else 0
            return [flat[i * size:(i + 1) * size] for i in range(n)]
        return self._derive(fn, "repartition", n)

    coalesce = repartition

    def group_by_key(self) -> "PartitionedDataset":
        """Hash-partition key/value pairs (host-tier shuffle analog).

        Partition assignment uses a PYTHONHASHSEED-independent hash (the
        reference's Partitioner contract: every process must agree), and
        each bucket aggregates through an ExternalAppendOnlyMap that spills
        sorted runs to disk past ``cyclone.shuffle.spill.rowBudget`` values
        per bucket (ref ExternalAppendOnlyMap.scala:55). Output partitions
        whose VALUE count exceeds the budget become disk-backed
        :class:`SpilledPartition` sequences instead of lists, so both the
        aggregation working set and the shuffle output are bounded; the
        cross-process variant of this shuffle is
        ``parallel.exchange.exchange_group_by_key``."""
        n = self.num_partitions
        from cycloneml_tpu.conf import SHUFFLE_SPILL_ROW_BUDGET
        budget = int(self.ctx.conf.get(SHUFFLE_SPILL_ROW_BUDGET)) \
            if hasattr(self.ctx, "conf") else 1 << 20

        from cycloneml_tpu.parallel.exchange import (
            active_exchange_group, exchange_group_partitions)
        group = active_exchange_group() if hasattr(self.ctx, "conf") else None
        if group is not None:
            # multihost: route the shuffle over the wire fabric — every
            # cooperating process runs this same lineage SPMD-style and
            # keeps the groups it owns (ShuffleExchangeExec analog). The
            # exchange is a collective: materializing this dataset on one
            # rank requires every rank to reach the same point.
            rank, addresses, n_buckets = group

            n_owned = sum(1 for b in range(n_buckets)
                          if b % len(addresses) == rank)
            from cycloneml_tpu.conf import (ADAPTIVE_ENABLED,
                                            ADVISORY_PARTITION_BYTES,
                                            ADVISORY_PARTITION_ROWS)
            adaptive = self.ctx.conf.get(ADAPTIVE_ENABLED)
            advisory = (self.ctx.conf.get(ADVISORY_PARTITION_ROWS)
                        if adaptive else None)
            # byte target takes precedence (Spark's
            # advisoryPartitionSizeInBytes semantics); rows are the
            # fallback when it is explicitly zeroed
            advisory_b = (self.ctx.conf.get(ADVISORY_PARTITION_BYTES)
                          if adaptive else None)

            def fn(ps):
                # _derive syncs num_partitions to whatever this returns,
                # so the AQE-coalesced count is never misreported
                return exchange_group_partitions(
                    (kv for p in ps for kv in p), rank, addresses,
                    n_buckets, row_budget=budget, advisory_rows=advisory,
                    advisory_bytes=advisory_b)
            return self._derive(fn, "groupByKey(exchange)", n_owned)

        def fn(ps):
            from cycloneml_tpu.dataset.spill import (ExternalAppendOnlyMap,
                                                     materialize_grouped,
                                                     stable_hash)
            # budget is PER BUCKET, matching the conf doc (≈ the reference's
            # per-collection numElementsForceSpillThreshold)
            buckets = [ExternalAppendOnlyMap(row_budget=budget)
                       for _ in range(n)]
            for p in ps:
                for k, v in p:
                    buckets[stable_hash(k) % n].insert(k, v)
            # output partitions spill too (r2 verdict item 5): the shared
            # materializer turns each bucket's stream into a list or a
            # disk-backed partition past the budget
            return [materialize_grouped(b.items(), budget) for b in buckets]
        return self._derive(fn, "groupByKey", n)

    def reduce_by_key(self, f: Callable) -> "PartitionedDataset":
        return self.group_by_key().map(
            lambda kv: (kv[0], functools.reduce(f, kv[1])))

    def union(self, other: "PartitionedDataset") -> "PartitionedDataset":
        parent = self

        def compute():
            return parent._partitions() + other._partitions()
        return PartitionedDataset(self.ctx, compute,
                                  self.num_partitions + other.num_partitions, "union")

    # -- actions (eager, threaded over partitions) ----------------------------
    def _run_per_partition(self, f: Callable[[List[Any]], Any]) -> List[Any]:
        parts = self._partitions()
        return list(_pool().map(f, parts))

    def collect(self) -> List[Any]:
        return [x for p in self._partitions() for x in p]

    def count(self) -> int:
        return sum(self._run_per_partition(len))

    def take(self, n: int) -> List[Any]:
        out: List[Any] = []
        for p in self._partitions():
            out.extend(p[: n - len(out)])
            if len(out) >= n:
                break
        return out

    def first(self) -> Any:
        got = self.take(1)
        if not got:
            raise ValueError("empty dataset")
        return got[0]

    def reduce(self, f: Callable) -> Any:
        partials = [functools.reduce(f, p) for p in self._run_per_partition(list) if p]
        if not partials:
            raise ValueError("empty dataset")
        return functools.reduce(f, partials)

    def aggregate(self, zero: Any, seq_op: Callable, comb_op: Callable) -> Any:
        import copy
        partials = self._run_per_partition(
            lambda p: functools.reduce(seq_op, p, copy.deepcopy(zero)))
        return functools.reduce(comb_op, partials, copy.deepcopy(zero))

    def tree_aggregate(self, zero: Any, seq_op: Callable, comb_op: Callable,
                       depth: int = 2) -> Any:
        """Log-depth host reduction (ref RDD.scala:1223). The numeric tier
        uses psum instead; this is the object-data fallback."""
        import copy
        partials = self._run_per_partition(
            lambda p: functools.reduce(seq_op, p, copy.deepcopy(zero)))
        while len(partials) > 2 and depth > 1:
            scale = max(2, int(np.ceil(len(partials) ** (1.0 / depth))))
            groups = [partials[i::scale] for i in range(scale)]
            partials = [functools.reduce(comb_op, g) for g in groups if g]
            depth -= 1
        return functools.reduce(comb_op, partials, copy.deepcopy(zero))

    def foreach(self, f: Callable) -> None:
        self._run_per_partition(lambda p: [f(x) for x in p])

    def is_empty(self) -> bool:
        return not self.take(1)

    # -- bridge to the numeric tier -------------------------------------------
    def to_instance_dataset(self, n_features: Optional[int] = None,
                            label_fn=None, weight_fn=None, features_fn=None) -> "InstanceDataset":
        rows = self.collect()
        features_fn = features_fn or (lambda r: r.features)
        label_fn = label_fn or (lambda r: getattr(r, "label", 0.0))
        weight_fn = weight_fn or (lambda r: getattr(r, "weight", 1.0))
        feats = [features_fn(r) for r in rows]
        x = rows_to_dense(feats, n_features)
        y = np.array([label_fn(r) for r in rows], dtype=np.float64)
        w = np.array([weight_fn(r) for r in rows], dtype=np.float64)
        return InstanceDataset.from_numpy(self.ctx, x, y, w)


def _npz_pack(x: np.ndarray):
    """numpy's npz format silently drops extension dtypes — a bf16 block
    written directly loads back as raw ``|V2`` bytes. Pack narrow extension
    floats as an unsigned bit-view (uint16 for the 2-byte bf16 tier, uint8
    for the 1-byte fp8 tier) plus a dtype tag (returned as
    ``(packed, dtype_str)``); plain float arrays pass through untagged."""
    dt = np.dtype(x.dtype)
    if dt.kind == "V" or str(dt).startswith("float8"):
        view = np.uint8 if dt.itemsize == 1 else np.uint16
        return x.view(view), str(x.dtype)
    return x, ""


def _npz_unpack(x: np.ndarray, dtype_str) -> np.ndarray:
    tag = str(dtype_str)
    if not tag:
        return x
    try:
        dt = np.dtype(tag)
    except TypeError as e:
        # a torn/corrupt tag must be a loud load error, never silently
        # reinterpreted bytes
        raise ValueError(
            f"corrupt npz dtype tag {tag!r}: not a known dtype") from e
    if dt.itemsize != x.dtype.itemsize:
        raise ValueError(
            f"corrupt npz dtype tag {tag!r}: itemsize {dt.itemsize} does "
            f"not match the packed {x.dtype} payload")
    return x.view(dt)


def fp8_fallback(ds: "InstanceDataset", estimator: str,
                 reason: str) -> "InstanceDataset":
    """Leave the fp8 storage tier for THIS fit: dequantize to bf16 and
    surface the decision — a ``PrecisionFallback`` event on the context
    bus and a ``precision.fallback`` tracing instant (the
    ``FitProfile.fp8_fallbacks`` counter). The estimator keeps training;
    only the storage rung changes."""
    from cycloneml_tpu.observe import tracing
    from_dt = str(ds.x.dtype)
    logger.warning("%s: falling back from %s to bfloat16 storage — %s",
                   estimator, from_dt, reason)
    tracing.instant("precision.fallback", estimator=estimator,
                    reason=reason, from_dtype=from_dt)
    bus = getattr(ds.ctx, "listener_bus", None)
    if bus is not None:
        from cycloneml_tpu.util.events import PrecisionFallback
        try:
            bus.post(PrecisionFallback(estimator=estimator,
                                       from_dtype=from_dt,
                                       to_dtype="bfloat16", reason=reason))
        except Exception:
            pass  # a stopped bus must not fail the fit
    return ds.dequantized()


def resolve_fp8_fit(ds: "InstanceDataset", stats,
                    estimator: str) -> "InstanceDataset":
    """The per-fit fp8 safety rail: run the cheap envelope probe
    (``instance.fp8_probe_ok`` — condition/scale heuristics on the
    one-pass Summarizer moments, zero extra data passes) and fall back to
    bf16 storage when e4m3 would break the documented accuracy envelope.
    No-op for non-quantized datasets."""
    if ds.x_scale is None:
        return ds
    from cycloneml_tpu.dataset.instance import fp8_probe_ok
    w_max = None
    try:
        w_host = ds.w_host()
        if w_host is not None and len(w_host):
            w_max = float(np.max(w_host))
    except Exception:
        w_max = None
    reason = fp8_probe_ok(stats, w_max,
                          probe_ratio=ds._fp8_probe_ratio)
    if reason is None:
        return ds
    return fp8_fallback(ds, estimator, reason)


@functools.lru_cache(maxsize=None)
def _widen_prog(dtype_str: str):
    """Jitted fp8 dequantization pass, cached per target dtype so repeated
    fallbacks replay one compiled program per (shape, mesh)."""
    import jax
    import jax.numpy as jnp
    dt = np.dtype(dtype_str)

    @jax.jit
    def widen(x, s):
        return (x.astype(jnp.float32) * s[None, :]).astype(dt)

    return widen


class InstanceDataset:
    """Numeric tier: row-sharded device arrays with static shapes.

    The unit every estimator trains on. ``x`` is (n_pad, d), ``y``/``w`` are
    (n_pad,), all sharded over (replica, data); padding rows carry w=0.
    """

    def __init__(self, ctx, x, y, w, n_rows: int, n_features: int,
                 valid_mask: Optional[np.ndarray] = None,
                 x_scale: Optional[np.ndarray] = None):
        self.ctx = ctx
        self._x = x
        self._y = y
        self._w = w
        # fp8 storage tier: per-column dequantization scales (float64,
        # accumulator width — host-resident, (d,)). x holds e4m3 CODES;
        # the real value is x * x_scale[None, :]. None for every wider
        # tier. Consumers fold the scale into their replicated (d,)
        # vectors (inv_std, kernel scale operands) — the wide X never
        # re-materializes.
        self._x_scale: Optional[np.ndarray] = (
            np.asarray(x_scale, dtype=np.float64)
            if x_scale is not None else None)
        # materialization-time per-column absmax/std of the RAW data —
        # the fp8 envelope probe's condition input (post-quantization
        # stats cannot witness a collapsed column); rides the scales
        self._fp8_probe_ratio: Optional[np.ndarray] = None
        self._host: Optional[Tuple[np.ndarray, np.ndarray, np.ndarray]] = None
        # (y, w) host twins kept when construction started from numpy —
        # estimators read label histograms/weights every fit, and a
        # device→host readback through a TPU relay costs seconds
        self._yw_host: Optional[Tuple[np.ndarray, np.ndarray]] = None
        # real-row mask when padding is interleaved per shard (chunked
        # loaders); None means padding sits at the global tail ([:n_rows])
        self._valid_mask: Optional[np.ndarray] = valid_mask
        self._disk_path: Optional[str] = None  # DISK storage tier source
        self._storage_cb = None  # StorageManager notification hook
        self._array_parent = None      # weakref: dataset we share arrays with
        self._derived_children = None  # WeakSet of datasets sharing ours
        # padded geometry captured up-front so storage accounting never
        # has to touch (and possibly restore) the device arrays; X and the
        # (y, w) vectors can sit in DIFFERENT tiers (bf16 data tier vs the
        # fp32/f64 accumulator tier), so both itemsizes are recorded
        self._n_pad = int(x.shape[0]) if x is not None else 0
        self._itemsize = int(np.dtype(str(x.dtype)).itemsize) if x is not None else 4
        self._yw_itemsize = int(np.dtype(str(y.dtype)).itemsize) \
            if y is not None else self._itemsize
        # y can be a stacked (n_pad, K) label matrix (fit_stacked derives
        # one); storage accounting must count all K columns
        self._y_cols = (int(np.prod(y.shape[1:]))
                        if y is not None and len(y.shape) > 1 else 1)
        self.n_rows = n_rows
        self.n_features = n_features

    def derive(self, x=None, y=None, w=None,
               n_features: Optional[int] = None) -> "InstanceDataset":
        """A dataset with some arrays replaced and THIS dataset's row
        metadata (row count, interleaved-padding mask, host label twins when
        y/w are unchanged) preserved. Every row-aligned transformation
        (standardization, normalization, X·B products) must construct its
        result through this — a raw ``InstanceDataset(...)`` call silently
        drops the padding mask and corrupts chunk-loaded datasets."""
        # property access (not _x) so an evicted dataset restores instead
        # of silently deriving a dataset with no arrays at all
        ds = InstanceDataset(self.ctx,
                             self.x if x is None else x,
                             self.y if y is None else y,
                             self.w if w is None else w,
                             self.n_rows,
                             self.n_features if n_features is None
                             else n_features,
                             valid_mask=self._valid_mask,
                             # quantization scales describe X: they follow
                             # an unchanged X and are dropped with a
                             # replaced one (the replacement is presumed
                             # dequantized — see dequantized())
                             x_scale=self._x_scale if x is None else None)
        if x is None:
            ds._fp8_probe_ratio = self._fp8_probe_ratio
        if y is None and w is None:
            ds._yw_host = self._yw_host
        # derived datasets SHARE unchanged device arrays with this one;
        # the StorageManager must not demote either side while the other
        # is alive (persist_host/persist_disk delete the shared buffers).
        # Link to the ROOT of the derive chain too: arrays flow
        # transitively, and a dead intermediate must not break the
        # protection between grandparent and grandchild (review r4)
        import weakref
        root = self
        while root._array_parent is not None:
            p = root._array_parent()
            if p is None:
                break
            root = p
        ds._array_parent = weakref.ref(root)
        for owner in ({id(root): root, id(self): self}).values():
            if owner._derived_children is None:
                owner._derived_children = weakref.WeakSet()
            owner._derived_children.add(ds)
        return ds

    def attach_host_labels(self, y: np.ndarray, w: np.ndarray) -> "InstanceDataset":
        """Attach padded host twins of (y, w) so ``y_host``/``w_host`` never
        pay a device readback — the supported way for external constructors
        (generators, chunked loaders) to install the cache ``from_numpy``
        sets internally."""
        self._yw_host = (y, w)
        return self

    def to_instance_dataset(self, features_col=None, label_col=None,
                            weight_col=None, dtype=None,
                            fp8_capable: bool = False) -> "InstanceDataset":
        """An InstanceDataset is already device-placed instance blocks:
        every estimator's ``frame.to_instance_dataset(...)`` bridge accepts
        one transparently (column names and dtype are frame concepts and
        are ignored — the data is used as placed). A quantized (fp8)
        dataset handed to a NON-capable estimator dequantizes to bf16
        first — raw e4m3 codes must never be read as values."""
        if self._x_scale is not None and not fp8_capable:
            return fp8_fallback(
                self, "to_instance_dataset",
                "estimator is not fp8-capable; dequantizing its view")
        return self

    def y_host(self) -> np.ndarray:
        """Padded label vector as numpy, without a device readback when the
        dataset was built from host arrays."""
        if self._yw_host is not None:
            return self._yw_host[0]
        return np.asarray(self.y)

    def w_host(self) -> np.ndarray:
        if self._yw_host is not None:
            return self._yw_host[1]
        return np.asarray(self.w)

    def _restore_device(self) -> None:
        restored = False
        if self._x is None and self._host is not None:
            rt = self.ctx.mesh_runtime
            self._x = rt.device_put_sharded_rows(self._host[0])
            self._y = rt.device_put_sharded_rows(self._host[1])
            self._w = rt.device_put_sharded_rows(self._host[2])
            restored = True
        elif self._x is None and self._disk_path:
            # DISK storage tier (StorageManager eviction): reload the npz
            # block and re-place it on the mesh transparently
            z = np.load(self._disk_path)
            rt = self.ctx.mesh_runtime
            self._x = rt.device_put_sharded_rows(
                _npz_unpack(z["x"], z.get("x_dtype", "")))
            self._y = rt.device_put_sharded_rows(
                _npz_unpack(z["y"], z.get("y_dtype", "")))
            self._w = rt.device_put_sharded_rows(
                _npz_unpack(z["w"], z.get("w_dtype", "")))
            restored = True
        if restored and self._storage_cb is not None:
            # lazy restores must reach the StorageManager's accounting, or
            # device usage silently exceeds its budget until a touch()
            self._storage_cb(self)

    def release_device(self) -> None:
        """Free the device arrays (data must already live in a durable
        tier — host tuple or disk file)."""
        if self._host is None and not self._disk_path:
            raise RuntimeError("release_device would drop the only copy")
        for a in (self._x, self._y, self._w):
            try:
                a.delete()
            except Exception:
                pass
        self._x = self._y = self._w = None

    def persist_disk(self, path: str) -> "InstanceDataset":
        """Spill to an npz file and release BOTH device and host copies
        (the DISK storage tier; symmetric to :meth:`persist_host`).
        Writes from the host tuple when present — never re-uploads an
        evicted dataset to the device just to read it back."""
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        if self._host is not None:
            x, y, w = self._host
        else:
            x, y, w = (np.asarray(self.x), np.asarray(self.y),
                       np.asarray(self.w))
        extra = ({"valid_mask": self._valid_mask}
                 if self._valid_mask is not None else {})
        if self._x_scale is not None:
            # the codes are meaningless without their scales — spill both
            extra["x_scale"] = self._x_scale
            if self._fp8_probe_ratio is not None:
                extra["x_probe_ratio"] = self._fp8_probe_ratio
        # y rides the data tier too when it carries a stacked label matrix
        # (fit_stacked derives y at X's dtype) — pack all three
        x_packed, x_dtype = _npz_pack(x)
        y_packed, y_dtype = _npz_pack(y)
        w_packed, w_dtype = _npz_pack(w)
        np.savez(path, x=x_packed, x_dtype=x_dtype, y=y_packed,
                 y_dtype=y_dtype, w=w_packed, w_dtype=w_dtype,
                 n_rows=self.n_rows, n_features=self.n_features, **extra)
        self._disk_path = path if path.endswith(".npz") else path + ".npz"
        self._host = None
        if self._x is not None:
            self.release_device()
        return self

    def padded_bytes(self) -> int:
        """Storage footprint of the padded block (metadata only — never
        touches, and so never restores, the arrays)."""
        return self._n_pad * (self.n_features * self._itemsize
                              + (self._y_cols + 1) * self._yw_itemsize)

    @property
    def x(self):
        self._restore_device()
        return self._x

    @property
    def x_scale(self) -> Optional[np.ndarray]:
        """Per-column fp8 dequantization scales (float64 host (d,)), or
        None for every non-quantized tier. ``x`` stores codes; the value
        is ``x * x_scale``."""
        return self._x_scale

    def dequantized(self, dtype=None) -> "InstanceDataset":
        """A derived dataset with X dequantized out of the fp8 tier —
        the per-fit bf16 FALLBACK path (``dtype`` defaults to bfloat16,
        the next rung down). One elementwise device pass
        (``codes.astype(f32) * scale -> dtype``); sharding is preserved
        and y/w/metadata ride through ``derive``. No-op (self) when this
        dataset is not quantized."""
        if self._x_scale is None:
            return self
        import jax.numpy as jnp
        if dtype is None:
            import ml_dtypes
            dtype = ml_dtypes.bfloat16
        widen = _widen_prog(str(np.dtype(dtype)))
        return self.derive(
            x=widen(self.x, jnp.asarray(self._x_scale, jnp.float32)))

    @property
    def y(self):
        self._restore_device()
        return self._y

    @property
    def w(self):
        self._restore_device()
        return self._w

    @classmethod
    def from_numpy(cls, ctx, x: np.ndarray, y: Optional[np.ndarray] = None,
                   w: Optional[np.ndarray] = None, dtype=None) -> "InstanceDataset":
        from cycloneml_tpu.dataset.instance import (compute_dtype,
                                                    data_dtype, is_fp8_dtype,
                                                    quantize_fp8)
        if dtype is None:
            # X lands in the data tier (bf16 by default off-x64); y/w stay
            # at accumulator width — see blockify_arrays
            dtype = data_dtype(getattr(ctx, "conf", None))
        x_scale = probe_ratio = None
        if is_fp8_dtype(dtype):
            # the fp8 rung quantizes at materialization: per-column scales
            # keep every stored code finite (e4m3fn overflows to NaN) and
            # fold into the consumers' replicated vectors at fit time
            x, x_scale, probe_ratio = quantize_fp8(x, dtype)
        rt = ctx.mesh_runtime
        x_p, y_p, w_p, n = blockify_arrays(x, y, w, rt.data_parallelism,
                                           dtype=dtype,
                                           yw_dtype=compute_dtype())
        ds = cls(ctx,
                 rt.device_put_sharded_rows(x_p),
                 rt.device_put_sharded_rows(y_p),
                 rt.device_put_sharded_rows(w_p),
                 n, x.shape[1], x_scale=x_scale)
        ds._fp8_probe_ratio = probe_ratio
        ds._yw_host = (y_p, w_p)
        return ds

    @classmethod
    def from_dense_chunks(cls, ctx, chunks: Iterable, n_features: int,
                          dtype=None) -> "InstanceDataset":
        """Out-of-core dense ingest: build a row-sharded dataset from an
        iterator of ``(x_chunk, y_chunk_or_None, w_chunk_or_None)`` host
        chunks WITHOUT ever holding the full matrix in driver memory — the
        dense twin of ``SparseInstanceDataset.from_libsvm_stream`` (ref:
        HadoopRDD.scala:87 partition streaming; the round-2 verdict's
        out-of-core-dense demand).

        Each chunk is ``device_put`` onto one mesh device round-robin and
        released; at exhaustion the per-device chunk lists are concatenated
        ON DEVICE, padded to equal shard length with zero-weight rows, and
        stitched into global arrays with
        ``jax.make_array_from_single_device_arrays``. Driver peak memory is
        O(one chunk + the (n,) label/weight vectors); row order is
        chunk-round-robin over devices (a permutation of input order —
        training rows are exchangeable, padding carries w=0)."""
        import jax
        import jax.numpy as jnp
        from cycloneml_tpu.dataset.instance import compute_dtype, data_dtype
        if dtype is None:
            dtype = data_dtype(getattr(ctx, "conf", None))
        yw_dt = compute_dtype()
        rt = ctx.mesh_runtime
        if rt.mesh.devices.shape[2] != 1:
            raise ValueError(
                "from_dense_chunks shards rows over (replica, data) and "
                "requires model_parallelism == 1")
        devices = list(rt.mesh.devices.reshape(-1))
        n_dev = len(devices)

        per_dev: List[list] = [[] for _ in range(n_dev)]
        yw_host: List[list] = [[] for _ in range(n_dev)]  # [(y, w) chunks]
        n_true = 0
        for ci, (cx, cy, cw) in enumerate(chunks):
            cx = np.ascontiguousarray(cx, dtype=dtype)
            m = cx.shape[0]
            if cx.ndim != 2 or cx.shape[1] != n_features:
                raise ValueError(
                    f"chunk {ci} has shape {cx.shape}, expected "
                    f"(rows, {n_features})")
            cy = (np.zeros(m, dtype=yw_dt) if cy is None
                  else np.asarray(cy, dtype=yw_dt))
            cw = (np.ones(m, dtype=yw_dt) if cw is None
                  else np.asarray(cw, dtype=yw_dt))
            if len(cy) != m or len(cw) != m:
                # a silent mismatch would shift every later label in the
                # shard against its features
                raise ValueError(
                    f"chunk {ci}: y/w lengths ({len(cy)}/{len(cw)}) != "
                    f"x rows ({m})")
            # split every chunk across ALL devices (rotating the remainder)
            # so shard row counts stay balanced regardless of chunk count —
            # whole-chunk round-robin left shards up to one chunk apart,
            # permanently padding every later fit by that imbalance
            base, rem = divmod(m, n_dev)
            sizes = [base + (1 if (di - ci) % n_dev < rem else 0)
                     for di in range(n_dev)]
            lo = 0
            for di in range(n_dev):
                hi_ = lo + sizes[di]
                if hi_ > lo:
                    per_dev[di].append(
                        jax.device_put(cx[lo:hi_], devices[di]))
                    yw_host[di].append((cy[lo:hi_], cw[lo:hi_]))
                lo = hi_
            n_true += m

        dev_rows = [sum(int(c.shape[0]) for c in chunks_)
                    for chunks_ in per_dev]
        shard_rows = max(max(dev_rows), 8)
        shard_rows = ((shard_rows + 7) // 8) * 8  # sublane-friendly
        shards = []
        for di in range(n_dev):
            cs = per_dev[di]
            if cs:
                a = jnp.concatenate(cs) if len(cs) > 1 else cs[0]
            else:
                a = jax.device_put(
                    np.zeros((0, n_features), dtype=dtype), devices[di])
            pad = shard_rows - a.shape[0]
            if pad:
                a = jnp.pad(a, ((0, pad), (0, 0)))
            shards.append(a)
            per_dev[di] = None  # release chunk refs as we go

        n_pad = shard_rows * n_dev
        x = jax.make_array_from_single_device_arrays(
            (n_pad, n_features), rt.data_sharding(1), shards)
        # (n,) label/weight vectors assembled host-side in shard order —
        # tiny next to X (accumulator tier), and estimators want the host
        # twins anyway
        y_pad = np.zeros(n_pad, dtype=yw_dt)
        w_pad = np.zeros(n_pad, dtype=yw_dt)
        valid = np.zeros(n_pad, dtype=bool)
        for di in range(n_dev):
            off = di * shard_rows
            for cy, cw in yw_host[di]:
                y_pad[off:off + len(cy)] = cy
                w_pad[off:off + len(cw)] = cw
                valid[off:off + len(cy)] = True
                off += len(cy)
        ds = cls(ctx, x, rt.device_put_sharded_rows(y_pad),
                 rt.device_put_sharded_rows(w_pad), n_true, n_features)
        # padding is interleaved (per-shard tails), so readbacks need the
        # explicit real-row mask, not [:n_rows]
        ds._valid_mask = valid
        return ds.attach_host_labels(y_pad.astype(np.float64),
                                     w_pad.astype(np.float64))

    @property
    def shape(self) -> Tuple[int, int]:
        return (self.n_rows, self.n_features)

    def tree_aggregate_fn(self, fn: Callable, auto_psum: bool = True):
        """Compile ``fn(x_shard, y_shard, w_shard, *extras) -> pytree`` into a
        mesh-wide psum aggregation; returns jitted callable taking extras.
        With ``auto_psum=False``, ``fn`` runs its own collectives (pmax etc.)."""
        rt = self.ctx.mesh_runtime
        ds = self
        compiled = collectives.tree_aggregate(fn, rt, ds.x, ds.y, ds.w,
                                              auto_psum=auto_psum)

        def call(*extras):
            return compiled(ds.x, ds.y, ds.w, *extras)

        # expose the raw program + sharded operands so callers (e.g. the
        # device-resident line search) can inline this aggregation inside a
        # larger jitted program instead of dispatching it standalone
        call.compiled = compiled
        call.arrays = lambda: (ds.x, ds.y, ds.w)
        return call

    def map_batches(self, fn: Callable):
        """Apply a jitted elementwise/rowwise fn over the sharded arrays,
        returning new sharded arrays (stays on device)."""
        import jax
        return jax.jit(fn)(self.x, self.y, self.w)

    def persist(self, level: str = "DEVICE") -> "InstanceDataset":
        """Register with the context's StorageManager (the default storage
        path, ≈ ``rdd.persist()`` landing in the BlockManager): conf
        budgets (``cyclone.storage.deviceBudget``/``.hostBudget``) then
        bound what cold cached blocks hold, demoting LRU datasets down the
        DEVICE→HOST→DISK tiers."""
        mgr = getattr(self.ctx, "storage", None)
        if mgr is not None:
            mgr.persist(self, level)
        return self

    def cache(self) -> "InstanceDataset":
        return self.persist()

    def unpersist(self) -> "InstanceDataset":
        mgr = getattr(self.ctx, "storage", None)
        if mgr is not None:
            mgr.unpersist(self)
        return self

    def persist_host(self) -> "InstanceDataset":
        """Spill to host memory and release device HBM (≈ MEMORY_AND_DISK
        tier, ref LogisticRegression.scala:968 persists blocks). Arrays are
        transparently re-placed on the mesh at next access."""
        self._host = (np.asarray(self._x), np.asarray(self._y), np.asarray(self._w))
        for a in (self._x, self._y, self._w):
            try:
                a.delete()
            except Exception:
                pass
        self._x = self._y = self._w = None
        return self

    def checkpoint(self, path: str) -> str:
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        extra = ({"valid_mask": self._valid_mask}
                 if self._valid_mask is not None else {})
        if self._x_scale is not None:
            extra["x_scale"] = self._x_scale
            if self._fp8_probe_ratio is not None:
                extra["x_probe_ratio"] = self._fp8_probe_ratio
        x_packed, x_dtype = _npz_pack(np.asarray(self.x))
        y_packed, y_dtype = _npz_pack(np.asarray(self.y))
        w_packed, w_dtype = _npz_pack(np.asarray(self.w))
        np.savez(path, x=x_packed, x_dtype=x_dtype, y=y_packed,
                 y_dtype=y_dtype, w=w_packed, w_dtype=w_dtype,
                 n_rows=self.n_rows, n_features=self.n_features, **extra)
        return path

    @classmethod
    def restore(cls, ctx, path: str) -> "InstanceDataset":
        z = np.load(path if path.endswith(".npz") else path + ".npz")
        rt = ctx.mesh_runtime
        ds = cls(ctx,
                 rt.device_put_sharded_rows(
                     _npz_unpack(z["x"], z.get("x_dtype", ""))),
                 rt.device_put_sharded_rows(
                     _npz_unpack(z["y"], z.get("y_dtype", ""))),
                 rt.device_put_sharded_rows(
                     _npz_unpack(z["w"], z.get("w_dtype", ""))),
                 int(z["n_rows"]), int(z["n_features"]))
        if "valid_mask" in z:
            ds._valid_mask = z["valid_mask"]
        if "x_scale" in z:
            ds._x_scale = np.asarray(z["x_scale"], dtype=np.float64)
        if "x_probe_ratio" in z:
            ds._fp8_probe_ratio = np.asarray(z["x_probe_ratio"],
                                             dtype=np.float64)
        return ds

    def valid_indices(self) -> np.ndarray:
        """Padded-array positions of the real (non-padding) rows."""
        if self._valid_mask is not None:
            return np.nonzero(self._valid_mask)[0]
        return np.arange(self.n_rows)

    def unpad(self, arr: np.ndarray) -> np.ndarray:
        """Drop padding rows from a host array aligned with this dataset's
        padded row space. EVERY host readback that trims padding must go
        through this (or ``to_numpy``): chunked loaders interleave padding
        per shard, so ``arr[:n_rows]`` silently mixes padding in and real
        rows out."""
        if self._valid_mask is not None:
            return arr[self._valid_mask]
        return arr[:self.n_rows]

    def gather_rows(self, idx) -> np.ndarray:
        """Host copy of the given padded row positions — O(len(idx) · d)
        transfer; never materializes X host-side (the out-of-core-safe
        replacement for ``to_numpy()[0][idx]``).

        Implemented as a shard-LOCAL masked gather + psum: each shard
        contributes the requested rows it owns and zeros elsewhere. A global
        ``jnp.take`` would instead make XLA all-gather (replicate) X on every
        device — O(n · d) per device, an OOM at out-of-core scale. The index
        vector is padded to the next power of two so repeated calls with
        varying counts (k-means|| sampling) reuse a handful of programs."""
        import jax
        import jax.numpy as jnp
        from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS

        idx = np.asarray(idx, dtype=np.int64).ravel()
        m = len(idx)
        if m == 0:
            return np.zeros((0, self.n_features))
        m_pad = 1 << (m - 1).bit_length()
        idx_pad = np.zeros(m_pad, dtype=np.int64)
        idx_pad[:m] = idx

        call = getattr(self, "_gather_call", None)
        if call is None:
            d_size = self.ctx.mesh_runtime.mesh.devices.shape[1]

            def pick(xl, yl, wl, ii):
                per = xl.shape[0]
                shard = (jax.lax.axis_index(REPLICA_AXIS) * d_size
                         + jax.lax.axis_index(DATA_AXIS))
                local = ii - shard.astype(ii.dtype) * per
                ok = (local >= 0) & (local < per)
                rows = jnp.take(xl, jnp.clip(local, 0, per - 1), axis=0)
                # gathered rows ride the psum at ACCUMULATOR width: the
                # reduction is exact (one shard contributes, the rest
                # zeros) and fp8 codes refuse implicit promotion anyway
                return jnp.where(ok[:, None], rows.astype(wl.dtype), 0)

            call = self._gather_call = self.tree_aggregate_fn(pick)
        out = np.asarray(call(jnp.asarray(idx_pad)))[:m]
        if self._x_scale is not None:
            # fp8 codes -> values at the host boundary (O(m * d), host)
            out = out.astype(np.float64) * self._x_scale[None, :]
        return out

    def to_numpy(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Unpadded host copies (fp8 codes dequantized — host readbacks
        always see VALUES; only the device tier holds codes)."""
        if self._valid_mask is not None:
            m = self._valid_mask
            x, y, w = (np.asarray(self.x)[m], np.asarray(self.y)[m],
                       np.asarray(self.w)[m])
        else:
            n = self.n_rows
            x, y, w = (np.asarray(self.x)[:n], np.asarray(self.y)[:n],
                       np.asarray(self.w)[:n])
        if self._x_scale is not None:
            x = x.astype(np.float64) * self._x_scale[None, :]
        return x, y, w
