"""Data ingest.

Host ingest (SURVEY §7: parallel sharded readers → dense blocks → device
feed). LibSVM parity matters most: the reference's MLlib reads libsvm via
``MLUtils.loadLibSVMFile`` / the ``libsvm`` datasource, 1-based indices.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset


def parse_libsvm(path: str, n_features: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Parse a libsvm file to dense (X, y). Indices are 1-based on disk.

    Fast path: the multithreaded C++ parser (native/host.py); this Python
    loop is the fallback when the toolchain is unavailable."""
    try:
        from cycloneml_tpu.native.host import parse_libsvm_native
        got = parse_libsvm_native(path, n_features)
        if got is not None:
            return np.asarray(got[0], dtype=np.float64), got[1]
    except Exception:
        pass
    labels = []
    rows = []
    max_idx = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            idx = []
            vals = []
            for tok in parts[1:]:
                i, v = tok.split(":")
                idx.append(int(i) - 1)
                vals.append(float(v))
            if idx:
                max_idx = max(max_idx, max(idx))
            rows.append((np.array(idx, dtype=np.int32), np.array(vals)))
    d = n_features if n_features is not None else max_idx + 1
    x = np.zeros((len(rows), d), dtype=np.float64)
    for r, (idx, vals) in enumerate(rows):
        x[r, idx] = vals
    return x, np.array(labels, dtype=np.float64)


def read_libsvm(ctx, path: str, n_features: Optional[int] = None) -> InstanceDataset:
    x, y = parse_libsvm(path, n_features)
    return InstanceDataset.from_numpy(ctx, x, y)


def read_csv(ctx, path: str, label_col: int = 0, delimiter: str = ",",
             skip_header: bool = False) -> InstanceDataset:
    data = None
    try:
        from cycloneml_tpu.native.host import parse_csv_native
        data = parse_csv_native(path, delimiter, skip_header)
    except Exception:
        pass
    if data is None:
        data = np.loadtxt(path, delimiter=delimiter,
                          skiprows=1 if skip_header else 0)
    y = data[:, label_col]
    x = np.delete(data, label_col, axis=1)
    return InstanceDataset.from_numpy(ctx, x, y)
