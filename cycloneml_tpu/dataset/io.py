"""Data ingest.

Host ingest (SURVEY §7: parallel sharded readers → dense blocks → device
feed). LibSVM parity matters most: the reference's MLlib reads libsvm via
``MLUtils.loadLibSVMFile`` / the ``libsvm`` datasource, 1-based indices.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset


def parse_libsvm(path: str, n_features: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Parse a libsvm file to dense (X, y). Indices are 1-based on disk."""
    labels = []
    rows = []
    max_idx = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            idx = []
            vals = []
            for tok in parts[1:]:
                i, v = tok.split(":")
                idx.append(int(i) - 1)
                vals.append(float(v))
            if idx:
                max_idx = max(max_idx, max(idx))
            rows.append((np.array(idx, dtype=np.int32), np.array(vals)))
    d = n_features if n_features is not None else max_idx + 1
    x = np.zeros((len(rows), d), dtype=np.float64)
    for r, (idx, vals) in enumerate(rows):
        x[r, idx] = vals
    return x, np.array(labels, dtype=np.float64)


def read_libsvm(ctx, path: str, n_features: Optional[int] = None) -> InstanceDataset:
    x, y = parse_libsvm(path, n_features)
    return InstanceDataset.from_numpy(ctx, x, y)


def read_csv(ctx, path: str, label_col: int = 0, delimiter: str = ",",
             skip_header: bool = False) -> InstanceDataset:
    data = np.loadtxt(path, delimiter=delimiter, skiprows=1 if skip_header else 0)
    y = data[:, label_col]
    x = np.delete(data, label_col, axis=1)
    return InstanceDataset.from_numpy(ctx, x, y)
