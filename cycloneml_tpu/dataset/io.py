"""Data ingest.

Host ingest (SURVEY §7: parallel sharded readers → dense blocks → device
feed). LibSVM parity matters most: the reference's MLlib reads libsvm via
``MLUtils.loadLibSVMFile`` / the ``libsvm`` datasource, 1-based indices.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset


def parse_libsvm(path: str, n_features: Optional[int] = None) -> Tuple[np.ndarray, np.ndarray]:
    """Parse a libsvm file to dense (X, y). Indices are 1-based on disk.

    Fast path: the multithreaded C++ parser (native/host.py); this Python
    loop is the fallback when the toolchain is unavailable."""
    try:
        from cycloneml_tpu.native.host import parse_libsvm_native
        got = parse_libsvm_native(path, n_features)
        if got is not None:
            return np.asarray(got[0], dtype=np.float64), got[1]
    except Exception:
        pass
    labels = []
    rows = []
    max_idx = 0
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line or line.startswith("#"):
                continue
            parts = line.split()
            labels.append(float(parts[0]))
            idx = []
            vals = []
            for tok in parts[1:]:
                i, v = tok.split(":")
                idx.append(int(i) - 1)
                vals.append(float(v))
            if idx:
                max_idx = max(max_idx, max(idx))
            rows.append((np.array(idx, dtype=np.int32), np.array(vals)))
    d = n_features if n_features is not None else max_idx + 1
    x = np.zeros((len(rows), d), dtype=np.float64)
    for r, (idx, vals) in enumerate(rows):
        x[r, idx] = vals
    return x, np.array(labels, dtype=np.float64)


#: files above this size route through the out-of-core chunked readers
#: instead of whole-file materialization (override per-call with streamed=)
DENSE_STREAM_THRESHOLD = 256 << 20


def read_libsvm(ctx, path: str, n_features: Optional[int] = None,
                streamed: Optional[bool] = None) -> InstanceDataset:
    """Dense libsvm ingest. Large files (``streamed=None`` and size over
    :data:`DENSE_STREAM_THRESHOLD`, or ``streamed=True``) stream CSR chunks
    from the native scanner and densify block-by-block straight onto the
    mesh — the driver never holds the densified matrix (out-of-core path;
    ref MLUtils.scala:77 via HadoopRDD.scala:87 partition streaming).
    Streaming requires ``n_features`` (dense chunk width is fixed up-front;
    without it, fall back to the whole-file parser or use the sparse tier's
    ``from_libsvm_stream``, which can infer it)."""
    if streamed is None:
        big = os.path.getsize(path) > DENSE_STREAM_THRESHOLD
        streamed = n_features is not None and big
        if big and not streamed:
            from cycloneml_tpu.util.logging import get_logger
            get_logger(__name__).warning(
                "read_libsvm: %s exceeds the streaming threshold but "
                "n_features was not given — falling back to WHOLE-FILE "
                "driver materialization; pass n_features to stream, or use "
                "SparseInstanceDataset.from_libsvm_stream (infers it)", path)
    if streamed:
        if n_features is None:
            raise ValueError("streamed dense libsvm ingest requires "
                             "n_features (chunk width is fixed up-front)")
        return InstanceDataset.from_dense_chunks(
            ctx, _libsvm_dense_chunks(path, n_features), n_features)
    x, y = parse_libsvm(path, n_features)
    return InstanceDataset.from_numpy(ctx, x, y)


def iter_libsvm_chunks(path: str, n_features: int, chunk_rows: int = 65536):
    """Public alias of the dense libsvm chunk stream — the ``(x, y, w)``
    chunk contract shared by ``InstanceDataset.from_dense_chunks`` and the
    out-of-core shard builder (``oocore.StreamingDataset.from_chunks``)."""
    return _libsvm_dense_chunks(path, n_features, chunk_rows)


def _libsvm_dense_chunks(path: str, n_features: int,
                         chunk_rows: int = 65536):
    """Yield (x, y, None) dense blocks from the bounded-memory CSR streamer;
    densification is per-chunk, so peak host memory is one block."""
    from cycloneml_tpu.native.host import stream_libsvm_chunks
    for cy, cnnz, cfi, cfv, mf in stream_libsvm_chunks(
            path, chunk_rows=chunk_rows):
        if mf > n_features:
            raise ValueError(
                f"observed feature index {mf - 1} >= declared "
                f"n_features={n_features}")
        m = len(cy)
        x = np.zeros((m, n_features), dtype=np.float32)
        rows = np.repeat(np.arange(m), cnnz)
        x[rows, cfi] = cfv
        yield x, cy, None


def npy_header(path: str):
    """``(n_rows, n_cols, dtype)`` of a C-order 2-D .npy file — the shape
    probe the chunked/streamed readers size themselves from."""
    import numpy.lib.format as npf
    with open(path, "rb") as fh:
        version = npf.read_magic(fh)
        if version == (1, 0):
            shape, fortran, dt = npf.read_array_header_1_0(fh)
        elif version == (2, 0):
            shape, fortran, dt = npf.read_array_header_2_0(fh)
        else:
            shape, fortran, dt = npf._read_array_header(fh, version)
    if fortran or len(shape) != 2:
        raise ValueError("chunked .npy ingest requires a C-order 2-D array")
    return shape[0], shape[1], dt


def iter_npy_chunks(path: str, label_col: Optional[int] = None,
                    chunk_rows: int = 65536):
    """Yield ``(x, y_or_None, None)`` blocks of a 2-D .npy file with plain
    ``file.read`` (no mmap — mapped pages would count toward driver RSS and
    defeat the bounded-memory contract). The chunk contract shared by
    ``read_npy_chunked`` and the out-of-core shard builder."""
    import numpy.lib.format as npf
    n, d_file, dt = npy_header(path)
    row_bytes = d_file * dt.itemsize
    with open(path, "rb") as fh:
        version = npf.read_magic(fh)
        if version == (1, 0):
            npf.read_array_header_1_0(fh)
        elif version == (2, 0):
            npf.read_array_header_2_0(fh)
        else:
            npf._read_array_header(fh, version)
        done = 0
        while done < n:
            m = min(chunk_rows, n - done)
            buf = fh.read(m * row_bytes)
            if len(buf) != m * row_bytes:
                raise IOError(f"truncated .npy payload in {path!r}")
            block = np.frombuffer(buf, dtype=dt).reshape(m, d_file)
            if label_col is None:
                yield block, None, None
            else:
                y = block[:, label_col].astype(np.float64)
                yield np.delete(block, label_col, axis=1), y, None
            done += m


def read_npy_chunked(ctx, path: str, label_col: Optional[int] = None,
                     chunk_rows: int = 65536) -> InstanceDataset:
    """Out-of-core ingest of a .npy 2-D array: chunks stream through
    :func:`iter_npy_chunks` and land on the mesh as they arrive.
    ``label_col`` splits one column off as the label."""
    _, d_file, _ = npy_header(path)
    d = d_file - (1 if label_col is not None else 0)
    return InstanceDataset.from_dense_chunks(
        ctx, iter_npy_chunks(path, label_col, chunk_rows), d)


def _first_data_line(fh, skip_header: bool):
    if skip_header:
        fh.readline()
    for line in fh:  # blank lines anywhere (incl. leading) are skipped
        if line.strip():
            return line
    return None


def iter_csv_chunks(path: str, label_col: int = 0, delimiter: str = ",",
                    skip_header: bool = False, chunk_rows: int = 65536):
    """Yield ``(x, y, None)`` blocks of a CSV file, one line batch at a
    time — the chunk contract shared by ``read_csv_chunked`` and the
    out-of-core shard builder."""
    with open(path) as fh:
        first = _first_data_line(fh, skip_header)
        if first is None:
            return
        d_file = len(first.split(delimiter))
        batch = [first]
        for line in fh:
            if not line.strip():
                continue
            batch.append(line)
            if len(batch) >= chunk_rows:
                yield _csv_block(batch, delimiter, d_file, label_col)
                batch = []
        if batch:
            yield _csv_block(batch, delimiter, d_file, label_col)


def read_csv_chunked(ctx, path: str, label_col: int = 0, delimiter: str = ",",
                     skip_header: bool = False,
                     chunk_rows: int = 65536) -> InstanceDataset:
    """Out-of-core CSV ingest: parse line batches and place each block on
    the mesh as it is read; driver peak memory is one block."""
    # peek the width for from_dense_chunks without consuming the stream
    with open(path) as fh:
        head = _first_data_line(fh, skip_header)
    if head is None:
        raise ValueError(f"{path!r} has no data rows")
    d = len(head.split(delimiter)) - 1
    return InstanceDataset.from_dense_chunks(
        ctx, iter_csv_chunks(path, label_col, delimiter, skip_header,
                             chunk_rows), d)


def _csv_block(lines, delimiter, d_file, label_col):
    data = np.loadtxt(lines, delimiter=delimiter, ndmin=2)
    if data.shape[1] != d_file:
        raise ValueError(f"ragged CSV: expected {d_file} columns, "
                         f"got {data.shape[1]}")
    y = data[:, label_col]
    x = np.delete(data, label_col, axis=1)
    return x, y, None


def read_csv(ctx, path: str, label_col: int = 0, delimiter: str = ",",
             skip_header: bool = False) -> InstanceDataset:
    data = None
    try:
        from cycloneml_tpu.native.host import parse_csv_native
        data = parse_csv_native(path, delimiter, skip_header)
    except Exception:
        pass
    if data is None:
        data = np.loadtxt(path, delimiter=delimiter,
                          skiprows=1 if skip_header else 0)
    y = data[:, label_col]
    x = np.delete(data, label_col, axis=1)
    return InstanceDataset.from_numpy(ctx, x, y)
