"""CycloneContext — the driver entry point.

Analog of ``SparkContext`` (ref: core/src/main/scala/org/apache/spark/
SparkContext.scala:83): owns the conf, the device mesh (≈ executor fleet),
the listener bus + event journal (≈ LiveListenerBus + EventLoggingListener),
dataset factories (≈ parallelize/textFile), broadcast, accumulators, and
shutdown. Unlike the reference there is no DAG scheduler: "jobs" are
jit-compiled SPMD steps on the mesh, so the scheduling layer collapses to
step dispatch + the event journal.
"""

from __future__ import annotations

import atexit
import os
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from cycloneml_tpu import mesh as mesh_mod
from cycloneml_tpu.conf import (
    APP_NAME, CHECKPOINT_DIR, CycloneConf, DEFAULT_PARALLELISM,
    EVENT_LOG_DIR, EVENT_LOG_ENABLED, MASTER, METRICS_CSV_DIR,
    METRICS_PERIOD_S, METRICS_SINKS, PROMETHEUS_PORT,
)
from cycloneml_tpu.observe import tracing as _tracing
from cycloneml_tpu.util.events import (
    ApplicationEnd, ApplicationStart, BlocksMigrated, CycloneEvent,
    EventJournal, FitProfileCompleted, JobEnd, JobStart, ListenerBus, MeshUp,
    StepCompleted,
)
from cycloneml_tpu.util.metrics import ConsoleSink, CsvSink, MetricsSystem
from cycloneml_tpu.util.status import AppStatusListener
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

_active_lock = threading.Lock()
_active_context: Optional["CycloneContext"] = None


def active_context() -> Optional["CycloneContext"]:
    """The live context, or None (used by layers — e.g. the SQL engine's
    exchange routing — that cannot thread a ctx handle through)."""
    with _active_lock:
        if _active_context is not None and not _active_context._stopped:
            return _active_context
    return None


class Broadcast:
    """Replicated pytree on every device (replaces TorrentBroadcast,
    ref: core/.../broadcast/TorrentBroadcast.scala:58 — replication is an
    XLA transfer onto the replicated sharding, no torrent protocol needed)."""

    def __init__(self, ctx: "CycloneContext", value: Any, bid: int):
        self.id = bid
        self._value = value
        self._device_value = None
        self._ctx = ctx

    @property
    def value(self) -> Any:
        return self._value

    @property
    def device_value(self) -> Any:
        if self._device_value is None:
            self._device_value = self._ctx.mesh_runtime.device_put_replicated(self._value)
        return self._device_value

    def unpersist(self) -> None:
        self._device_value = None

    def destroy(self) -> None:
        self._device_value = None
        self._value = None


class Accumulator:
    """Driver-merged counter (ref: util/AccumulatorV2.scala:44). In the SPMD
    model task-side partials are device scalars summed into host state after
    each step."""

    def __init__(self, initial: float = 0.0, name: str = ""):
        self.name = name
        self._value = initial
        self._lock = threading.Lock()

    def add(self, v) -> None:
        with self._lock:
            self._value += float(v)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0.0


class CycloneContext:
    def __init__(self, conf: Optional[CycloneConf] = None,
                 master: Optional[str] = None, app_name: Optional[str] = None):
        global _active_context
        with _active_lock:
            if _active_context is not None and not _active_context._stopped:
                raise RuntimeError(
                    "An active CycloneContext already exists in this process; "
                    "use CycloneContext.get_or_create() or stop() it first.")
        self.conf = (conf or CycloneConf()).clone()
        if master is not None:
            self.conf.set(MASTER, master)
        if app_name is not None:
            self.conf.set(APP_NAME, app_name)
        self.app_id = f"cyclone-{int(time.time())}-{uuid.uuid4().hex[:6]}"
        self.app_name = self.conf.get(APP_NAME)

        self.listener_bus = ListenerBus()
        self._journal: Optional[EventJournal] = None
        if self.conf.get(EVENT_LOG_ENABLED):
            d = self.conf.get(EVENT_LOG_DIR)
            os.makedirs(d, exist_ok=True)
            self._journal = EventJournal(os.path.join(d, f"{self.app_id}.jsonl"))
            self.listener_bus.add_listener(self._journal)
        self.listener_bus.start()

        self._status_listener = AppStatusListener()
        self.listener_bus.add_listener(self._status_listener)

        # multihost conf (cyclone.multihost.*) feeds the bootstrap defaults
        # and the hierarchical mesh shape; a mesh built ahead of the
        # context (the worker-script idiom) is adopted as-is
        from cycloneml_tpu.conf import (MULTIHOST_BARRIER_TIMEOUT_MS,
                                        MULTIHOST_CPU_COLLECTIVES,
                                        MULTIHOST_MODEL_PARALLELISM,
                                        MULTIHOST_REPLICAS)
        from cycloneml_tpu.multihost import bootstrap as _bootstrap
        _bootstrap.configure(
            cpu_collectives=self.conf.get(MULTIHOST_CPU_COLLECTIVES),
            barrier_timeout_ms=self.conf.get(MULTIHOST_BARRIER_TIMEOUT_MS))
        mesh_kw: Dict[str, Any] = {}
        if self.conf.get(MULTIHOST_REPLICAS):
            mesh_kw["n_replicas"] = self.conf.get(MULTIHOST_REPLICAS)
        if self.conf.get(MULTIHOST_MODEL_PARALLELISM) > 1:
            mesh_kw["model_parallelism"] = \
                self.conf.get(MULTIHOST_MODEL_PARALLELISM)
        self.mesh_runtime = mesh_mod.get_or_create(self.conf.get(MASTER),
                                                   **mesh_kw)

        # context-owned storage tiers (BlockManager analog): every
        # persisted/cached numeric dataset registers here, so conf budgets
        # bound HBM/RAM held by cold cached blocks (r3 verdict item 6 —
        # the manager was opt-in construction before)
        from cycloneml_tpu.conf import (STORAGE_DEVICE_BUDGET,
                                        STORAGE_HOST_BUDGET)
        from cycloneml_tpu.dataset.storage import StorageManager
        dev_b = self.conf.get(STORAGE_DEVICE_BUDGET)
        host_b = self.conf.get(STORAGE_HOST_BUDGET)
        self.storage = StorageManager(
            device_budget=dev_b or None, host_budget=host_b or None)

        self._next_broadcast = 0
        self._next_job = 0
        self._job_stack: List[int] = []
        # job/rebuild mutual exclusion: run_job brackets count themselves
        # in under this condition, and a mesh rebuild (allocation scale-up)
        # may only begin while the count is zero — closing the window where
        # a job starting between "is a job active?" and rebuild_mesh() had
        # its compiled step torn down mid-flight (advisor r4)
        self._job_cond = threading.Condition()
        self._active_jobs = 0
        self._mesh_rebuild_in_flight = False
        self._job_steps: Dict[int, int] = {}
        self._stopped = False
        self._accumulators: List[Accumulator] = []
        self._heartbeats = None
        self._hb_lock = threading.Lock()
        self._speculators: List[Any] = []  # armed by mesh_supervisor()
        self._autoscalers: List[Any] = []  # built by autoscaler()

        # cross-process liveness: when a driver heartbeat address is
        # configured, this process pings it over TCP (the wire leg of
        # HeartbeatReceiver; ref HeartbeatReceiver.scala:37)
        self._hb_sender = None
        self._hb_server = None
        from cycloneml_tpu.conf import (DRIVER_HEARTBEAT_ADDRESS,
                                        HEARTBEAT_INTERVAL_MS, WORKER_ID)
        hb_addr = self.conf.get(DRIVER_HEARTBEAT_ADDRESS)
        if hb_addr:
            import socket as _socket
            from cycloneml_tpu.parallel.resilience import HeartbeatSender
            wid = self.conf.get(WORKER_ID) or \
                f"{_socket.gethostname()}:{os.getpid()}"
            self._hb_sender = HeartbeatSender(
                wid, hb_addr,
                interval_s=self.conf.get(HEARTBEAT_INTERVAL_MS) / 1000.0)

        self.metrics = MetricsSystem("driver", self.conf.get(METRICS_PERIOD_S))
        for name in [s.strip() for s in self.conf.get(METRICS_SINKS).split(",")
                     if s.strip()]:
            if name == "console":
                self.metrics.register_sink(ConsoleSink())
            elif name == "csv":
                self.metrics.register_sink(CsvSink(self.conf.get(METRICS_CSV_DIR)))
            elif name == "prometheus":
                self.prometheus_port = self.metrics.start_prometheus(
                    self.conf.get(PROMETHEUS_PORT))
            else:
                logger.warning("unknown metrics sink %r", name)
        self.metrics.registry.gauge("mesh.devices",
                                    lambda: self.mesh_runtime.n_devices)
        self.metrics.registry.gauge(
            "listenerBus.queued", lambda: self.listener_bus.metrics["queued"])
        # live device-memory telemetry (HBM gauges where the backend
        # reports memory_stats; always a 1/0 availability gauge — CPU has
        # none, see docs/observability.md backend matrix)
        from cycloneml_tpu.observe import costs as _costs
        _costs.register_memory_gauges(self.metrics.registry)
        self.metrics.start()

        # step-level tracing (observe/): conf or CYCLONE_TRACE env var; the
        # context only disables a tracer it installed itself, so a tracer
        # enabled programmatically (tests, bench) survives ctx teardown.
        # A configured trace-collector address (the deploy launch env's
        # conf seed) also demands full tracing — the submitting process
        # asked for a distributed trace of this app.
        from cycloneml_tpu.conf import (
            COLLECT_ADDRESS, COLLECT_INTERVAL_MS, COLLECT_MAX_BATCH,
            FLIGHT_ENABLED, FLIGHT_MIN_INTERVAL_MS, FLIGHT_RING_SPANS,
            SKEW_ENABLED, TRACE_ENABLED, TRACE_MAX_SPANS,
        )
        self._trace_owner = False
        collect_addr = self.conf.get(COLLECT_ADDRESS)
        want_trace = self.conf.get(TRACE_ENABLED) or bool(collect_addr) or \
            os.environ.get("CYCLONE_TRACE", "").lower() not in \
            ("", "0", "false", "no")
        if want_trace and _tracing.full_active() is None:
            # enable() also UPGRADES an installed flight ring to full
            _tracing.enable(max_spans=self.conf.get(TRACE_MAX_SPANS),
                            registry=self.metrics.registry)
            self._trace_owner = True

        # always-on flight recorder: a bounded span ring when full tracing
        # is off, dumped to cyclone.trace.dir on triggers (fault firing,
        # mesh rebuild, serving shed, SLO breach) — observe/flight.py
        from cycloneml_tpu.observe import flight as _flight
        self._flight_owner = False
        if self.conf.get(FLIGHT_ENABLED) and _tracing.active() is None:
            _flight.enable(ring_spans=self.conf.get(FLIGHT_RING_SPANS))
            self._flight_owner = True
        from cycloneml_tpu.conf import DOCTOR_FLIGHT_DIAGNOSIS as _DOCTOR_FD
        from cycloneml_tpu.conf import TRACE_DIR as _TRACE_DIR
        _flight.configure(
            dump_dir=self.conf.get(_TRACE_DIR) or None,
            min_interval_s=self.conf.get(FLIGHT_MIN_INTERVAL_MS) / 1e3,
            diagnose=self.conf.get(_DOCTOR_FD))

        # distributed-trace adoption + span shipping (observe/collect.py):
        # a deploy-launched app joins the submitting process's trace
        tracer = _tracing.active()
        trace_env_id = os.environ.get("CYCLONE_TRACE_ID", "")
        if tracer is not None and trace_env_id:
            tracer.set_trace_context(
                trace_env_id, os.environ.get("CYCLONE_TRACE_PARENT", ""))
        self._shipper = None
        if collect_addr and tracer is not None:
            from cycloneml_tpu.conf import WORKER_ID as _WORKER_ID
            from cycloneml_tpu.observe.collect import SpanShipper
            label = self.conf.get(_WORKER_ID)
            if not label:
                proc_id = os.environ.get("CYCLONE_PROC_ID", "")
                label = f"proc{proc_id}" if proc_id else \
                    f"{__import__('socket').gethostname()}:{os.getpid()}"
            batch = self.conf.get(COLLECT_MAX_BATCH)
            self._shipper = SpanShipper(
                collect_addr, host_label=label,
                interval_s=self.conf.get(COLLECT_INTERVAL_MS) / 1e3,
                max_batch=batch,
                # the conf contract: a collector outage buffers 16x a
                # batch before drop-counting oldest
                max_buffer=16 * batch)

        # online skew/straggler detection (observe/skew.py): installed
        # process-globally so the oocore/serving/heartbeat lanes feed it
        # with one global read per sample
        from cycloneml_tpu.observe import skew as _skew
        self._skew_owner = False
        if self.conf.get(SKEW_ENABLED) and _skew.active() is None:
            _skew.install(_skew.SkewDetector.from_conf(
                self.conf, bus=self.listener_bus,
                registry=self.metrics.registry))
            self._skew_owner = True
        self.skew_detector = _skew.active()

        # usage attribution (observe/attribution.py): the per-job /
        # per-tenant metering ledger + its periodic UsageReport feed. The
        # context only disables a ledger it installed itself (tests and
        # bench enable programmatically). The reporter also carries the
        # telemetry drop-counter rollup so the status store / REST / web
        # UI see span loss without a scrape.
        from cycloneml_tpu.conf import (USAGE_ENABLED,
                                        USAGE_REPORT_INTERVAL_MS)
        from cycloneml_tpu.observe import attribution as _attribution
        self._usage_owner = False
        if self.conf.get(USAGE_ENABLED) and _attribution.active() is None:
            _attribution.enable(self.conf, registry=self.metrics.registry)
            self._usage_owner = True
        self._usage_reporter = None
        if _attribution.active() is not None:
            from cycloneml_tpu.conf import WORKER_ID as _WID
            host = self.conf.get(_WID)
            if not host:
                proc_id = os.environ.get("CYCLONE_PROC_ID", "")
                host = f"proc{proc_id}" if proc_id else ""
            self._usage_reporter = _attribution.UsageReporter(
                self.listener_bus,
                interval_s=self.conf.get(USAGE_REPORT_INTERVAL_MS) / 1e3,
                host=host, telemetry_fn=self._telemetry_stats)
            self._usage_reporter.start()

        from cycloneml_tpu.conf import PLUGINS
        from cycloneml_tpu.plugin import load_plugins
        self._plugins = load_plugins(
            self, self.conf.get(PLUGINS).split(","))

        self.listener_bus.post(ApplicationStart(app_name=self.app_name, app_id=self.app_id))
        self.listener_bus.post(MeshUp(
            n_devices=self.mesh_runtime.n_devices,
            platform=self.mesh_runtime.platform,
            mesh_shape=str(dict(zip(self.mesh_runtime.mesh.axis_names,
                                    self.mesh_runtime.mesh.devices.shape)))))
        with _active_lock:
            _active_context = self
        atexit.register(self.stop)

    # -- factories -------------------------------------------------------------
    @classmethod
    def get_or_create(cls, conf: Optional[CycloneConf] = None, **kw) -> "CycloneContext":
        with _active_lock:
            if _active_context is not None and not _active_context._stopped:
                return _active_context
        return cls(conf, **kw)

    @property
    def default_parallelism(self) -> int:
        n = self.conf.get(DEFAULT_PARALLELISM)
        return n if n > 0 else self.mesh_runtime.n_devices

    def broadcast(self, value: Any) -> Broadcast:
        self._next_broadcast += 1
        return Broadcast(self, value, self._next_broadcast)

    def accumulator(self, initial: float = 0.0, name: str = "") -> Accumulator:
        acc = Accumulator(initial, name)
        self._accumulators.append(acc)
        return acc

    def parallelize(self, data, num_partitions: Optional[int] = None):
        from cycloneml_tpu.dataset.dataset import PartitionedDataset
        return PartitionedDataset.from_sequence(
            self, list(data), num_partitions or self.default_parallelism)

    def read_libsvm(self, path: str, n_features: Optional[int] = None):
        from cycloneml_tpu.dataset.io import read_libsvm
        return read_libsvm(self, path, n_features)

    # -- job bracketing (events only; execution is jit dispatch) --------------
    def run_job(self, description: str, fn: Callable[[], Any]) -> Any:
        with self._job_cond:
            while self._mesh_rebuild_in_flight:
                self._job_cond.wait()
            self._active_jobs += 1
        self._next_job += 1
        jid = self._next_job
        # traced jobs open a root 'job' span; every span the fit opens in
        # this thread nests under it, and the rollup posts as a FitProfile
        tracer = _tracing.active()
        job_span = tracer.span("job", description) if tracer is not None \
            else None
        sid = ""
        mark = 0
        if job_span is not None:
            mark = tracer.mark()  # rollup scans only this job's spans
            job_span.__enter__()
            sid = job_span.span_id
        # usage attribution bracket: an un-scoped job gets an automatic
        # "job-{id}" scope (a caller's explicit attribution.scope wins),
        # and the scope row's delta across the fit lands on the profile
        from cycloneml_tpu.observe import attribution as _attribution
        led = _attribution.active()
        job_scope = None
        usage_key = ""
        usage_before = None
        if led is not None:
            sc = _attribution.current_scope()
            if sc is None:
                job_scope = _attribution.scope(f"job-{jid}")
                sc = job_scope.__enter__()
            usage_key = sc.key
            usage_before = led.row(usage_key)
        self.listener_bus.post(JobStart(job_id=jid, description=description,
                                        span_id=sid))
        self._job_stack.append(jid)
        self.metrics.registry.counter("jobs.started").inc()
        try:
            with self.metrics.registry.timer("job.duration"):
                out = fn()
        except Exception as e:
            self.listener_bus.post(JobEnd(job_id=jid, succeeded=False, error=str(e)))
            self.metrics.registry.counter("jobs.failed").inc()
            raise
        finally:
            self._job_stack.pop()
            with self._job_cond:
                self._active_jobs -= 1
                self._job_cond.notify_all()
            if job_scope is not None:
                job_scope.__exit__(None, None, None)
            if job_span is not None:
                job_span.__exit__(None, None, None)
            if job_span is not None and tracer.full:
                # profile rollups are a FULL-tracing feature: the flight
                # ring records the job span for post-hoc dumps but must
                # not pay a per-job scan/event (the always-on contract)
                try:
                    prof = tracer.profile_for(sid, since=mark)
                    prof.job_id = jid
                    prof.description = description
                    if usage_before is not None:
                        prof.job_usage = _attribution.usage_delta(
                            usage_before, led.row(usage_key))
                    self.listener_bus.post(FitProfileCompleted(
                        job_id=jid, profile=prof.to_dict()))
                except Exception:
                    logger.exception("fit profile rollup failed")
        self.listener_bus.post(JobEnd(job_id=jid, succeeded=True))
        self.metrics.registry.counter("jobs.succeeded").inc()
        return out

    def try_begin_mesh_rebuild(self) -> bool:
        """Atomically claim the mesh for a rebuild IFF no ``run_job``
        bracket is active. While claimed, new jobs block at entry until
        :meth:`end_mesh_rebuild` — so a fit starting concurrently with an
        allocation scale-up either runs entirely before the rebuild or
        entirely on the rebuilt mesh, never across it."""
        with self._job_cond:
            if self._active_jobs or self._mesh_rebuild_in_flight:
                return False
            self._mesh_rebuild_in_flight = True
            return True

    def end_mesh_rebuild(self) -> None:
        with self._job_cond:
            self._mesh_rebuild_in_flight = False
            self._job_cond.notify_all()

    @property
    def current_job_id(self) -> int:
        return self._job_stack[-1] if self._job_stack else 0

    def record_step(self, step_metrics: Dict[str, float]) -> None:
        """Post per-step metrics (≈ TaskMetrics travelling with each task;
        here one jitted step = one 'stage' of work)."""
        jid = self.current_job_id
        step = self._job_steps.get(jid, 0)
        self._job_steps[jid] = step + 1
        self.listener_bus.post(StepCompleted(
            job_id=jid, step=step, metrics=dict(step_metrics),
            span_id=_tracing.current_span_id()))
        reg = self.metrics.registry
        reg.counter("steps.completed").inc()
        for k, v in step_metrics.items():
            try:
                reg.histogram(f"step.{k}").update(float(v))
            except (TypeError, ValueError):
                pass

    def _telemetry_stats(self) -> Dict[str, Any]:
        """Drop-counter rollup across this process's telemetry stack —
        tracer ring overflow, span-shipper delivery loss, bus queue depth
        — the ``TelemetryStatsUpdated`` payload the usage reporter posts.
        A lossy pipeline must say so where the usage numbers are read."""
        stats: Dict[str, Any] = {
            "busQueued": int(self.listener_bus.metrics["queued"])}
        tracer = _tracing.active()
        if tracer is not None:
            stats["spansDropped"] = int(tracer.spans_dropped)
        shipper = getattr(self, "_shipper", None)
        if shipper is not None:
            stats["shipper"] = shipper.delivery_stats()
        return stats

    @property
    def status_store(self):
        """Live application status (≈ AppStatusStore:35, REST api/v1)."""
        return self._status_listener.store

    @property
    def heartbeat_receiver(self):
        """Host-worker liveness registry (≈ HeartbeatReceiver endpoint).
        Created lazily — single-host runs have no worker fleet to track."""
        with self._hb_lock:  # double-start would orphan a sweep thread
            if self._stopped:
                raise RuntimeError("context is stopped")
            if self._heartbeats is None:
                from cycloneml_tpu.conf import NETWORK_TIMEOUT_MS
                from cycloneml_tpu.parallel.resilience import HeartbeatReceiver
                self._heartbeats = HeartbeatReceiver(
                    timeout_s=self.conf.get(NETWORK_TIMEOUT_MS) / 1000.0,
                    listener_bus=self.listener_bus)
                self._heartbeats.start()
            return self._heartbeats

    def mesh_supervisor(self, **kw):
        """Degraded-mesh recovery + elastic-scheduling supervisor wired to
        this context: worker loss (heartbeat expiry or a step's
        DeviceLostError) → program-cache clear + mesh rebuild over the
        survivors + re-shard + resume-from-checkpoint; capacity events
        (the process-global elastic channel) → in-place reshape; latched
        straggler verdicts → speculative re-dispatch when
        ``cyclone.elastic.speculation`` is set. Pass the result as
        ``train_with_checkpoints(..., supervisor=...)``; see
        docs/resilience.md for the failure and elasticity models."""
        from cycloneml_tpu.conf import (ELASTIC_DRAIN_WINDOW_MS,
                                        ELASTIC_MAX_RESHAPES,
                                        ELASTIC_SPECULATION)
        from cycloneml_tpu.elastic import capacity as _capacity
        from cycloneml_tpu.elastic import speculation as _speculation
        from cycloneml_tpu.parallel.resilience import MeshSupervisor
        kw.setdefault("max_reshapes", self.conf.get(ELASTIC_MAX_RESHAPES))
        kw.setdefault("drain_window_s",
                      self.conf.get(ELASTIC_DRAIN_WINDOW_MS) / 1e3)
        # scale announcements (API / SIGTERM / elastic.capacity chaos
        # point) reach the training loop through the process-global
        # channel unless the caller wired its own
        kw.setdefault("capacity", _capacity.channel())
        sup = MeshSupervisor(self, **kw)
        sup.attach(self.heartbeat_receiver)
        if self.skew_detector is not None:
            # straggler verdicts land in sup.stragglers() — the elastic
            # re-dispatch's mitigation input (ROADMAP item 4)
            sup.attach_skew(self.skew_detector)
        if self.conf.get(ELASTIC_SPECULATION) \
                and _speculation.active() is None:
            sp = _speculation.Speculator(sup.stragglers)
            _speculation.install(sp)
            self._speculators.append(sp)  # disarmed + closed on stop
        from cycloneml_tpu.conf import AUTOSCALE_ENABLED
        if self.conf.get(AUTOSCALE_ENABLED) and not self._autoscalers:
            # close the elastic loop: sensors (skew/SLO/occupancy) →
            # policy → this supervisor's capacity channel. Opt-in, one
            # per context; stopped (latched) before supervisors on stop()
            self.autoscaler().start()
        return sup

    def autoscaler(self, **kw):
        """Build the SLO control loop (elastic/autoscale.py) wired to
        this context's signal plane: serving p99 from the metrics
        registry, straggler pressure + step-SLO latches from the skew
        detector, occupancy from the memory gauges — announcing on the
        process-global capacity channel. Returned unstarted (call
        ``.start()`` for the daemon loop, or drive ``tick()`` yourself);
        stopped with the context. ``cyclone.autoscale.enabled`` makes
        ``mesh_supervisor()`` arm one automatically."""
        from cycloneml_tpu.conf import AUTOSCALE_ACQUIRE_TIMEOUT_MS
        from cycloneml_tpu.elastic import autoscale as _autoscale
        from cycloneml_tpu.elastic import capacity as _capacity
        from cycloneml_tpu.elastic.policy import AutoscalePolicy
        policy = kw.pop("policy", None)
        if policy is None:
            policy = AutoscalePolicy.from_conf(self.conf)
        kw.setdefault("channel", _capacity.channel())
        kw.setdefault("detector", self.skew_detector)
        kw.setdefault("registry", self.metrics.registry)
        kw.setdefault("bus", self.listener_bus)
        kw.setdefault("used_fn", lambda: self.mesh_runtime.n_devices)
        kw.setdefault("acquire_timeout_s",
                      self.conf.get(AUTOSCALE_ACQUIRE_TIMEOUT_MS) / 1e3)
        kw.setdefault("occupancy_fn",
                      lambda: _autoscale.occupancy_fraction(self.conf))
        auto = _autoscale.Autoscaler(policy, **kw)
        self._autoscalers.append(auto)
        return auto

    def start_ui(self, host: str = "127.0.0.1", port: int = 0):
        """Serve the live status web UI (≈ SparkUI.scala:40 — jobs/steps/
        failures over the status store). Returns the server; ``.url`` is the
        address. Stopped automatically with the context."""
        from cycloneml_tpu.observe import attribution as _attribution
        from cycloneml_tpu.util.webui import StatusWebUI

        def _live_usage():
            # live ledger beats the store's last periodic UsageReport;
            # with attribution off the store (possibly replayed) serves
            led = _attribution.active()
            return led.snapshot() if led is not None \
                else self.status_store.usage_rollup()

        if getattr(self, "_web_ui", None) is None:
            self._web_ui = StatusWebUI(
                self.status_store, host, port,
                storage_usage=self.storage.usage,
                usage=_live_usage, telemetry=self._telemetry_stats)
        return self._web_ui

    def start_heartbeat_server(self, host: str = "127.0.0.1", port: int = 0):
        """Start the driver-side TCP heartbeat endpoint (≈ the
        HeartbeatReceiver RPC endpoint registration). Point each worker's
        ``cyclone.driver.heartbeatAddress`` at the returned server's
        ``.address``; expiry lands on the listener bus as WorkerLost."""
        from cycloneml_tpu.parallel.resilience import HeartbeatServer
        receiver = self.heartbeat_receiver  # raises if stopped; outside the
        # lock below because it takes _hb_lock itself
        with self._hb_lock:  # no double-start, no post-stop leak
            if self._stopped:
                raise RuntimeError("context is stopped")
            if self._hb_server is None:
                self._hb_server = HeartbeatServer(receiver, host, port)
            elif (host, port) not in ((self._hb_server.host,
                                       self._hb_server.port),
                                      ("127.0.0.1", 0)):
                raise ValueError(
                    f"heartbeat server already bound to "
                    f"{self._hb_server.address}; cannot rebind to "
                    f"{host}:{port}")
        return self._hb_server

    def with_resources(self, profile) -> "CycloneContext":
        """Stage-level scheduling decision (ref: RDD.withResources,
        rdd/RDD.scala:1806): ensure the mesh matches the profile's slice
        topology, rebuilding it when it does not. Raises if the attached
        hardware cannot satisfy the request."""
        if profile.satisfied_by(self.mesh_runtime):
            return self
        # validate feasibility BEFORE the destructive rebuild — a failed
        # request must not leave the caller without its previous mesh/data
        master = self.conf.get(MASTER)
        n = mesh_mod.probe_device_count(master)
        if n is not None:
            if profile.min_devices and n < profile.min_devices:
                raise RuntimeError(
                    f"resource profile needs {profile.min_devices} devices; "
                    f"master {master!r} provides {n}")
            split = profile.replicas * profile.model_parallelism
            if n % split != 0:
                raise RuntimeError(
                    f"{n} devices not divisible by replicas×model = {split}")
        self.rebuild_mesh(**profile.mesh_kwargs())
        if not profile.satisfied_by(self.mesh_runtime):
            raise RuntimeError(
                f"mesh for master {master!r} "
                f"({self.mesh_runtime.n_devices} devices) cannot satisfy "
                f"profile {profile}")
        return self

    def decommission(self, master: Optional[str] = None, **mesh_kwargs):
        """Planned scale-down with cached-block MIGRATION (ref:
        storage/BlockManagerDecommissioner.scala:40 — a draining executor
        pushes its cached RDD blocks to surviving peers before exiting).

        On a device mesh the draining unit is the device set, so while the
        OLD mesh is still alive every device-tier managed dataset is
        pulled to the host tier (the migration hop; on multihost JAX the
        re-place below is a resharding device transfer), the mesh is
        rebuilt onto the surviving devices, and the datasets are re-placed
        there eagerly — bit-identical data, no recompute from source, no
        checkpoint read. UNPLANNED loss still takes :meth:`rebuild_mesh`'s
        checkpoint-based contract: after a crash there is no live mesh to
        migrate from, which is exactly the reference's split between
        decommissioning and failure recovery."""
        if not self.try_begin_mesh_rebuild():
            raise RuntimeError(
                "cannot decommission while jobs are active; retry when "
                "run_job brackets have drained")
        try:
            # raises BEFORE any teardown if a dataset cannot leave the
            # device tier — the old mesh stays intact on failure
            migrated, moved_bytes = self.storage.migrate_device_to_host()
            rt = self._rebuild_mesh_locked(master, **mesh_kwargs)
            for ds in migrated:
                ds.x  # eager re-place on the surviving devices
            self.listener_bus.post(BlocksMigrated(
                n_datasets=len(migrated), bytes=moved_bytes,
                n_devices=rt.n_devices))
            logger.info("decommission: migrated %d cached datasets "
                        "(%d bytes) onto %d devices",
                        len(migrated), moved_bytes, rt.n_devices)
            return rt
        finally:
            self.end_mesh_rebuild()

    def rebuild_mesh(self, master: Optional[str] = None, **mesh_kwargs):
        """Elastic recovery (SURVEY §5.3): tear down the mesh and bring up a
        new one — possibly smaller, possibly a spare slice — after device or
        host loss. Device-resident data dies with the old mesh; callers
        restore datasets from host copies or checkpoints and resume from the
        last optimizer-state checkpoint (lineage recomputation does not
        translate to TPU; checkpoint-based recovery does). For a PLANNED
        scale-down prefer :meth:`decommission`, which migrates cached
        blocks instead."""
        return self._rebuild_mesh_locked(master, **mesh_kwargs)

    def _rebuild_mesh_locked(self, master: Optional[str] = None,
                             **mesh_kwargs):
        mesh_mod.reset()
        self.mesh_runtime = mesh_mod.get_or_create(
            master or self.conf.get(MASTER), **mesh_kwargs)
        self.listener_bus.post(MeshUp(
            n_devices=self.mesh_runtime.n_devices,
            platform=self.mesh_runtime.platform,
            mesh_shape=str(dict(zip(self.mesh_runtime.mesh.axis_names,
                                    self.mesh_runtime.mesh.devices.shape)))))
        logger.info("mesh rebuilt: %d devices", self.mesh_runtime.n_devices)
        return self.mesh_runtime

    def profile(self, log_dir: str):
        """Capture a device trace for a code region (≈ §5.1: per-step
        XPlane traces replace the reference's per-task metrics UI):
        ``with ctx.profile('/tmp/trace'): step()`` then inspect with
        TensorBoard/xprof."""
        import jax
        return jax.profiler.trace(log_dir)

    def export_trace(self, path: str) -> str:
        """Write the step-level Chrome trace (observe/) collected so far to
        ``path``; requires tracing to be enabled (cyclone.trace.enabled /
        CYCLONE_TRACE). Load the file in Perfetto or chrome://tracing."""
        tracer = _tracing.active()
        if tracer is None:
            raise RuntimeError(
                "tracing is not enabled; set cyclone.trace.enabled=true "
                "(or CYCLONE_TRACE=1) before creating the context")
        return tracer.export_chrome_trace(path)

    def fit_profile(self, job_id: Optional[int] = None):
        """FitProfile dict for ``job_id`` (default: the most recent job
        that has one), or {} when tracing was off."""
        store = self.status_store
        if job_id is not None:
            return store.profile(job_id)
        return store.latest_profile()

    def diagnose(self, spans=None):
        """Run the performance doctor (observe/diagnose.py) over the
        live telemetry plane: the active tracer's spans (or ``spans``),
        the installed SkewDetector's lane snapshot, the latest serving
        rollup and the shard-set cache stats. Posts a
        ``DiagnosisCompleted`` event so ``/api/v1/diagnosis``, the web
        UI and journal replay all see the report; returns it."""
        from cycloneml_tpu.observe.diagnose import diagnose as _diagnose
        from cycloneml_tpu.util.events import DiagnosisCompleted
        if spans is None:
            tracer = _tracing.active()
            spans = tracer.snapshot() if tracer is not None else []
        report = _diagnose(
            spans=spans, conf=self.conf,
            serving_stats=self.status_store.serving_stats() or None,
            source="live")
        self.listener_bus.post(DiagnosisCompleted(
            source=report.source, n_findings=len(report.findings),
            report=report.to_dict()))
        return report

    @property
    def checkpoint_dir(self) -> str:
        return self.conf.get(CHECKPOINT_DIR)

    def set_checkpoint_dir(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)
        self.conf.set(CHECKPOINT_DIR, path)

    def stop(self) -> None:
        global _active_context
        # stopped-flag flip AND heartbeat-machinery capture in ONE lock
        # acquisition, pairing with the lazy creators: two concurrent
        # stop() calls race the unguarded check-then-act (double
        # ApplicationEnd, double plugin shutdown), and a creator between
        # the flag flip and the old unguarded `self._hb_server` read
        # leaves an orphaned server thread. The (blocking) .stop() joins
        # run AFTER release — holding `_hb_lock` across a thread join
        # convoys every heartbeat_receiver caller.
        with self._hb_lock:
            if self._stopped:
                return
            self._stopped = True
            heartbeats, self._heartbeats = self._heartbeats, None
            hb_sender, self._hb_sender = self._hb_sender, None
            hb_server, self._hb_server = self._hb_server, None
        self.listener_bus.post(ApplicationEnd(app_id=self.app_id))
        for p in getattr(self, "_plugins", []):
            try:
                p.shutdown()
            except Exception:
                logger.exception("plugin shutdown failed")
        if heartbeats is not None:
            heartbeats.stop()
        if hb_sender is not None:
            hb_sender.stop()
        if hb_server is not None:
            hb_server.stop()
        if getattr(self, "_web_ui", None) is not None:
            self._web_ui.stop()
        if getattr(self, "storage", None) is not None:
            self.storage.close()  # spill files + dir, never leaked to /tmp
        try:
            # release the exchange listener THIS context's conf introduced
            # (servers are shared across rounds, not across contexts with
            # different addresses — advisor r4)
            from cycloneml_tpu.conf import EXCHANGE_ADDRESSES, EXCHANGE_RANK
            addrs_s = self.conf.get(EXCHANGE_ADDRESSES)
            if addrs_s:
                addrs = [a.strip() for a in addrs_s.split(",") if a.strip()]
                rank = self.conf.get(EXCHANGE_RANK)
                if 0 <= rank < len(addrs):
                    from cycloneml_tpu.parallel.exchange import \
                        _ExchangeServer
                    _ExchangeServer.close_address(addrs[rank])
        except Exception:
            logger.exception("exchange server shutdown failed")
        if getattr(self, "_shipper", None) is not None:
            # final flush BEFORE any tracer teardown: the collector must
            # see every span this app recorded, including ApplicationEnd's
            self._shipper.stop(flush=True)
        if getattr(self, "_usage_reporter", None) is not None:
            # final UsageReport flush while the tracer/shipper still
            # exist: the journal carries the complete ledger for replay
            # and the last TelemetryStatsUpdated still sees span loss
            try:
                self._usage_reporter.stop()
            except Exception:
                logger.exception("usage reporter shutdown failed")
            self._usage_reporter = None
        self._shipper = None
        if getattr(self.mesh_runtime, "is_multihost", False):
            # barriered multihost teardown: sync every process before
            # disconnecting so no peer exits while another is
            # mid-collective; a dead peer bounds the wait at
            # cyclone.multihost.barrierTimeoutMs
            try:
                from cycloneml_tpu.multihost import bootstrap as _bootstrap
                _bootstrap.shutdown(barrier_first=True)
            except Exception:
                logger.exception("multihost teardown failed")
        for a in getattr(self, "_autoscalers", []):
            # stop the control plane BEFORE the supervisors it feeds:
            # the latch guarantees no decision lands on a stopping mesh
            try:
                a.stop()
            except Exception:
                logger.exception("autoscaler shutdown failed")
        self._autoscalers = []
        for sp in getattr(self, "_speculators", []):
            # disarm BEFORE closing: a staging thread mid-race keeps its
            # already-submitted backup; new sites fall back to plain work
            from cycloneml_tpu.elastic import speculation as _speculation
            _speculation.uninstall(sp)
            try:
                sp.close()
            except Exception:
                logger.exception("speculator shutdown failed")
        self._speculators = []
        if getattr(self, "_skew_owner", False):
            from cycloneml_tpu.observe import skew as _skew
            _skew.uninstall()
        if getattr(self, "_flight_owner", False):
            from cycloneml_tpu.observe import flight as _flight
            _flight.disable()
        if getattr(self, "_trace_owner", False):
            # full_active: the full tracer this context installed (never a
            # flight ring someone else slipped in after a disable)
            tracer = _tracing.full_active()
            if tracer is not None:
                from cycloneml_tpu.conf import TRACE_DIR
                d = self.conf.get(TRACE_DIR)
                if d:
                    try:
                        os.makedirs(d, exist_ok=True)
                        path = os.path.join(d, f"{self.app_id}.trace.json")
                        tracer.export_chrome_trace(path)
                        logger.info("trace exported to %s", path)
                    except Exception:
                        logger.exception("trace export failed")
                _tracing.disable()
        if getattr(self, "_usage_owner", False):
            from cycloneml_tpu.observe import attribution as _attribution
            _attribution.disable()
        self.metrics.stop()
        self.listener_bus.stop()
        if self._journal is not None:
            self._journal.close()
        with _active_lock:
            if _active_context is self:
                _active_context = None

    def __enter__(self) -> "CycloneContext":
        return self

    def __exit__(self, *exc) -> None:
        self.stop()
