"""Micro-batch stream execution engine.

Analog of StreamExecution / MicroBatchExecution (ref: sql/core/.../execution/
streaming/StreamExecution.scala:69, MicroBatchExecution.scala:39). Each
micro-batch: resolve new source offsets → write the offset log → execute the
incrementalized plan → commit state + sink → write the commit log. Restart
recovery replays the last uncommitted batch at the logged offsets against the
last committed state version — exactly-once given replayable sources and
idempotent sinks (the same contract the reference documents).
"""

from __future__ import annotations

import os
import tempfile
import threading
import time
import uuid
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from cycloneml_tpu.sql.plan import Aggregate, Batch, Join, LogicalPlan, Scan
from cycloneml_tpu.streaming.metadata_log import MetadataLog
from cycloneml_tpu.streaming.sinks import (ConsoleSink, FileSink,
                                           ForeachBatchSink, MemorySink, Sink)
from cycloneml_tpu.streaming.sources import (FileStreamSource, RateSource,
                                             Source, StreamingScan)
from cycloneml_tpu.streaming.state import StateStoreProvider
from cycloneml_tpu.streaming.stateful import (Deduplicate, StatefulAggregation,
                                              StatefulDedup, StatefulJoin,
                                              Watermark)
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


# -- plan utilities ------------------------------------------------------------

def find_nodes(plan: LogicalPlan, pred: Callable[[LogicalPlan], bool]
               ) -> List[LogicalPlan]:
    out = []
    if pred(plan):
        out.append(plan)
    for c in plan.children:
        out.extend(find_nodes(c, pred))
    return out


def replace_node(plan: LogicalPlan, target: LogicalPlan,
                 replacement: LogicalPlan) -> LogicalPlan:
    """Rebuild the tree with ``target`` (by identity) swapped out."""
    if plan is target:
        return replacement
    new_children = [replace_node(c, target, replacement) for c in plan.children]
    if all(n is o for n, o in zip(new_children, plan.children)):
        return plan
    return plan.with_children(new_children)


def is_streaming_plan(plan: LogicalPlan) -> bool:
    return bool(find_nodes(plan, lambda n: isinstance(n, StreamingScan)))


class MicroBatchExecution:
    """Drives one streaming query's batch loop."""

    def __init__(self, plan: LogicalPlan, sink: Sink, mode: str,
                 checkpoint_dir: str, session=None):
        self.plan = plan
        self.sink = sink
        self.mode = mode
        self.session = session
        self.checkpoint_dir = checkpoint_dir
        self.offset_log = MetadataLog(os.path.join(checkpoint_dir, "offsets"))
        self.commit_log = MetadataLog(os.path.join(checkpoint_dir, "commits"))

        self.scans: List[StreamingScan] = find_nodes(
            plan, lambda n: isinstance(n, StreamingScan))
        if not self.scans:
            raise ValueError("plan has no streaming source")
        names = set()
        for i, s in enumerate(self.scans):
            if s.name in names:
                s.name = f"{s.name}#{i}"
            names.add(s.name)
        self.watermarks: List[Watermark] = find_nodes(
            plan, lambda n: isinstance(n, Watermark))
        self._wm_col = self.watermarks[0].event_col if self.watermarks else None

        # locate the (single) stateful operator, topmost first; operators on
        # purely static subtrees execute batch-style and carry no state
        self.stateful_node: Optional[LogicalPlan] = None
        self.stateful_op: Optional[Any] = None
        aggs = [a for a in find_nodes(plan, lambda n: isinstance(n, Aggregate))
                if is_streaming_plan(a)]
        dedups = [d for d in find_nodes(plan,
                                        lambda n: isinstance(n, Deduplicate))
                  if is_streaming_plan(d)]
        joins = [j for j in find_nodes(plan, lambda n: isinstance(n, Join))
                 if is_streaming_plan(j.children[0])
                 and is_streaming_plan(j.children[1])]
        if len(aggs) + len(dedups) + len(joins) > 1:
            raise ValueError("streaming supports one stateful operator per "
                             "query (ref: UnsupportedOperationChecker)")
        state_path = os.path.join(checkpoint_dir, "state")
        if aggs:
            self.stateful_node = aggs[0]
            self.stateful_op = StatefulAggregation(aggs[0], mode, self._wm_col)
        elif dedups:
            self.stateful_node = dedups[0]
            self.stateful_op = StatefulDedup(dedups[0], self._wm_col)
        elif joins:
            wm_cols = {w.event_col: w.delay for w in self.watermarks}
            self.stateful_node = joins[0]
            self.stateful_op = StatefulJoin(joins[0], wm_cols)
        if mode == "complete" and not isinstance(self.stateful_op,
                                                 StatefulAggregation):
            # dedup/join emit per-batch increments; complete-mode sinks
            # replace their contents, silently losing earlier rows
            raise ValueError("complete mode requires a streaming aggregation "
                             "(ref: UnsupportedOperationChecker)")
        self.state_provider = (StateStoreProvider(state_path)
                               if self.stateful_op is not None else None)
        self._batch_lock = threading.Lock()
        for s in self.scans:
            if hasattr(s.source, "set_log_dir"):
                s.source.set_log_dir(
                    os.path.join(checkpoint_dir, "sources", s.name))

        # recovery (ref: StreamExecution.populateStartOffsets)
        self.watermark: Optional[float] = None
        self._committed_offsets: Dict[str, int] = {s.name: 0 for s in self.scans}
        self._pending: Optional[Dict[str, Any]] = None
        self.batch_id = 0
        latest = self.offset_log.latest()
        if latest is not None:
            bid, entry = latest
            if self.commit_log.get(bid) is not None:
                self.batch_id = bid + 1
                self._committed_offsets = dict(entry["offsets"])
                self.watermark = entry.get("watermark")
            else:
                self.batch_id = bid
                self._pending = entry
                prev = self.offset_log.get(bid - 1)
                if prev is not None:
                    self._committed_offsets = dict(prev["offsets"])
                    self.watermark = prev.get("watermark")
        self._wm_dirty = self.watermark is not None

    # -- one batch -------------------------------------------------------------
    def construct_next_batch(self) -> bool:
        """Returns True if a batch was run. Serialized: the processing-time
        trigger thread and user calls (process_all_available) may overlap."""
        with self._batch_lock:
            return self._construct_next_batch_locked()

    def _construct_next_batch_locked(self) -> bool:
        if self._pending is not None:
            entry = self._pending
            self._pending = None
            self._run_batch(entry["offsets"], entry.get("watermark"))
            return True
        ends = {s.name: s.source.latest_offset() for s in self.scans}
        has_data = any(ends[n] > self._committed_offsets.get(n, 0)
                       for n in ends)
        if not has_data and not self._wm_dirty:
            return False
        self._wm_dirty = False
        entry = {"offsets": ends, "watermark": self.watermark}
        self.offset_log.add(self.batch_id, entry)
        self._run_batch(ends, self.watermark)
        return True

    def _run_batch(self, ends: Dict[str, int], watermark: Optional[float]) -> None:
        t0 = time.perf_counter()
        n_in = 0
        for s in self.scans:
            start = self._committed_offsets.get(s.name, 0)
            s.current = s.source.get_batch(start, ends[s.name])
            n_in += len(next(iter(s.current.values()))) if s.current else 0

        out = self._execute(watermark)

        self.sink.add_batch(self.batch_id, out, self.mode)
        self.commit_log.add(self.batch_id, {"watermark": watermark})
        for s in self.scans:
            s.source.commit(ends[s.name])
            s.current = None
        self._committed_offsets = dict(ends)
        self.batch_id += 1
        self._advance_watermark()
        if self.batch_id % 20 == 0:
            # bound checkpoint growth (≈ minBatchesToRetain compaction)
            self.offset_log.purge(keep_last=100)
            self.commit_log.purge(keep_last=100)
            if self.state_provider is not None:
                self.state_provider.purge(max(1, self.batch_id - 100))
        self.last_progress = {
            "batchId": self.batch_id - 1,
            "numInputRows": int(n_in),
            "durationMs": int((time.perf_counter() - t0) * 1000),
            "watermark": self.watermark,
            "stateRows": (len(self._last_store) if self._last_store is not None
                          else 0),
        }

    _last_store = None

    def _execute(self, watermark: Optional[float]) -> Batch:
        self._last_store = None
        if self.stateful_op is None:
            return self.plan.execute()
        store = self.state_provider.get_store(self.batch_id)
        node = self.stateful_node
        if isinstance(self.stateful_op, StatefulJoin):
            new_l = node.children[0].execute()
            new_r = node.children[1].execute()
            result = self.stateful_op.process_batch(new_l, new_r, store,
                                                    watermark, self.batch_id)
        elif isinstance(self.stateful_op, StatefulAggregation):
            child_batch = node.children[0].execute()
            result = self.stateful_op.process_batch(child_batch, store,
                                                    watermark)
        else:
            child_batch = node.children[0].execute()
            result = self.stateful_op.process_batch(child_batch, store,
                                                    watermark)
        self._last_store = store
        store.commit()
        above = replace_node(self.plan, node, Scan(result, "stateful"))
        return above.execute() if above is not node else result

    def _advance_watermark(self) -> None:
        new_wm = self.watermark
        candidates = [w.observed_max - w.delay for w in self.watermarks
                      if w.observed_max is not None]
        if candidates:
            candidate = min(candidates)  # multiple watermark ops: global min
            if new_wm is None or candidate > new_wm:
                new_wm = candidate
                self._wm_dirty = True
        self.watermark = new_wm


class ContinuousExecution(MicroBatchExecution):
    """Continuous processing (ref: continuous/ContinuousExecution.scala:42).

    Rows are processed AS THEY ARRIVE — the driver loop polls sources at
    sub-epoch cadence and pushes every new delta straight through the
    (stateless) plan to the sink — while offsets/commits are logged once
    per EPOCH (``epoch_interval`` seconds), the reference's epoch-marker
    model. Recovery restarts from the last committed epoch, so rows
    processed after it are emitted again: **at-least-once**, exactly the
    reference's continuous-mode guarantee (its micro-batch mode is the
    exactly-once one, here too). Stateless append-mode plans only, as the
    reference restricts (no aggregations/dedup/joins/watermarks).
    """

    def __init__(self, plan: LogicalPlan, sink: Sink, mode: str,
                 checkpoint_dir: str, session=None,
                 epoch_interval: float = 1.0):
        super().__init__(plan, sink, mode, checkpoint_dir, session)
        if self.stateful_op is not None or self.watermarks:
            raise ValueError(
                "continuous processing supports stateless queries only "
                "(ref: UnsupportedOperationChecker continuous checks)")
        if mode != "append":
            raise ValueError("continuous processing requires append mode")
        self.epoch_interval = float(epoch_interval)
        # a logged-but-uncommitted epoch is NOT replayed exactly; the rows
        # since the previous epoch re-emit (at-least-once)
        self._pending = None
        self._last_epoch_time = time.monotonic()
        self._epoch_start_offsets = dict(self._committed_offsets)
        # sinks deduplicate on batch id (the micro-batch exactly-once
        # contract); each DELTA therefore needs an id no other delta — in
        # THIS run or any previous crashed run — ever used, or a dedup sink
        # would silently drop re-emitted rows (losing, not duplicating).
        # A persisted run counter namespaces ids: run * 2^40 + epoch * 2^20
        # + seq.
        self._delta_seq = 0
        run_file = os.path.join(checkpoint_dir, "continuous-runs")
        run_id = 0
        if os.path.exists(run_file):
            with open(run_file, encoding="utf-8") as fh:
                run_id = int(fh.read().strip() or 0) + 1
        tmp = run_file + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(str(run_id))
        os.replace(tmp, run_file)
        self._run_id = run_id

    def _construct_next_batch_locked(self) -> bool:
        ends = {s.name: s.source.latest_offset() for s in self.scans}
        has_data = any(ends[n] > self._committed_offsets.get(n, 0)
                       for n in ends)
        if has_data:
            self._run_delta(ends)
        now = time.monotonic()
        if (now - self._last_epoch_time >= self.epoch_interval
                and self._committed_offsets != self._epoch_start_offsets):
            self._commit_epoch()
            self._last_epoch_time = now
        return has_data

    def _run_delta(self, ends: Dict[str, int]) -> None:
        t0 = time.perf_counter()
        n_in = 0
        for s in self.scans:
            start = self._committed_offsets.get(s.name, 0)
            s.current = s.source.get_batch(start, ends[s.name])
            n_in += len(next(iter(s.current.values()))) if s.current else 0
        out = self.plan.execute()
        self.sink.add_batch(self._run_id * (1 << 40)
                            + self.batch_id * (1 << 20) + self._delta_seq,
                            out, self.mode)
        self._delta_seq += 1
        for s in self.scans:
            s.current = None
        self._committed_offsets = dict(ends)
        self.last_progress = {
            "batchId": self.batch_id,
            "numInputRows": int(n_in),
            "durationMs": int((time.perf_counter() - t0) * 1000),
            "watermark": None,
            "stateRows": 0,
        }

    def _commit_epoch(self) -> None:
        """Write the epoch marker: one offset+commit log entry covering
        everything processed since the previous epoch."""
        entry = {"offsets": dict(self._committed_offsets), "watermark": None}
        # a crash between a previous epoch's offset and commit writes leaves
        # a stale offset entry at this id; MetadataLog.add refuses to
        # overwrite, so advance to a fresh id rather than letting the next
        # commit vouch for the stale offsets
        while not self.offset_log.add(self.batch_id, entry):
            self.batch_id += 1
        self.commit_log.add(self.batch_id, {"watermark": None})
        for s in self.scans:
            s.source.commit(self._committed_offsets[s.name])
        self._epoch_start_offsets = dict(self._committed_offsets)
        self.batch_id += 1
        self._delta_seq = 0
        if self.batch_id % 20 == 0:
            # the micro-batch purge lives in _run_batch, which this path
            # bypasses — a 1 s epoch would otherwise grow the checkpoint by
            # ~172k files/day
            self.offset_log.purge(keep_last=100)
            self.commit_log.purge(keep_last=100)

    def finalize(self) -> None:
        """Flush a final epoch on clean shutdown."""
        with self._batch_lock:
            if self._committed_offsets != self._epoch_start_offsets:
                self._commit_epoch()


class StreamingQuery:
    """User handle (ref: StreamingQuery.scala / StreamingQueryManager)."""

    def __init__(self, execution: MicroBatchExecution, trigger: Dict[str, Any],
                 name: str = ""):
        self.id = uuid.uuid4().hex
        self.name = name or f"query-{self.id[:8]}"
        self._exec = execution
        self._trigger = trigger
        self._active = True
        self._exception: Optional[Exception] = None
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self.recent_progress: List[Dict[str, Any]] = []

        if "continuous" in trigger:
            # sub-epoch polling: rows flow as they arrive, epochs commit on
            # the engine's own clock
            self._thread = threading.Thread(
                target=self._continuous_loop,
                name=f"stream-{self.name}", daemon=True)
            self._thread.start()
        elif "processingTime" in trigger:
            self._thread = threading.Thread(
                target=self._loop, name=f"stream-{self.name}", daemon=True)
            self._thread.start()
        elif trigger.get("once") or trigger.get("availableNow"):
            try:
                self.process_all_available()
            finally:
                self._active = False

    def _record(self, ran: bool) -> None:
        if ran:
            self.recent_progress.append(self._exec.last_progress)
            del self.recent_progress[:-100]

    def process_all_available(self) -> None:
        """Run batches until sources are drained (≈ Trigger.AvailableNow /
        StreamTest's ProcessAllAvailable)."""
        if self._exception:
            raise self._exception
        while True:
            ran = self._exec.construct_next_batch()
            self._record(ran)
            if not ran:
                return

    def _loop(self) -> None:
        interval = float(self._trigger["processingTime"])
        delay = 0.0  # first attempt immediately, then poll at the interval
        while not self._stop_evt.wait(delay):
            delay = interval
            try:
                self._record(self._exec.construct_next_batch())
            except Exception as e:  # surfaced via .exception, as the ref does
                self._exception = e
                self._active = False
                return

    def _continuous_loop(self) -> None:
        poll = min(0.005, float(self._trigger["continuous"]) / 10.0)
        while not self._stop_evt.wait(poll):
            try:
                self._record(self._exec.construct_next_batch())
            except Exception as e:
                self._exception = e
                self._active = False
                return

    def stop(self) -> None:
        self._stop_evt.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        if hasattr(self._exec, "finalize"):
            try:
                self._exec.finalize()  # continuous mode: flush final epoch
            except Exception:
                pass
        self._active = False

    def await_termination(self, timeout: Optional[float] = None) -> bool:
        if self._thread is not None:
            self._thread.join(timeout)
            return not self._thread.is_alive()
        return True

    @property
    def is_active(self) -> bool:
        return self._active

    @property
    def exception(self) -> Optional[Exception]:
        return self._exception

    @property
    def last_progress(self) -> Optional[Dict[str, Any]]:
        return self.recent_progress[-1] if self.recent_progress else None

    @property
    def status(self) -> Dict[str, Any]:
        return {"isActive": self._active,
                "batchId": self._exec.batch_id,
                "watermark": self._exec.watermark}


class DataStreamReader:
    """(ref: DataStreamReader.scala) — ``session.read_stream.format(...)``."""

    def __init__(self, session):
        self._session = session
        self._format = "csv"
        self._options: Dict[str, Any] = {}
        self._schema: Optional[List[str]] = None

    def format(self, fmt: str) -> "DataStreamReader":
        self._format = fmt
        return self

    def option(self, key: str, value) -> "DataStreamReader":
        self._options[key] = value
        return self

    def schema(self, cols: List[str]) -> "DataStreamReader":
        self._schema = list(cols)
        return self

    def load(self, path: Optional[str] = None):
        from cycloneml_tpu.sql.dataframe import DataFrame
        if self._format == "rate":
            src: Source = RateSource(
                int(self._options.get("rowsPerSecond", 10)))
        elif self._format in ("csv", "text", "file"):
            fmt = "text" if self._format == "text" else "csv"
            src = FileStreamSource(
                path or self._options["path"], fmt=fmt,
                pattern=self._options.get("pattern", "*"),
                header=bool(self._options.get("header", True)),
                delimiter=self._options.get("delimiter", ","),
                schema=self._schema)
        else:
            raise ValueError(f"unknown stream format {self._format!r}")
        return DataFrame(StreamingScan(src, self._format), self._session)

    def csv(self, path: str):
        return self.format("csv").load(path)

    def text(self, path: str):
        return self.format("text").load(path)


class DataStreamWriter:
    """(ref: DataStreamWriter.scala) — ``df.write_stream...start()``."""

    def __init__(self, df):
        self._df = df
        self._mode = "append"
        self._format = "memory"
        self._options: Dict[str, Any] = {}
        # default = continuous micro-batches ASAP (ref: Trigger.ProcessingTime(0))
        self._trigger: Dict[str, Any] = {"processingTime": 0.1}
        self._name = ""
        self._foreach: Optional[Callable] = None
        self._custom_sink: Optional[Sink] = None
        self.sink: Optional[Sink] = None

    def output_mode(self, mode: str) -> "DataStreamWriter":
        if mode not in ("append", "update", "complete"):
            raise ValueError(f"unknown output mode {mode!r}")
        self._mode = mode
        return self

    def format(self, fmt: str) -> "DataStreamWriter":
        self._format = fmt
        return self

    def option(self, key: str, value) -> "DataStreamWriter":
        self._options[key] = value
        return self

    def query_name(self, name: str) -> "DataStreamWriter":
        self._name = name
        return self

    def trigger(self, once: bool = False, available_now: bool = False,
                processing_time: Optional[float] = None,
                continuous: Optional[float] = None) -> "DataStreamWriter":
        if continuous is not None:
            # (ref Trigger.Continuous) — epoch checkpoint interval in seconds
            self._trigger = {"continuous": float(continuous)}
        elif processing_time is not None:
            self._trigger = {"processingTime": processing_time}
        elif once:
            self._trigger = {"once": True}
        elif available_now:
            self._trigger = {"availableNow": True}
        return self

    def foreach_batch(self, fn: Callable) -> "DataStreamWriter":
        self._foreach = fn
        self._format = "foreach_batch"
        return self

    def sink_to(self, sink: Sink) -> "DataStreamWriter":
        """Write to a caller-constructed Sink instance (e.g. a
        ``serving.ScoringSink`` wrapping a MemorySink — the
        featurize→predict→sink pipeline). The sink owns idempotence per
        batch id, like every other sink."""
        self._custom_sink = sink
        self._format = "custom"
        return self

    def start(self, path: Optional[str] = None) -> StreamingQuery:
        session = self._df.session
        ckpt = self._options.get("checkpointLocation") or tempfile.mkdtemp(
            prefix="cyclone-stream-")
        if self._format == "memory":
            sink: Sink = MemorySink()
        elif self._format == "console":
            sink = ConsoleSink(int(self._options.get("numRows", 20)))
        elif self._format in ("csv", "json"):
            sink = FileSink(path or self._options["path"], self._format)
        elif self._format == "foreach_batch":
            sink = ForeachBatchSink(self._foreach, session)
        elif self._format == "custom" and self._custom_sink is not None:
            sink = self._custom_sink
        else:
            raise ValueError(f"unknown sink format {self._format!r}")
        self.sink = sink
        if "continuous" in self._trigger:
            execution: MicroBatchExecution = ContinuousExecution(
                self._df.plan, sink, self._mode, ckpt, session,
                epoch_interval=float(self._trigger["continuous"]))
        else:
            execution = MicroBatchExecution(self._df.plan, sink, self._mode,
                                            ckpt, session)
        q = StreamingQuery(execution, dict(self._trigger), self._name)
        q.sink = sink
        if self._format == "memory" and session is not None and self._name:
            session.register_memory_stream_table(self._name, sink)
        return q
