"""Kinesis streaming source.

Analog of the reference's kinesis-asl connector (ref: external/kinesis-asl —
KinesisReceiver/KinesisInputDStream reading shard records with
sequence-number checkpoints via the KCL). The AWS client is optional: pass
``client_factory`` for tests or local stacks (kinesalite/localstack);
without it the constructor needs ``boto3`` (gated import, not bundled —
the reference ships kinesis-asl as a separate artifact for the same
reason, ASL licensing included).

Rows follow the reference's record schema: ``(data, partitionKey,
sequenceNumber, streamName, approximateArrivalTimestamp)``.

Offsets: the engine's single int offset is a row count over records merged
from all shards in iterator order; per-shard sequence numbers are tracked
and persisted at commit (the KCL checkpoint analog), so a restarted query
resumes each shard AFTER its last committed sequence number and replays
consumed-but-uncommitted rows from the engine's own offset log semantics
(``get_batch`` stays replayable until ``commit`` — the Source contract).
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional

import numpy as np

from cycloneml_tpu.sql.plan import Batch
from cycloneml_tpu.streaming.sources import Source

SCHEMA = ["data", "partitionKey", "sequenceNumber", "streamName",
          "approximateArrivalTimestamp"]


class KinesisSource(Source):
    schema = SCHEMA

    def __init__(self, stream_name: str, region: Optional[str] = None,
                 client_factory: Optional[Callable] = None,
                 records_per_poll: int = 1000, decode: bool = True):
        self.stream_name = stream_name
        self.records_per_poll = records_per_poll
        self.decode = decode
        if client_factory is not None:
            self._client = client_factory()
        else:
            try:
                import boto3  # gated optional dep
            except ImportError as e:
                raise ImportError(
                    "KinesisSource needs the 'boto3' package (or pass "
                    "client_factory=); it is not bundled with "
                    "cycloneml_tpu") from e
            self._client = boto3.client("kinesis", region_name=region)
        self._rows: List[tuple] = []   # replay buffer
        self._row_shards: List[str] = []  # source shard per buffered row
        self._base = 0                 # engine offset of _rows[0]
        self._log_dir: Optional[str] = None
        # shard id -> last committed sequence number (KCL checkpoint analog)
        self._committed_seq: Dict[str, str] = {}
        # shard id -> live iterator token
        self._iterators: Dict[str, Optional[str]] = {}
        # shards whose iterator chain ended (reshard/closed): never re-open,
        # or every poll would replay them from the checkpoint
        self._closed: set = set()

    # -- checkpoint persistence -------------------------------------------
    def set_log_dir(self, path: str) -> None:
        """Recover committed shard sequence numbers from a query checkpoint
        (idempotent; the engine's offset log replays uncommitted batches)."""
        os.makedirs(path, exist_ok=True)
        first = self._log_dir is None
        self._log_dir = path
        if not first:
            return
        meta_p = os.path.join(path, "kinesis.json")
        if os.path.exists(meta_p) and os.path.getsize(meta_p) > 0:
            with open(meta_p, encoding="utf-8") as fh:
                meta = json.load(fh)
            self._base = int(meta["base"])
            self._committed_seq = dict(meta.get("shards", {}))
            self._iterators = {}  # re-open AFTER the committed seqs

    def _shard_iterator(self, shard_id: str) -> Optional[str]:
        if shard_id in self._closed:
            return None
        it = self._iterators.get(shard_id)
        if it is not None:
            return it
        seq = self._committed_seq.get(shard_id)
        kwargs = dict(StreamName=self.stream_name, ShardId=shard_id)
        if seq:
            kwargs.update(ShardIteratorType="AFTER_SEQUENCE_NUMBER",
                          StartingSequenceNumber=seq)
        else:
            kwargs.update(ShardIteratorType="TRIM_HORIZON")
        it = self._client.get_shard_iterator(**kwargs)["ShardIterator"]
        self._iterators[shard_id] = it
        return it

    def _poll(self) -> None:
        shards = self._client.list_shards(StreamName=self.stream_name)
        for shard in shards.get("Shards", []):
            sid = shard["ShardId"]
            it = self._shard_iterator(sid)
            if not it:
                continue
            resp = self._client.get_records(ShardIterator=it,
                                            Limit=self.records_per_poll)
            nxt = resp.get("NextShardIterator")
            self._iterators[sid] = nxt
            if nxt is None:
                self._closed.add(sid)
            for rec in resp.get("Records", []):
                data = rec["Data"]
                if self.decode and isinstance(data, (bytes, bytearray)):
                    try:
                        data = data.decode("utf-8")
                    except UnicodeDecodeError:
                        pass  # binary payloads stay bytes
                ts = rec.get("ApproximateArrivalTimestamp", 0)
                ts = int(getattr(ts, "timestamp", lambda: ts)())
                self._rows.append((data, rec.get("PartitionKey", ""),
                                   rec["SequenceNumber"], self.stream_name,
                                   ts))
                self._row_shards.append(sid)

    # -- Source contract ----------------------------------------------------
    def latest_offset(self) -> int:
        self._poll()
        return self._base + len(self._rows)

    def get_batch(self, start: int, end: int) -> Batch:
        lo, hi = start - self._base, end - self._base
        rows = self._rows[max(0, lo):hi]
        cols = list(zip(*rows)) if rows else [[] for _ in SCHEMA]
        out: Batch = {}
        for name, vals in zip(SCHEMA, cols):
            if name == "approximateArrivalTimestamp":
                out[name] = np.array(vals, dtype=np.int64)
            else:
                out[name] = np.array(vals, dtype=object)
        return out

    def commit(self, end: int) -> None:
        """Discard replay rows up to ``end`` and checkpoint per-shard
        sequence numbers (the KCL checkpoint analog)."""
        drop = end - self._base
        if drop <= 0:
            return
        for row, sid in zip(self._rows[:drop], self._row_shards[:drop]):
            # sequence numbers are large decimal strings AWS says to
            # compare NUMERICALLY (lexicographic breaks across lengths)
            seq = str(row[2])
            prev = self._committed_seq.get(sid)
            if prev is None or int(seq) > int(prev):
                self._committed_seq[sid] = seq
        self._rows = self._rows[drop:]
        self._row_shards = self._row_shards[drop:]
        self._base = end
        if self._log_dir:
            meta_p = os.path.join(self._log_dir, "kinesis.json")
            tmp = meta_p + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump({"base": self._base,
                           "shards": self._committed_seq}, fh)
            os.replace(tmp, meta_p)  # atomic, torn-write safe
