"""Atomic file-based metadata logs.

Analog of HDFSMetadataLog / OffsetSeqLog / CommitLog (ref: sql/core/.../
streaming/HDFSMetadataLog.scala, OffsetSeqLog.scala, CommitLog.scala and the
atomic-rename discipline of CheckpointFileManager.scala): one JSON file per
batch id, written to a temp name then renamed so readers never observe a
partial entry. The pair (offsets written before a batch runs, commit written
after the sink accepts it) is what makes restart recovery exactly-once for
replayable sources and idempotent sinks.
"""

from __future__ import annotations

import json
import os
import tempfile
from typing import Any, Dict, List, Optional, Tuple


class MetadataLog:
    """Monotonic batch-id → JSON-dict log with atomic writes."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _file(self, batch_id: int) -> str:
        return os.path.join(self.path, str(batch_id))

    def add(self, batch_id: int, metadata: Dict[str, Any]) -> bool:
        """Write entry if absent; False if the batch id already exists."""
        target = self._file(batch_id)
        if os.path.exists(target):
            return False
        fd, tmp = tempfile.mkstemp(dir=self.path, prefix=".tmp-")
        try:
            with os.fdopen(fd, "w", encoding="utf-8") as fh:
                json.dump(metadata, fh)
            os.replace(tmp, target)
        finally:
            if os.path.exists(tmp):
                os.unlink(tmp)
        return True

    def get(self, batch_id: int) -> Optional[Dict[str, Any]]:
        target = self._file(batch_id)
        if not os.path.exists(target):
            return None
        with open(target, encoding="utf-8") as fh:
            return json.load(fh)

    def batch_ids(self) -> List[int]:
        out = []
        for name in os.listdir(self.path):
            if name.isdigit():
                out.append(int(name))
        return sorted(out)

    def latest(self) -> Optional[Tuple[int, Dict[str, Any]]]:
        ids = self.batch_ids()
        if not ids:
            return None
        return ids[-1], self.get(ids[-1])

    def purge(self, keep_last: int = 100) -> None:
        """Drop entries older than the newest ``keep_last`` (≈ the reference's
        minBatchesToRetain compaction)."""
        ids = self.batch_ids()
        for bid in ids[:-keep_last] if keep_last else ids:
            try:
                os.unlink(self._file(bid))
            except OSError:
                pass
