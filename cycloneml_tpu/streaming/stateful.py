"""Stateful streaming operators.

Analogs of the reference's stateful physical operators: streaming aggregation
(ref: sql/core/.../execution/streaming/statefulOperators.scala
StateStoreSaveExec/StateStoreRestoreExec), streaming deduplication
(StreamingDeduplicateExec), stream-stream join
(StreamingSymmetricHashJoinExec + SymmetricHashJoinStateManager), and event-
time watermarks (EventTimeWatermarkExec).

Aggregations are incrementalized by keeping *mergeable partials* per group in
the state store (sum/count/min/max merge directly; avg as (sum,count);
count_distinct as a value set) — the same partial-aggregate shape the
reference's HashAggregateExec produces before its state-store save.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from cycloneml_tpu.sql.column import (AggExpr, Alias, ColumnRef, Expr,
                                      WindowExpr)
from cycloneml_tpu.sql.plan import (Aggregate, Batch, Join, LogicalPlan, Scan,
                                    _factorize)
from cycloneml_tpu.streaming.state import StateStore


class Watermark(LogicalPlan):
    """Pass-through marker: ``event_col`` lags at most ``delay`` seconds
    behind the max observed event time (ref: EventTimeWatermarkExec). The
    engine reads ``observed_max`` after each batch to advance the global
    watermark."""

    def __init__(self, child: LogicalPlan, event_col: str, delay: float):
        self.children = [child]
        self.event_col = event_col
        self.delay = float(delay)
        self.observed_max: Optional[float] = None

    def with_children(self, c):
        w = Watermark(c[0], self.event_col, self.delay)
        w.observed_max = self.observed_max
        return w

    def output(self):
        return self.children[0].output()

    def execute(self):
        batch = self.children[0].execute()
        col = batch.get(self.event_col)
        if col is not None and len(col):
            m = float(np.max(np.asarray(col, dtype=float)))
            self.observed_max = m if self.observed_max is None else max(
                self.observed_max, m)
        return batch

    def __repr__(self):
        return f"Watermark({self.event_col}, delay={self.delay}s)"


class Deduplicate(LogicalPlan):
    """dropDuplicates(subset) — batch execution dedups within the batch;
    the streaming engine adds cross-batch state (StreamingDeduplicateExec)."""

    def __init__(self, child: LogicalPlan, subset: Optional[List[str]] = None):
        self.children = [child]
        self.subset = subset

    def with_children(self, c):
        return Deduplicate(c[0], self.subset)

    def output(self):
        return self.children[0].output()

    def execute(self):
        batch = self.children[0].execute()
        cols = self.subset or list(batch)
        n = len(next(iter(batch.values()))) if batch else 0
        if n == 0:
            return batch
        keys = [np.asarray(batch[c]) for c in cols]
        _, _, first_idx = _factorize(keys)
        first_idx = np.sort(first_idx)
        return {c: np.asarray(v)[first_idx] for c, v in batch.items()}

    def __repr__(self):
        return f"Deduplicate({self.subset or '*'})"


# -- mergeable partials for each aggregate kind --------------------------------

def _batch_partials(a: AggExpr, batch: Batch, codes: np.ndarray,
                    n_groups: int, n_rows: int) -> List[Any]:
    values = None
    if a.children:
        values = np.atleast_1d(a.children[0].eval(batch))
        if values.shape[0] != n_rows:
            values = np.broadcast_to(values, (n_rows,)).copy()
    if a.fn == "avg":
        s = np.bincount(codes, weights=np.asarray(values, dtype=float),
                        minlength=n_groups)
        c = np.bincount(codes, minlength=n_groups)
        return [(float(s[i]), int(c[i])) for i in range(n_groups)]
    if a.fn == "count_distinct":
        sets: List[set] = [set() for _ in range(n_groups)]
        for g, v in zip(codes, values):
            sets[g].add(v.item() if isinstance(v, np.generic) else v)
        return sets
    if a.fn == "collect_list":
        lists: List[list] = [[] for _ in range(n_groups)]
        for g, v in zip(codes, values):
            lists[g].append(v.item() if isinstance(v, np.generic) else v)
        return lists
    if a.fn == "first":
        out: List[Any] = [None] * n_groups
        seen = [False] * n_groups
        for g, v in zip(codes, values):
            if not seen[g]:
                out[g] = v.item() if isinstance(v, np.generic) else v
                seen[g] = True
        return out
    # sum / count / min / max: the per-group result IS the mergeable partial
    arr = a.agg(values, codes, n_groups)
    return [x.item() if isinstance(x, np.generic) else x for x in arr]


def _merge_partial(fn: str, old: Any, new: Any) -> Any:
    if old is None:
        return new
    if fn in ("sum", "count"):
        return old + new
    if fn == "min":
        return min(old, new)
    if fn == "max":
        return max(old, new)
    if fn == "avg":
        return (old[0] + new[0], old[1] + new[1])
    if fn == "count_distinct":
        return old | new
    if fn == "collect_list":
        return old + new
    if fn == "first":
        return old
    raise ValueError(f"aggregate {fn!r} is not supported in streaming "
                     f"(not mergeable)")


def _finalize_partial(fn: str, p: Any) -> Any:
    if fn == "avg":
        return p[0] / p[1] if p[1] else float("nan")
    if fn == "count_distinct":
        return len(p)
    return p


class StatefulAggregation:
    """Incremental group-by over micro-batches.

    Per batch: evaluate group keys + per-aggregate partials on the new rows,
    merge into the keyed state store, then emit per the output mode:
    ``complete`` = all groups, ``update`` = groups touched this batch,
    ``append`` = watermark-expired groups only (emitted once, then evicted) —
    the same mode semantics as the reference (InternalOutputModes).
    """

    def __init__(self, agg: Aggregate, mode: str,
                 watermark_col: Optional[str] = None):
        self.agg = agg
        self.mode = mode
        self.agg_ids = []
        seen = set()
        for e in agg.agg_exprs:
            for a in e.find_aggregates():
                key = f"__agg_{a}"
                if key not in seen:
                    seen.add(key)
                    self.agg_ids.append((key, a))
        self.watermark_key_idx: Optional[int] = None
        self.window_width = 0.0  # 0 = point events (raw event-time key)
        if watermark_col is not None:
            for i, g in enumerate(agg.group_exprs):
                base = g.children[0] if isinstance(g, Alias) else g
                if isinstance(base, ColumnRef) and base.name == watermark_col:
                    self.watermark_key_idx = i
                    break
                if (isinstance(base, WindowExpr)
                        and watermark_col in base.references()):
                    self.watermark_key_idx = i
                    self.window_width = base.width
                    break
            else:
                derived = [i for i, g in enumerate(agg.group_exprs)
                           if watermark_col in g.references()]
                if derived and mode == "append":
                    raise ValueError(
                        "append mode needs the event-time grouping key to be "
                        f"the watermarked column {watermark_col!r} itself or "
                        "F.window() over it — an arbitrary derived expression "
                        "has no known window end, so windows would be closed "
                        "while still open")
        if mode == "append" and self.watermark_key_idx is None:
            raise ValueError(
                "append mode on a streaming aggregation requires a watermark "
                "on (a derivative of) one of the grouping columns "
                "(ref: UnsupportedOperationChecker)")

    def process_batch(self, batch: Batch, store: StateStore,
                      watermark: Optional[float]) -> Batch:
        n = len(next(iter(batch.values()))) if batch else 0
        touched: List[Tuple] = []
        if n > 0:
            keys = [np.atleast_1d(g.eval(batch)) for g in self.agg.group_exprs]
            if keys:
                codes, n_groups, first_idx = _factorize(keys)
            else:
                codes = np.zeros(n, dtype=np.int64)
                n_groups, first_idx = 1, np.array([0])
            partials = {key: _batch_partials(a, batch, codes, n_groups, n)
                        for key, a in self.agg_ids}
            for g in range(n_groups):
                row = first_idx[g]
                key = tuple(
                    k[row].item() if isinstance(k[row], np.generic) else k[row]
                    for k in keys)
                if (self.mode in ("append", "update") and watermark is not None
                        and self.watermark_key_idx is not None
                        and self._expired(key, watermark)):
                    # late data: append already finalized the group; update
                    # already evicted it (StateStoreSaveExec drops late rows
                    # in both modes)
                    continue
                state = store.get(key) or {}
                for pkey, a in self.agg_ids:
                    state[pkey] = _merge_partial(a.fn, state.get(pkey),
                                                 partials[pkey][g])
                store.put(key, state)
                touched.append(key)

        if self.mode == "complete":
            return self._emit([(k, v) for k, v in store.items()])
        if self.mode == "update":
            # update mode also evicts watermark-expired groups (without
            # emitting them — they were already emitted on their last change;
            # ref: StateStoreSaveExec update-mode removeKeysOlderThanWatermark)
            # so long-running queries don't leak state without bound
            if watermark is not None and self.watermark_key_idx is not None:
                for k, _ in list(store.items()):
                    if self._expired(k, watermark):
                        store.remove(k)
            return self._emit([(k, store.get(k)) for k in touched])
        # append: emit + evict groups whose window END passed the watermark
        out: List[Tuple[Tuple, Dict]] = []
        if watermark is not None:
            for k, v in list(store.items()):
                if self._expired(k, watermark):
                    out.append((k, v))
                    store.remove(k)
        return self._emit(out)

    def _expired(self, key: Tuple, watermark: float) -> bool:
        t = float(key[self.watermark_key_idx])
        if self.window_width > 0:
            return t + self.window_width <= watermark  # window end passed
        return t < watermark  # point event-time key

    def _emit(self, groups: List[Tuple[Tuple, Dict]]) -> Batch:
        group_batch: Batch = {}
        n = len(groups)
        for i, g in enumerate(self.agg.group_exprs):
            group_batch[g.name_hint()] = np.array(
                [k[i] for k, _ in groups], dtype=object)
        for pkey, a in self.agg_ids:
            group_batch[pkey] = np.array(
                [_finalize_partial(a.fn, v[pkey]) for _, v in groups],
                dtype=object)
        group_batch["__len__"] = n
        out: Batch = {}
        for g in self.agg.group_exprs:
            out[g.name_hint()] = _narrow(group_batch[g.name_hint()])
        for e in self.agg.agg_exprs:
            rewritten = e.transform(
                lambda node: ColumnRef(f"__agg_{node}")
                if isinstance(node, AggExpr) else None)
            v = np.atleast_1d(np.asarray(rewritten.eval(group_batch)))
            if v.shape[0] != n:
                v = np.broadcast_to(v, (n,)).copy() if n else v[:0]
            out[e.name_hint()] = _narrow(v)
        return out


class StatefulDedup:
    """Cross-batch dropDuplicates (ref: StreamingDeduplicateExec). With a
    watermarked event-time column in the key, expired keys are evicted."""

    def __init__(self, dedup: Deduplicate, watermark_col: Optional[str] = None):
        self.subset = dedup.subset
        self.watermark_col = watermark_col

    def process_batch(self, batch: Batch, store: StateStore,
                      watermark: Optional[float]) -> Batch:
        cols = self.subset or list(batch)
        n = len(next(iter(batch.values()))) if batch else 0
        keep = []
        for i in range(n):
            key = tuple(
                batch[c][i].item() if isinstance(batch[c][i], np.generic)
                else batch[c][i] for c in cols)
            if store.get(key) is None:
                ts = (float(batch[self.watermark_col][i])
                      if self.watermark_col in batch else 0.0)
                store.put(key, ts)
                keep.append(i)
        if watermark is not None and self.watermark_col is not None:
            for k, ts in list(store.items()):
                if ts < watermark:
                    store.remove(k)
        idx = np.asarray(keep, dtype=np.int64)
        return {c: np.asarray(v)[idx] for c, v in batch.items()}


class StatefulJoin:
    """Inner stream-stream join (ref: StreamingSymmetricHashJoinExec): both
    inputs are buffered in state; each batch joins its new rows against the
    other side's full buffer, so every matching pair is emitted exactly once.
    Buffers are stored as one chunk per micro-batch under ("L"/"R", batch_id)
    keys, so each state delta carries only that batch's new rows (the
    referenced SymmetricHashJoinStateManager keys per-row for the same
    reason); watermarked event-time columns bound the buffers."""

    def __init__(self, join: Join, watermark_cols: Dict[str, float]):
        if join.how != "inner":
            raise ValueError("streaming stream-stream join supports inner only "
                             "(outer joins need watermark range analysis)")
        self.join = join
        self.watermark_cols = watermark_cols

    @staticmethod
    def _rows(b: Optional[Batch]) -> int:
        return len(next(iter(b.values()))) if b else 0

    def _side_chunks(self, store: StateStore, side: str) -> List[Tuple[Tuple, Batch]]:
        return sorted(((k, v) for k, v in store.items() if k[0] == side),
                      key=lambda kv: kv[0][1])

    def _evict_chunks(self, store: StateStore, side: str,
                      watermark: Optional[float]) -> None:
        if watermark is None:
            return
        for key, chunk in self._side_chunks(store, side):
            col = next((c for c in self.watermark_cols if c in chunk), None)
            if col is None:
                return
            mask = np.asarray(chunk[col], dtype=float) >= watermark
            if mask.all():
                continue  # untouched chunks produce no delta entry
            if not mask.any():
                store.remove(key)
            else:
                store.put(key, {c: np.asarray(v)[mask]
                                for c, v in chunk.items()})

    def process_batch(self, new_left: Batch, new_right: Batch,
                      store: StateStore, watermark: Optional[float],
                      batch_id: int) -> Batch:
        from cycloneml_tpu.streaming.sources import _concat_batches

        def gather(side: str) -> Optional[Batch]:
            chunks = [v for _, v in self._side_chunks(store, side)
                      if self._rows(v)]
            if not chunks:
                return None
            return _concat_batches(chunks, list(chunks[0]))

        def run(lb: Optional[Batch], rb: Optional[Batch]) -> Optional[Batch]:
            if not self._rows(lb) or not self._rows(rb):
                return None
            j = self.join.with_children([Scan(lb, "l"), Scan(rb, "r")])
            return j.execute()

        buf_l, buf_r = gather("L"), gather("R")
        full_r = (_concat_batches([b for b in (buf_r, new_right)
                                   if self._rows(b)],
                                  list(new_right or buf_r))
                  if (self._rows(buf_r) or self._rows(new_right)) else None)
        parts = [run(new_left, full_r), run(buf_l, new_right)]
        parts = [p for p in parts if p is not None]

        if self._rows(new_left):
            store.put(("L", batch_id), new_left)
        if self._rows(new_right):
            store.put(("R", batch_id), new_right)
        self._evict_chunks(store, "L", watermark)
        self._evict_chunks(store, "R", watermark)

        if not parts:
            return {c: np.array([]) for c in self.join.output()}
        return {c: np.concatenate([np.asarray(p[c]) for p in parts])
                for c in parts[0]}


def _narrow(arr: np.ndarray) -> np.ndarray:
    if arr.dtype == object and len(arr):
        first = arr[0]
        if isinstance(first, (int, np.integer)) and all(
                isinstance(x, (int, np.integer)) for x in arr):
            return arr.astype(np.int64)
        if isinstance(first, (float, int, np.floating, np.integer)) and all(
                isinstance(x, (float, int, np.floating, np.integer)) for x in arr):
            return arr.astype(np.float64)
    return arr
