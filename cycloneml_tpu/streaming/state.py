"""Versioned per-query state stores.

Analog of the reference's StateStore stack (ref: sql/core/.../streaming/
state/StateStore.scala, HDFSBackedStateStoreProvider.scala:73 snapshot+delta
layout, RocksDBStateStoreProvider.scala:30). A provider owns the state of one
stateful operator; each micro-batch loads version ``v`` (the last committed
batch), mutates a copy, and commits version ``v+1`` as a delta file. Every
``snapshot_interval`` commits a full snapshot is written so recovery replays
a bounded number of deltas. Values are arbitrary pickled Python objects keyed
by tuples — the host ETL tier's row format is columnar numpy, but state is
touched per-group, so a keyed map is the right shape (the reference's
UnsafeRow-keyed maps serve the same role).

When the native host runtime is available, snapshot/delta bytes go through
the zstd codec (ref: the reference compresses state snapshots via its codec
plugin point, io/CompressionCodec.scala:63).
"""

from __future__ import annotations

import os
import pickle
import tempfile
from typing import Any, Dict, Iterator, Optional, Tuple

Key = Tuple
_TOMBSTONE = "__cyclone_tombstone__"


def _maybe_compress(data: bytes) -> bytes:
    try:
        from cycloneml_tpu.native.host import CompressionCodec, native_available
        if native_available():
            return b"Z" + CompressionCodec("zstd").compress(data)
    except Exception:
        pass
    return b"R" + data


def _maybe_decompress(blob: bytes) -> bytes:
    tag, payload = blob[:1], blob[1:]
    if tag == b"Z":
        from cycloneml_tpu.native.host import CompressionCodec
        return CompressionCodec.decompress(payload)
    return payload


class StateStore:
    """One mutable version of a keyed state map. Mutations are buffered and
    applied on ``commit`` (≈ StateStore.scala's abort/commit contract)."""

    def __init__(self, provider: "StateStoreProvider", version: int,
                 contents: Dict[Key, Any]):
        self._provider = provider
        self.version = version
        self._contents = contents
        self._updates: Dict[Key, Any] = {}
        self._committed = False

    def get(self, key: Key) -> Optional[Any]:
        if key in self._updates:
            v = self._updates[key]
            return None if v is _TOMBSTONE else v
        return self._contents.get(key)

    def put(self, key: Key, value: Any) -> None:
        self._updates[key] = value

    def remove(self, key: Key) -> None:
        self._updates[key] = _TOMBSTONE

    def items(self) -> Iterator[Tuple[Key, Any]]:
        for k, v in self._contents.items():
            if k not in self._updates:
                yield k, v
        for k, v in self._updates.items():
            if v is not _TOMBSTONE:
                yield k, v

    def __len__(self) -> int:
        n = sum(1 for k in self._contents if k not in self._updates)
        return n + sum(1 for v in self._updates.values() if v is not _TOMBSTONE)

    def commit(self) -> int:
        """Persist as version+1; returns the new version."""
        if self._committed:
            raise RuntimeError("state store already committed")
        self._committed = True
        return self._provider._commit(self.version, self._contents, self._updates)

    def abort(self) -> None:
        self._updates.clear()


class StateStoreProvider:
    """Snapshot+delta file layout under ``<dir>``:
    ``<v>.delta`` (changed keys + tombstones) and ``<v>.snapshot``."""

    def __init__(self, path: str, snapshot_interval: int = 10):
        self.path = path
        self.snapshot_interval = max(1, snapshot_interval)
        os.makedirs(path, exist_ok=True)

    # -- file helpers ----------------------------------------------------------
    def _write(self, name: str, obj: Any) -> None:
        blob = _maybe_compress(pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL))
        fd, tmp = tempfile.mkstemp(dir=self.path, prefix=".tmp-")
        with os.fdopen(fd, "wb") as fh:
            fh.write(blob)
        os.replace(tmp, os.path.join(self.path, name))

    def _read(self, name: str) -> Any:
        with open(os.path.join(self.path, name), "rb") as fh:
            return pickle.loads(_maybe_decompress(fh.read()))

    def _versions(self, suffix: str):
        out = []
        for name in os.listdir(self.path):
            if name.endswith(suffix):
                stem = name[: -len(suffix)]
                if stem.isdigit():
                    out.append(int(stem))
        return sorted(out)

    # -- public ----------------------------------------------------------------
    def get_store(self, version: int) -> StateStore:
        """Load state as of ``version`` (0 = empty) for the next batch."""
        if version == 0:
            return StateStore(self, 0, {})
        contents = self._load(version)
        return StateStore(self, version, contents)

    def _load(self, version: int) -> Dict[Key, Any]:
        snaps = [v for v in self._versions(".snapshot") if v <= version]
        base_version = snaps[-1] if snaps else 0
        contents: Dict[Key, Any] = (
            dict(self._read(f"{base_version}.snapshot")) if snaps else {})
        for v in range(base_version + 1, version + 1):
            delta = self._read(f"{v}.delta")
            for k, val in delta.items():
                if val == _TOMBSTONE:
                    contents.pop(k, None)
                else:
                    contents[k] = val
        return contents

    def _commit(self, version: int, contents: Dict[Key, Any],
                updates: Dict[Key, Any]) -> int:
        new_version = version + 1
        delta = {k: (_TOMBSTONE if v is _TOMBSTONE else v)
                 for k, v in updates.items()}
        self._write(f"{new_version}.delta", delta)
        if new_version % self.snapshot_interval == 0:
            merged = dict(contents)
            for k, v in updates.items():
                if v is _TOMBSTONE:
                    merged.pop(k, None)
                else:
                    merged[k] = v
            self._write(f"{new_version}.snapshot", merged)
        return new_version

    def latest_version(self) -> int:
        versions = self._versions(".delta") + self._versions(".snapshot")
        return max(versions) if versions else 0

    def purge(self, keep_version: int) -> None:
        """Drop files not needed to reconstruct ``keep_version`` onward."""
        snaps = [v for v in self._versions(".snapshot") if v <= keep_version]
        if not snaps:
            return
        floor = snaps[-1]
        for v in self._versions(".delta"):
            if v <= floor:
                os.unlink(os.path.join(self.path, f"{v}.delta"))
        for v in snaps[:-1]:
            os.unlink(os.path.join(self.path, f"{v}.snapshot"))
