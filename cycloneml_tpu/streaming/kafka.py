"""Kafka streaming source.

Analog of the reference's kafka-0-10-sql connector (ref: external/
kafka-0-10-sql — KafkaSource/KafkaMicroBatchStream reading (key, value,
topic, partition, offset, timestamp) rows with per-partition offset ranges).
The kafka client library is optional: pass ``consumer_factory`` for tests or
embedded brokers; without it the constructor needs ``kafka-python``
installed (gated import, not bundled — the reference ships its connector as
a separate artifact for the same reason).

Offsets: the engine's single monotonically-increasing int offset maps to a
row count; per-partition Kafka offsets are tracked internally and snapshots
of consumed-but-uncommitted rows are buffered so ``get_batch`` stays
replayable until ``commit`` (the Source contract).

Restart durability: under a checkpointed query the engine calls
``set_log_dir`` (same hook as FileStreamSource), and the source persists
(a) the committed engine offset + per-partition Kafka offsets and (b) a WAL
of consumed-but-uncommitted rows. A restarted query therefore rebuilds the
exact replay buffer — engine offsets recovered from the query's offset log
map to the same rows — and seeks the consumer past everything already
WAL'd, preserving the exactly-once restart contract (ref: KafkaSource logs
per-partition offset ranges in the offset log for the same reason).
"""

from __future__ import annotations

import base64
import json
import os
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from cycloneml_tpu.sql.plan import Batch
from cycloneml_tpu.streaming.sources import Source

SCHEMA = ["key", "value", "topic", "partition", "offset", "timestamp"]


class KafkaSource(Source):
    schema = SCHEMA

    def __init__(self, topic: str,
                 bootstrap_servers: str = "localhost:9092",
                 consumer_factory: Optional[Callable] = None,
                 poll_timeout_ms: int = 200, decode: bool = True,
                 decode_key: bool = False):
        self.topic = topic
        self.poll_timeout_ms = poll_timeout_ms
        # per-FIELD contracts (the reference exposes key and value as
        # independently-castable binary): values decode by default (text
        # topics), keys stay opaque bytes by default (hashed ids etc.)
        self.decode = decode
        self.decode_key = decode_key
        if consumer_factory is not None:
            self._consumer = consumer_factory()
        else:
            try:
                from kafka import KafkaConsumer  # gated optional dep
            except ImportError as e:
                raise ImportError(
                    "KafkaSource needs the 'kafka-python' package (or pass "
                    "consumer_factory=); it is not bundled with "
                    "cycloneml_tpu") from e
            self._consumer = KafkaConsumer(
                topic, bootstrap_servers=bootstrap_servers,
                enable_auto_commit=False, auto_offset_reset="earliest")
        self._rows: List[tuple] = []  # replay buffer of consumed rows
        self._base = 0  # engine offset of _rows[0]
        self._log_dir: Optional[str] = None
        self._wal_fh = None  # append handle for the pending-row WAL
        # (topic, partition) -> next Kafka offset; string-encoded only at the
        # offsets.json boundary
        self._pp_committed: Dict[Tuple[str, int], int] = {}
        # next expected Kafka offset per partition over EVERYTHING buffered or
        # committed — the dedup filter that makes re-delivery (failed seek,
        # group-rebalance replay, auto_offset_reset=earliest) harmless, and
        # the counter that synthesizes offsets for records lacking one
        self._pp_next: Dict[Tuple[str, int], int] = {}

    # -- checkpoint persistence -------------------------------------------
    def set_log_dir(self, path: str) -> None:
        """Recover committed base + pending rows from a query checkpoint.

        ``offsets.json`` holds the state at the last commit (committed engine
        offset, per-partition next-Kafka-offset); ``wal.jsonl`` holds every
        consumed-but-uncommitted row. Loading both rebuilds ``_rows``/``_base``
        exactly as the previous instance had them; the consumer is then
        seeked past the recovered positions, and the per-partition dedup
        filter drops any re-delivered row even if the seek could not land.
        Idempotent: a second call only re-points the WAL (recovery state is
        loaded once — re-loading would double-append the replay buffer).
        """
        os.makedirs(path, exist_ok=True)
        wal_p = os.path.join(path, "wal.jsonl")
        if self._wal_fh is not None:
            self._wal_fh.close()
            self._wal_fh = None
        first = self._log_dir is None
        self._log_dir = path
        if not first:
            self._wal_fh = open(wal_p, "a", encoding="utf-8")
            return
        meta_p = os.path.join(path, "offsets.json")
        if os.path.exists(meta_p) and os.path.getsize(meta_p) > 0:
            with open(meta_p, encoding="utf-8") as fh:
                meta = json.load(fh)
            self._base = int(meta["base"])
            self._pp_committed = {_tp_from_str(k): int(v)
                                  for k, v in meta.get("partitions", {}).items()}
            self._pp_next.update(self._pp_committed)
        if os.path.exists(wal_p):
            with open(wal_p, encoding="utf-8") as fh:
                lines = fh.readlines()
            for i, line in enumerate(lines):
                if not line.strip():
                    continue
                try:
                    row = _row_from_json(json.loads(line))
                except ValueError:
                    if i == len(lines) - 1:
                        break  # torn final record from a crash mid-append
                    raise  # corruption mid-log is NOT survivable silently
                tp = (row[2], int(row[3]))
                if int(row[4]) < self._pp_committed.get(tp, 0):
                    # crash between meta write and WAL compaction left a
                    # committed row behind
                    continue
                self._rows.append(row)
                self._pp_next[tp] = max(self._pp_next.get(tp, 0),
                                        int(row[4]) + 1)
        self._wal_fh = open(wal_p, "a", encoding="utf-8")
        self._seek()

    def _seek(self) -> None:
        """Best-effort: point a real consumer at the recovered offsets.

        A subscribed kafka-python consumer has no partition assignment until
        its first poll, so one zero-timeout poll forces assignment first; its
        records go through the normal ingest path (the dedup filter drops
        anything already recovered). Failure is safe — re-delivered rows are
        deduped — seeking just avoids re-reading from the earliest offset.
        """
        if not self._pp_next or not hasattr(self._consumer, "seek"):
            return
        # the forced-assignment poll uses the NORMAL ingest path: decode/IO
        # errors must surface exactly as they would on any other poll — only
        # the seek itself is best-effort (the dedup filter covers its failure)
        self._ingest(self._consumer.poll(timeout_ms=0))
        try:
            from kafka import TopicPartition
            for (topic, part), off in list(self._pp_next.items()):
                self._consumer.seek(TopicPartition(topic, part), off)
        except Exception:
            pass  # fake/embedded consumers replay from their own state

    def close(self) -> None:
        if self._wal_fh is not None:
            self._wal_fh.close()
            self._wal_fh = None

    def __del__(self):  # best-effort: queries have no source-close hook yet
        try:
            self.close()
        except Exception:
            pass

    def _decode(self, v, enabled: bool, field: str):
        """An enabled field asserts text: its column type is then uniformly
        str. Binary fields keep uniform bytes — a per-message fallback would
        yield a content-dependent str/bytes mix that corrupts downstream
        deserializers."""
        if not (enabled and isinstance(v, bytes)):
            return v
        try:
            return v.decode()
        except UnicodeDecodeError as e:
            flag = "decode_key" if field == "key" else "decode"
            raise ValueError(
                f"topic {self.topic!r} carries non-UTF8 {field}s; construct "
                f"KafkaSource(..., {flag}=False) for binary data") from e

    def _poll(self) -> None:
        self._ingest(self._consumer.poll(timeout_ms=self.poll_timeout_ms))

    def _ingest(self, records) -> None:
        """Normalize, dedup, buffer and WAL a poll() result.

        Records lacking a real ``.offset`` get a synthesized per-partition
        monotonic one (so the recovery filter never misreads a default);
        records whose offset sits below the partition's next-expected
        position are re-deliveries and are dropped.
        """
        wrote = False
        try:
            for batch in records.values():
                for r in batch:
                    topic = getattr(r, "topic", self.topic)
                    part = int(getattr(r, "partition", 0))
                    tp = (topic, part)
                    off = getattr(r, "offset", None)
                    if off is None:
                        off = self._pp_next.get(tp, 0)
                    elif int(off) < self._pp_next.get(tp, 0):
                        continue  # already buffered or committed
                    row = (
                        self._decode(r.key, self.decode_key, "key"),
                        self._decode(r.value, self.decode, "value"),
                        topic, part, int(off),
                        getattr(r, "timestamp", 0),
                    )
                    # buffer + WAL per record, and only THEN mark seen: an
                    # exception on a later record in the same poll (decode
                    # error) must not strand earlier rows as
                    # seen-but-never-buffered
                    self._rows.append(row)
                    if self._wal_fh is not None:
                        self._wal_fh.write(json.dumps(_row_to_json(row)) + "\n")
                        wrote = True
                    self._pp_next[tp] = int(off) + 1
        finally:
            if wrote:
                self._wal_fh.flush()
                os.fsync(self._wal_fh.fileno())

    def latest_offset(self) -> int:
        self._poll()
        return self._base + len(self._rows)

    def get_batch(self, start: int, end: int) -> Batch:
        lo, hi = start - self._base, end - self._base
        rows = self._rows[max(0, lo):hi]
        cols = list(zip(*rows)) if rows else [[] for _ in SCHEMA]
        out: Batch = {}
        for name, vals in zip(SCHEMA, cols):
            if name in ("partition", "offset", "timestamp"):
                out[name] = np.array(vals, dtype=np.int64)  # empty-safe
            else:
                out[name] = np.array(vals, dtype=object)
        return out

    def commit(self, end: int) -> None:
        """Discard replay rows up to ``end`` and commit consumer offsets."""
        drop = end - self._base
        if drop > 0:
            for row in self._rows[:drop]:
                tp = (row[2], int(row[3]))
                self._pp_committed[tp] = max(
                    self._pp_committed.get(tp, 0), int(row[4]) + 1)
            self._rows = self._rows[drop:]
            self._base = end
            self._persist_commit()
        if hasattr(self._consumer, "commit"):
            try:
                self._consumer.commit()
            except Exception:
                pass  # commit is an optimization; replay covers recovery

    def _persist_commit(self) -> None:
        """Atomically rewrite offsets.json, then compact the WAL down to the
        still-pending rows. Order matters for crash safety: a crash between
        the two leaves committed rows in the WAL, which recovery tolerates
        (their Kafka offsets sit below the committed per-partition positions
        and set_log_dir filters them out)."""
        if self._log_dir is None:
            return
        meta_p = os.path.join(self._log_dir, "offsets.json")
        tmp = meta_p + ".tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump({"base": self._base,
                       "partitions": {_tp_to_str(k): v
                                      for k, v in self._pp_committed.items()}},
                      fh)
            fh.flush()
            os.fsync(fh.fileno())  # replace is only atomic if the tmp is durable
        os.replace(tmp, meta_p)
        wal_p = os.path.join(self._log_dir, "wal.jsonl")
        if self._wal_fh is not None:
            self._wal_fh.close()
        wtmp = wal_p + ".tmp"
        with open(wtmp, "w", encoding="utf-8") as fh:
            for row in self._rows:
                fh.write(json.dumps(_row_to_json(row)) + "\n")
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(wtmp, wal_p)
        try:  # make both renames themselves durable
            dfd = os.open(self._log_dir, os.O_RDONLY)
            os.fsync(dfd)
            os.close(dfd)
        except OSError:
            pass  # directory fsync unsupported on this platform
        self._wal_fh = open(wal_p, "a", encoding="utf-8")


def _tp_to_str(tp: Tuple[str, int]) -> str:
    """offsets.json key encoding; partition LAST so rpartition('-') inverts
    it even for topic names containing '-'."""
    return f"{tp[0]}-{tp[1]}"


def _tp_from_str(s: str) -> Tuple[str, int]:
    topic, _, part = s.rpartition("-")
    return topic, int(part)


def _row_to_json(row: tuple) -> list:
    """JSON-safe row encoding; bytes fields round-trip via base64 tags."""
    out = []
    for v in row:
        if isinstance(v, bytes):
            out.append({"b64": base64.b64encode(v).decode("ascii")})
        elif isinstance(v, (np.integer, np.floating)):
            out.append(v.item())
        else:
            out.append(v)
    return out


def _row_from_json(vals: list) -> tuple:
    return tuple(base64.b64decode(v["b64"])
                 if isinstance(v, dict) and "b64" in v else v
                 for v in vals)
