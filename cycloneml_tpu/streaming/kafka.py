"""Kafka streaming source.

Analog of the reference's kafka-0-10-sql connector (ref: external/
kafka-0-10-sql — KafkaSource/KafkaMicroBatchStream reading (key, value,
topic, partition, offset, timestamp) rows with per-partition offset ranges).
The kafka client library is optional: pass ``consumer_factory`` for tests or
embedded brokers; without it the constructor needs ``kafka-python``
installed (gated import, not bundled — the reference ships its connector as
a separate artifact for the same reason).

Offsets: the engine's single monotonically-increasing int offset maps to a
row count; per-partition Kafka offsets are tracked internally and snapshots
of consumed-but-uncommitted rows are buffered so ``get_batch`` stays
replayable until ``commit`` (the Source contract).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

from cycloneml_tpu.sql.plan import Batch
from cycloneml_tpu.streaming.sources import Source

SCHEMA = ["key", "value", "topic", "partition", "offset", "timestamp"]


class KafkaSource(Source):
    schema = SCHEMA

    def __init__(self, topic: str,
                 bootstrap_servers: str = "localhost:9092",
                 consumer_factory: Optional[Callable] = None,
                 poll_timeout_ms: int = 200, decode: bool = True,
                 decode_key: bool = False):
        self.topic = topic
        self.poll_timeout_ms = poll_timeout_ms
        # per-FIELD contracts (the reference exposes key and value as
        # independently-castable binary): values decode by default (text
        # topics), keys stay opaque bytes by default (hashed ids etc.)
        self.decode = decode
        self.decode_key = decode_key
        if consumer_factory is not None:
            self._consumer = consumer_factory()
        else:
            try:
                from kafka import KafkaConsumer  # gated optional dep
            except ImportError as e:
                raise ImportError(
                    "KafkaSource needs the 'kafka-python' package (or pass "
                    "consumer_factory=); it is not bundled with "
                    "cycloneml_tpu") from e
            self._consumer = KafkaConsumer(
                topic, bootstrap_servers=bootstrap_servers,
                enable_auto_commit=False, auto_offset_reset="earliest")
        self._rows: List[tuple] = []  # replay buffer of consumed rows
        self._base = 0  # engine offset of _rows[0]

    def _decode(self, v, enabled: bool, field: str):
        """An enabled field asserts text: its column type is then uniformly
        str. Binary fields keep uniform bytes — a per-message fallback would
        yield a content-dependent str/bytes mix that corrupts downstream
        deserializers."""
        if not (enabled and isinstance(v, bytes)):
            return v
        try:
            return v.decode()
        except UnicodeDecodeError as e:
            flag = "decode_key" if field == "key" else "decode"
            raise ValueError(
                f"topic {self.topic!r} carries non-UTF8 {field}s; construct "
                f"KafkaSource(..., {flag}=False) for binary data") from e

    def _poll(self) -> None:
        records = self._consumer.poll(timeout_ms=self.poll_timeout_ms)
        for batch in records.values():
            for r in batch:
                self._rows.append((
                    self._decode(r.key, self.decode_key, "key"),
                    self._decode(r.value, self.decode, "value"),
                    getattr(r, "topic", self.topic),
                    getattr(r, "partition", 0),
                    getattr(r, "offset", 0),
                    getattr(r, "timestamp", 0),
                ))

    def latest_offset(self) -> int:
        self._poll()
        return self._base + len(self._rows)

    def get_batch(self, start: int, end: int) -> Batch:
        lo, hi = start - self._base, end - self._base
        rows = self._rows[max(0, lo):hi]
        cols = list(zip(*rows)) if rows else [[] for _ in SCHEMA]
        out: Batch = {}
        for name, vals in zip(SCHEMA, cols):
            if name in ("partition", "offset", "timestamp"):
                out[name] = np.array(vals, dtype=np.int64)  # empty-safe
            else:
                out[name] = np.array(vals, dtype=object)
        return out

    def commit(self, end: int) -> None:
        """Discard replay rows up to ``end`` and commit consumer offsets."""
        drop = end - self._base
        if drop > 0:
            self._rows = self._rows[drop:]
            self._base = end
        if hasattr(self._consumer, "commit"):
            try:
                self._consumer.commit()
            except Exception:
                pass  # commit is an optimization; replay covers recovery
