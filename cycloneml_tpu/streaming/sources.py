"""Streaming sources.

Analog of the reference's Source / MicroBatchStream connectors (ref:
sql/core/.../execution/streaming/Source.scala, memory stream
``MemoryStream`` in sources/memory.scala, FileStreamSource.scala,
RateStreamProvider). A source exposes a monotonically increasing offset;
``get_batch(start, end)`` must be replayable — the recovery contract that
lets the engine re-run an uncommitted batch after a crash.
"""

from __future__ import annotations

import glob
import os
import threading
import time
from typing import Dict, List, Optional

import numpy as np

from cycloneml_tpu.sql.plan import Batch, LogicalPlan


def _empty_like(schema: List[str]) -> Batch:
    return {c: np.array([], dtype=object) for c in schema}


def _concat_batches(parts: List[Batch], schema: List[str]) -> Batch:
    """Column-wise concat of non-empty batches (dtype coercion via the plan
    layer's _concat so mixed int/float/object chunks behave like Union)."""
    from cycloneml_tpu.sql.plan import _concat
    parts = [p for p in parts if p and len(next(iter(p.values()))) > 0]
    if not parts:
        return _empty_like(schema)
    return {c: _concat([np.asarray(p[c]) for p in parts]) for c in schema}


class Source:
    schema: List[str] = []

    def latest_offset(self) -> int:
        raise NotImplementedError

    def get_batch(self, start: int, end: int) -> Batch:
        """Rows in offset range (start, end] — replayable."""
        raise NotImplementedError

    def commit(self, end: int) -> None:
        """Source may discard data up to ``end`` (≈ Source.commit)."""


class StreamingScan(LogicalPlan):
    """Leaf plan node standing for 'the current micro-batch of a source'.

    The reference swaps a StreamingExecutionRelation for a per-batch
    LocalRelation during logical planning (MicroBatchExecution.scala:39);
    here the engine assigns ``current`` before executing the plan.
    """

    def __init__(self, source: Source, name: str = "streaming"):
        self.children = []
        self.source = source
        self.name = name
        self.current: Optional[Batch] = None

    def output(self):
        return list(self.source.schema)

    def execute(self):
        if self.current is None:
            raise RuntimeError(
                "streaming plan executed outside a micro-batch; use "
                "write_stream.start() (or .to_batch() for a snapshot)")
        return self.current

    def __repr__(self):
        return f"StreamingScan({self.name})"


class MemoryStream(Source):
    """Driver-held source for tests (≈ MemoryStream — the backbone of the
    reference's StreamTest AddData harness). Offset = number of chunks."""

    def __init__(self, schema: List[str]):
        self.schema = list(schema)
        self._chunks: List[Batch] = []
        self._lock = threading.Lock()

    def add_data(self, data=None, **cols) -> int:
        """Append a chunk (columnar dict or kwargs); returns the new offset."""
        chunk = dict(data) if data is not None else {}
        chunk.update(cols)
        batch = {c: np.asarray(chunk[c]) for c in self.schema}
        with self._lock:
            self._chunks.append(batch)
            return len(self._chunks)

    def latest_offset(self) -> int:
        with self._lock:
            return len(self._chunks)

    def get_batch(self, start: int, end: int) -> Batch:
        with self._lock:
            return _concat_batches(self._chunks[start:end], self.schema)

    def to_df(self, session=None):
        from cycloneml_tpu.sql.dataframe import DataFrame
        return DataFrame(StreamingScan(self, "memory"), session)


class FileStreamSource(Source):
    """Directory-watching source (ref: FileStreamSource.scala — offsets are
    positions in the sorted log of files ever seen). Supports csv (numeric,
    header names columns) and single-column text."""

    def __init__(self, path: str, fmt: str = "csv", pattern: str = "*",
                 header: bool = True, delimiter: str = ",",
                 schema: Optional[List[str]] = None):
        self.path = path
        self.fmt = fmt
        self.pattern = pattern
        self.header = header
        self.delimiter = delimiter
        self._seen: List[str] = []
        self._log_path: Optional[str] = None
        # explicit schema lets a query start on a still-empty directory
        self.schema = list(schema) if schema else self._infer_schema()

    def set_log_dir(self, path: str) -> None:
        """Persist the seen-file log in the query checkpoint so logged offsets
        stay replayable across restarts (ref: FileStreamSource.scala keeps its
        file log under <checkpoint>/sources/<id> for exactly this reason —
        directory listing order is not stable when files keep arriving)."""
        os.makedirs(path, exist_ok=True)
        self._log_path = os.path.join(path, "files")
        if os.path.exists(self._log_path):
            with open(self._log_path, encoding="utf-8") as fh:
                self._seen = [ln.rstrip("\n") for ln in fh if ln.strip()]

    def _list_files(self) -> List[str]:
        return sorted(glob.glob(os.path.join(self.path, self.pattern)))

    def _infer_schema(self) -> List[str]:
        if self.fmt == "text":
            return ["value"]
        files = self._list_files()
        if not files:
            raise ValueError(f"file source needs at least one file in "
                             f"{self.path!r} to infer a schema")
        with open(files[0]) as fh:
            head = fh.readline().rstrip("\n")
        if self.header:
            return [c.strip() for c in head.split(self.delimiter)]
        return [f"_c{i}" for i in range(len(head.split(self.delimiter)))]

    def _refresh(self) -> None:
        known = set(self._seen)
        new = [f for f in self._list_files() if f not in known]
        if not new:
            return
        self._seen.extend(new)
        if self._log_path is not None:
            tmp = self._log_path + ".tmp"
            with open(tmp, "w", encoding="utf-8") as fh:
                fh.write("\n".join(self._seen) + "\n")
            os.replace(tmp, self._log_path)

    def latest_offset(self) -> int:
        self._refresh()
        return len(self._seen)

    def _read_file(self, f: str) -> Batch:
        if self.fmt == "text":
            with open(f) as fh:
                lines = [ln.rstrip("\n") for ln in fh if ln.strip()]
            return {"value": np.array(lines, dtype=object)}
        data = np.loadtxt(f, delimiter=self.delimiter,
                          skiprows=1 if self.header else 0, ndmin=2)
        if data.size == 0:
            return _empty_like(self.schema)
        return {c: data[:, i] for i, c in enumerate(self.schema)}

    def get_batch(self, start: int, end: int) -> Batch:
        self._refresh()
        return _concat_batches([self._read_file(f) for f in self._seen[start:end]],
                               self.schema)


class RateSource(Source):
    """Synthetic load source (ref: RateStreamProvider): ``rows_per_second``
    rows with monotonically increasing ``value`` and a ``timestamp``."""

    schema = ["timestamp", "value"]

    def __init__(self, rows_per_second: int = 10):
        self.rows_per_second = rows_per_second
        self._start = time.time()

    def latest_offset(self) -> int:
        return int((time.time() - self._start) * self.rows_per_second)

    def get_batch(self, start: int, end: int) -> Batch:
        values = np.arange(start, end, dtype=np.int64)
        ts = self._start + values / float(self.rows_per_second)
        return {"timestamp": ts, "value": values}
