"""Streaming sinks.

Analog of the reference's Sink connectors (ref: sql/core/.../execution/
streaming/Sink.scala, memory.scala MemorySink, FileStreamSink.scala,
console.scala, ForeachBatchSink.scala). ``add_batch(batch_id, batch)`` must
be idempotent per batch id — together with the commit log this closes the
exactly-once loop on restart.
"""

from __future__ import annotations

import json
import os
from typing import Callable, Dict, List, Optional

import numpy as np

from cycloneml_tpu.sql.plan import Batch


class Sink:
    def add_batch(self, batch_id: int, batch: Batch, mode: str) -> None:
        raise NotImplementedError


class MemorySink(Sink):
    """Collects output rows on the driver (≈ MemorySink for CheckAnswer)."""

    def __init__(self):
        self._batches: Dict[int, Batch] = {}
        self._order: List[int] = []

    def add_batch(self, batch_id: int, batch: Batch, mode: str) -> None:
        if batch_id in self._batches:
            return  # replayed batch after recovery — idempotent
        if mode == "complete":
            self._batches.clear()
            self._order.clear()
        self._batches[batch_id] = batch
        self._order.append(batch_id)

    def to_batch(self, schema: Optional[List[str]] = None) -> Batch:
        from cycloneml_tpu.streaming.sources import _concat_batches
        parts = [self._batches[b] for b in self._order]
        live = [p for p in parts if p and len(next(iter(p.values()))) > 0]
        if not live:
            return {c: np.array([]) for c in (schema or [])}
        return _concat_batches(live, list(live[0]))

    def rows(self) -> List[tuple]:
        batch = self.to_batch()
        cols = list(batch)
        n = len(batch[cols[0]]) if cols else 0
        return [tuple(batch[c][i] for c in cols) for i in range(n)]

    def clear(self) -> None:
        self._batches.clear()
        self._order.clear()


class FileSink(Sink):
    """Part-file-per-batch writer with a manifest log (ref:
    FileStreamSink.scala's _spark_metadata commit protocol — readers trust
    only manifested files, making rewrites after crash invisible)."""

    def __init__(self, path: str, fmt: str = "csv"):
        self.path = path
        self.fmt = fmt
        self.manifest_dir = os.path.join(path, "_manifest")
        os.makedirs(self.manifest_dir, exist_ok=True)

    def add_batch(self, batch_id: int, batch: Batch, mode: str) -> None:
        marker = os.path.join(self.manifest_dir, str(batch_id))
        if os.path.exists(marker):
            return
        cols = list(batch)
        n = len(batch[cols[0]]) if cols else 0
        part = os.path.join(self.path, f"part-{batch_id:05d}.{self.fmt}")
        with open(part + ".tmp", "w", encoding="utf-8") as fh:
            if self.fmt == "json":
                for i in range(n):
                    fh.write(json.dumps(
                        {c: _py(batch[c][i]) for c in cols}) + "\n")
            else:
                fh.write(",".join(cols) + "\n")
                for i in range(n):
                    fh.write(",".join(str(_py(batch[c][i])) for c in cols) + "\n")
        os.replace(part + ".tmp", part)
        with open(marker, "w") as fh:
            fh.write(part)

    def committed_files(self) -> List[str]:
        out = []
        for name in sorted(os.listdir(self.manifest_dir), key=lambda s: int(s)):
            with open(os.path.join(self.manifest_dir, name)) as fh:
                out.append(fh.read())
        return out


class ForeachBatchSink(Sink):
    """(ref: ForeachBatchSink.scala) — hands (DataFrame, batch_id) to user
    code; the user owns idempotence, as in the reference."""

    def __init__(self, fn: Callable, session=None):
        self.fn = fn
        self.session = session

    def add_batch(self, batch_id: int, batch: Batch, mode: str) -> None:
        from cycloneml_tpu.sql.dataframe import DataFrame
        from cycloneml_tpu.sql.plan import Scan
        self.fn(DataFrame(Scan(batch, f"batch-{batch_id}"), self.session),
                batch_id)


class ConsoleSink(Sink):
    def __init__(self, num_rows: int = 20):
        self.num_rows = num_rows

    def add_batch(self, batch_id: int, batch: Batch, mode: str) -> None:
        from cycloneml_tpu.sql.dataframe import DataFrame
        from cycloneml_tpu.sql.plan import Scan
        print(f"-------------------------------------------\n"
              f"Batch: {batch_id}\n"
              f"-------------------------------------------")
        DataFrame(Scan(batch, "console")).show(self.num_rows)


def _py(v):
    if isinstance(v, np.generic):
        return v.item()
    return v
