"""DStream-style discretized streams.

Analog of the reference's legacy streaming layer (ref: streaming/.../
StreamingContext.scala:64, dstream/DStream.scala:63, scheduler/JobGenerator +
JobScheduler). A clock discretizes input into per-interval batches; each
batch is a ``PartitionedDataset`` (the RDD analog), and DStream operators are
lazy per-batch transformations plus windowed/stateful variants.

Push-based ingestion exists too: :class:`Receiver` (ref Receiver.scala:43)
runs user code in its own thread storing records into
:class:`ReceiverInputDStream`, optionally write-ahead-logged record-by-
record (:class:`WriteAheadLog` ≈ ReceivedBlockTracker + FileBasedWAL) so a
crashed driver replays unprocessed records; ``socket_text_stream`` is the
classic concrete receiver. Structured streaming (query.py) remains the
primary engine; this surface exists for parity with the reference's
DStream programs.
"""

from __future__ import annotations

import glob
import os
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class StreamingContext:
    """(ref StreamingContext.scala:64) — owns the batch clock and inputs."""

    def __init__(self, ctx, batch_duration: float = 1.0):
        self.ctx = ctx
        self.batch_duration = batch_duration
        self._inputs: List["InputDStream"] = []
        self._outputs: List[Tuple["DStream", Callable]] = []
        self._thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._started = False
        self.batch_time = 0
        self._lock = threading.Lock()
        self._remember = 100  # intervals of history to retain

    def remember(self, intervals: int) -> None:
        """Retain at least this many intervals (ref: DStream.remember —
        normally derived automatically from the widest window)."""
        self._remember = max(self._remember, intervals)

    # -- input streams ---------------------------------------------------------
    def queue_stream(self, batches: List[List[Any]],
                     default: Optional[List[Any]] = None) -> "DStream":
        """(ref queueStream — the standard test input)"""
        s = QueueInputDStream(self, list(batches), default)
        self._inputs.append(s)
        return s

    def text_file_stream(self, directory: str, pattern: str = "*") -> "DStream":
        """(ref textFileStream): new files each interval become the batch."""
        s = FileInputDStream(self, directory, pattern)
        self._inputs.append(s)
        return s

    def receiver_stream(self, receiver: "Receiver",
                        wal_dir: Optional[str] = None) -> "DStream":
        """(ref receiverStream): push-based input via a Receiver; records
        are write-ahead-logged before visibility when ``wal_dir`` is set."""
        s = ReceiverInputDStream(self, receiver, wal_dir)
        self._inputs.append(s)
        return s

    def socket_text_stream(self, host: str, port: int,
                           wal_dir: Optional[str] = None) -> "DStream":
        """(ref socketTextStream)"""
        return self.receiver_stream(SocketReceiver(host, port), wal_dir)

    # -- lifecycle (ref JobGenerator clock + JobScheduler) ---------------------
    def start(self) -> None:
        if self._started:
            return
        self._stop_evt.clear()  # allow stop() → start() restart
        self._started = True
        for s in self._inputs:  # ReceiverTracker.start analog
            if isinstance(s, ReceiverInputDStream):
                s.start_receiver()
        self._thread = threading.Thread(target=self._loop,
                                        name="cyclone-dstream-clock",
                                        daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop_evt.wait(self.batch_duration):
            try:
                self.run_one_interval()
            except Exception:
                logger.exception("batch generation failed")

    def run_one_interval(self) -> None:
        """Generate and process one interval's batches (tests drive this
        directly for determinism, like the reference's ManualClock)."""
        with self._lock:
            t = self.batch_time
            self.batch_time += 1
            for s in self._inputs:
                s.compute_batch(t)
            for stream, action in self._outputs:
                batch = stream.batch_for(t)
                if batch is not None:  # None = no RDD this interval
                    action(batch, t)
            for s in self._inputs:
                if hasattr(s, "post_interval"):
                    s.post_interval(t)  # outputs done: WAL may truncate
                s.gc(t)

    def stop(self) -> None:
        self._stop_evt.set()
        for s in self._inputs:
            if isinstance(s, ReceiverInputDStream):
                s.stop_receiver()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._started = False

    def await_termination(self, timeout: Optional[float] = None) -> None:
        if self._thread is not None:
            self._thread.join(timeout)

    def _register_output(self, stream: "DStream", action: Callable) -> None:
        self._outputs.append((stream, action))


class DStream:
    """Lazy per-interval transformation chain (ref: DStream.scala:63).
    ``batch_for(t)`` materializes interval ``t`` as a list of records."""

    def __init__(self, ssc: StreamingContext,
                 compute: Callable[[int], List[Any]]):
        self.ssc = ssc
        self._compute = compute
        self._cache: Dict[int, List[Any]] = {}

    def batch_for(self, t: int) -> List[Any]:
        if t not in self._cache:
            self._cache[t] = self._compute(t)
            # bound the memory of the per-interval cache (ref: DStream
            # rememberDuration, derived from the widest registered window)
            horizon = t - self.ssc._remember
            for old in [k for k in self._cache if k < horizon]:
                del self._cache[old]
        return self._cache[t]

    # -- stateless transformations --------------------------------------------
    def _derive(self, fn: Callable[[List[Any]], List[Any]]) -> "DStream":
        """``None`` batches mean 'no RDD this interval' (a slid window off
        its slide boundary) and propagate untouched — downstream operators
        and output actions must not observe a fabricated empty batch."""
        parent = self
        return DStream(self.ssc,
                       lambda t: (None if (b := parent.batch_for(t)) is None
                                  else fn(b)))

    def map(self, f: Callable) -> "DStream":
        return self._derive(lambda b: [f(x) for x in b])

    def flat_map(self, f: Callable) -> "DStream":
        return self._derive(lambda b: [y for x in b for y in f(x)])

    def filter(self, f: Callable) -> "DStream":
        return self._derive(lambda b: [x for x in b if f(x)])

    def count(self) -> "DStream":
        return self._derive(lambda b: [len(b)])

    def reduce(self, f: Callable) -> "DStream":
        import functools
        return self._derive(
            lambda b: [functools.reduce(f, b)] if b else [])

    def reduce_by_key(self, f: Callable) -> "DStream":
        def agg(b):
            out: Dict[Any, Any] = {}
            for k, v in b:
                out[k] = f(out[k], v) if k in out else v
            return list(out.items())
        return self._derive(agg)

    def union(self, other: "DStream") -> "DStream":
        parent = self

        def compute(t):
            a, b = parent.batch_for(t), other.batch_for(t)
            if a is None and b is None:
                return None
            return (a or []) + (b or [])
        return DStream(self.ssc, compute)

    def transform(self, f: Callable[[List[Any]], List[Any]]) -> "DStream":
        """(ref DStream.transform — arbitrary per-batch RDD work). ``f``
        receives a PartitionedDataset and returns one (or a list)."""
        parent = self
        ssc = self.ssc

        def compute(t):
            b = parent.batch_for(t)
            if b is None:
                return None
            out = f(ssc.ctx.parallelize(b))
            return out.collect() if hasattr(out, "collect") else list(out)
        return DStream(ssc, compute)

    # -- windowed transformations (ref: dstream/WindowedDStream.scala) --------
    def window(self, window_length: int, slide: int = 1) -> "DStream":
        """Window sizes are in INTERVALS (the reference validates durations
        are multiples of the batch duration; integers make that structural)."""
        parent = self
        self.ssc.remember(window_length + 1)  # widest window sets retention

        def compute(t):
            if slide > 1 and (t + 1) % slide != 0:
                return None  # no RDD at off-slide intervals (ref semantics)
            out: List[Any] = []
            for i in range(max(0, t - window_length + 1), t + 1):
                b = parent.batch_for(i)
                if b is not None:  # parent itself may be a slid window
                    out.extend(b)
            return out
        return DStream(self.ssc, compute)

    def count_by_window(self, window_length: int, slide: int = 1) -> "DStream":
        return self.window(window_length, slide).count()

    def reduce_by_key_and_window(self, f: Callable, window_length: int,
                                 slide: int = 1) -> "DStream":
        return self.window(window_length, slide).reduce_by_key(f)

    # -- stateful (ref: dstream/StateDStream.scala updateStateByKey) ----------
    def update_state_by_key(self, update: Callable[[List[Any], Any], Any]
                            ) -> "DStream":
        """``update(new_values, old_state) -> new_state`` per key; returning
        None drops the key. State is carried across intervals."""
        parent = self
        state: Dict[Any, Any] = {}
        last_t = [-1]

        def compute(t):
            if t <= last_t[0]:  # replays serve the memoized snapshot
                return list(state.items())
            last_t[0] = t
            grouped: Dict[Any, List[Any]] = {}
            for k, v in parent.batch_for(t) or []:
                grouped.setdefault(k, []).append(v)
            for k in set(state) | set(grouped):
                new_state = update(grouped.get(k, []), state.get(k))
                if new_state is None:
                    state.pop(k, None)
                else:
                    state[k] = new_state
            return list(state.items())
        return DStream(self.ssc, compute)

    # -- output operations (ref: DStream.foreachRDD / print) ------------------
    def foreach_rdd(self, f: Callable) -> None:
        ssc = self.ssc

        def action(batch, t):
            f(ssc.ctx.parallelize(batch), t)
        ssc._register_output(self, action)

    def pprint(self, num: int = 10) -> None:
        def action(batch, t):
            print(f"-------------------------------------------\n"
                  f"Time: {t}\n"
                  f"-------------------------------------------")
            for x in batch[:num]:
                print(x)
        self.ssc._register_output(self, action)

    def collect_to(self, sink: List) -> None:
        """Test helper: append (t, batch) tuples to ``sink``."""
        self.ssc._register_output(self, lambda b, t: sink.append((t, list(b))))


class InputDStream(DStream):
    def __init__(self, ssc: StreamingContext):
        super().__init__(ssc, self._input_batch)
        self._batches: Dict[int, List[Any]] = {}

    def _input_batch(self, t: int) -> List[Any]:
        return self._batches.get(t, [])

    def compute_batch(self, t: int) -> None:
        raise NotImplementedError

    def gc(self, t: int) -> None:
        horizon = t - self.ssc._remember
        for old in [k for k in self._batches if k < horizon]:
            del self._batches[old]


class QueueInputDStream(InputDStream):
    def __init__(self, ssc, queue: List[List[Any]],
                 default: Optional[List[Any]]):
        super().__init__(ssc)
        self._queue = queue
        self._default = default or []

    def push(self, batch: List[Any]) -> None:
        self._queue.append(batch)

    def compute_batch(self, t: int) -> None:
        self._batches[t] = (self._queue.pop(0) if self._queue
                            else list(self._default))


class FileInputDStream(InputDStream):
    def __init__(self, ssc, directory: str, pattern: str):
        super().__init__(ssc)
        self.directory = directory
        self.pattern = pattern
        self._seen: set = set(glob.glob(os.path.join(directory, pattern)))

    def compute_batch(self, t: int) -> None:
        now = sorted(glob.glob(os.path.join(self.directory, self.pattern)))
        lines: List[str] = []
        for f in now:
            if f not in self._seen:
                self._seen.add(f)
                with open(f, encoding="utf-8") as fh:
                    lines.extend(ln.rstrip("\n") for ln in fh if ln.strip())
        self._batches[t] = lines


# -- receivers + write-ahead log ------------------------------------------------

class Receiver:
    """Push-based ingestion endpoint (ref: streaming/receiver/Receiver.scala:43
    — user code runs on_start in its own thread and calls ``store`` for each
    arriving record; the supervisor buffers records into blocks).

    Subclass and implement ``on_start`` (spawn whatever reads your source and
    calls ``self.store(record)``) and optionally ``on_stop``.
    """

    def __init__(self):
        self._supervisor: Optional["ReceiverInputDStream"] = None
        self._stopped = threading.Event()

    def store(self, record: Any) -> None:
        if self._supervisor is not None:
            self._supervisor._store(record)

    def is_stopped(self) -> bool:
        return self._stopped.is_set()

    def on_start(self) -> None:
        raise NotImplementedError

    def on_stop(self) -> None:
        pass


class WriteAheadLog:
    """Record WAL (ref: streaming/util/FileBasedWriteAheadLog.scala:55 via
    ReceivedBlockTracker): stored records append (compressed with the
    native codec, flushed per record, **fsynced at block boundaries** —
    the reference also logs at block granularity) so a crashed driver
    replays unconsumed records on restart. On open, a torn tail from a
    crash mid-append is TRUNCATED before new appends (appending after
    garbage would strand everything written later). ``mark_consumed``
    advances a durable prefix counter; once consumption passes a
    threshold the log compacts to just the live suffix."""

    COMPACT_MIN = 4096

    def __init__(self, path: str):
        import struct as _struct
        from cycloneml_tpu.native.host import CompressionCodec
        self._struct = _struct
        self._codec = CompressionCodec()
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._consumed = 0  # records already folded into processed batches
        marker = path + ".consumed"
        if os.path.exists(marker):
            with open(marker, encoding="utf-8") as fh:
                self._consumed = int(fh.read().strip() or 0)
        self._count, valid_bytes = self._scan()
        if os.path.exists(path):
            with open(path, "r+b") as fh:
                fh.truncate(valid_bytes)  # drop any torn tail BEFORE append
        self._fh = open(path, "ab")

    def _scan(self):
        """(record count, byte offset of the last valid record boundary)."""
        import pickle
        from cycloneml_tpu.native.host import CompressionCodec
        count, pos = 0, 0
        if not os.path.exists(self.path):
            return 0, 0
        with open(self.path, "rb") as fh:
            while True:
                hdr = fh.read(4)
                if len(hdr) < 4:
                    break
                (n,) = self._struct.unpack("<I", hdr)
                blob = fh.read(n)
                if len(blob) < n:
                    break
                try:
                    pickle.loads(CompressionCodec.decompress(blob))
                except Exception:
                    break
                count += 1
                pos += 4 + n
        return count, pos

    def append(self, record: Any) -> None:
        import pickle
        blob = self._codec.compress(
            pickle.dumps(record, protocol=pickle.HIGHEST_PROTOCOL))
        self._fh.write(self._struct.pack("<I", len(blob)))
        self._fh.write(blob)
        self._fh.flush()  # reaches the OS; fsync happens per block
        self._count += 1

    def sync(self) -> None:
        """Durability point: called at block rotation, before the block
        becomes visible to batch generation."""
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def recover(self) -> List[Any]:
        """Records appended but not yet marked consumed (torn tails from a
        crash mid-append are ignored, standard WAL practice)."""
        import pickle
        from cycloneml_tpu.native.host import CompressionCodec
        out: List[Any] = []
        if not os.path.exists(self.path):
            return out
        with open(self.path, "rb") as fh:
            i = 0
            while True:
                hdr = fh.read(4)
                if len(hdr) < 4:
                    break
                (n,) = self._struct.unpack("<I", hdr)
                blob = fh.read(n)
                if len(blob) < n:
                    break  # torn tail
                try:
                    rec = pickle.loads(CompressionCodec.decompress(blob))
                except Exception:
                    break
                if i >= self._consumed:
                    out.append(rec)
                i += 1
        return out

    def mark_consumed(self, n_more: int) -> None:
        self._consumed += n_more
        tmp = self.path + ".consumed.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(str(self._consumed))
        os.replace(tmp, self.path + ".consumed")
        if (self._consumed >= self.COMPACT_MIN
                and self._consumed * 2 >= self._count):
            self._compact()

    def _compact(self) -> None:
        """Rewrite the log to just the unconsumed suffix (the 'clean' of
        FileBasedWriteAheadLog — without it the log grows forever)."""
        import pickle
        live = self.recover()
        self._fh.close()
        tmp = self.path + ".compact"
        with open(tmp, "wb") as fh:
            for rec in live:
                blob = self._codec.compress(
                    pickle.dumps(rec, protocol=pickle.HIGHEST_PROTOCOL))
                fh.write(self._struct.pack("<I", len(blob)))
                fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, self.path)
        self._consumed = 0
        self._count = len(live)
        ctmp = self.path + ".consumed.tmp"
        with open(ctmp, "w", encoding="utf-8") as fh:
            fh.write("0")
        os.replace(ctmp, self.path + ".consumed")
        self._fh = open(self.path, "ab")

    def close(self) -> None:
        try:
            self.sync()
        except (OSError, ValueError):
            pass
        self._fh.close()


class ReceiverInputDStream(InputDStream):
    """Receiver-fed input stream (ref: ReceiverInputDStream.scala:41 +
    ReceiverTracker/ReceiverSupervisor): the receiver thread stores records
    into the current buffer (each WAL'd first when ``wal_dir`` is set);
    every interval rotates the buffer into that interval's batch. On
    construction with an existing WAL, unconsumed records become the first
    batch — driver-crash recovery without re-asking the source."""

    def __init__(self, ssc: StreamingContext, receiver: Receiver,
                 wal_dir: Optional[str] = None):
        super().__init__(ssc)
        self.receiver = receiver
        receiver._supervisor = self
        self._buffer: List[Any] = []
        self._consume_queue = []
        self._buf_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        self._wal: Optional[WriteAheadLog] = None
        if wal_dir:
            self._wal = WriteAheadLog(os.path.join(wal_dir, "received.wal"))
            recovered = self._wal.recover()
            if recovered:
                self._buffer.extend(recovered)
                logger.info("receiver WAL recovered %d records",
                            len(recovered))

    def _store(self, record: Any) -> None:
        with self._buf_lock:
            if self._wal is not None:
                # flushed per record, fsynced at block rotation (the
                # reference logs at block granularity too)
                self._wal.append(record)
            self._buffer.append(record)

    def start_receiver(self) -> None:
        if self._thread is None:
            self.receiver._stopped.clear()  # stop() -> start() restart
            if self._wal is not None and self._wal._fh.closed:
                self._wal = WriteAheadLog(self._wal.path)
            self._thread = threading.Thread(
                target=self._run_receiver,
                name=f"cyclone-receiver-{type(self.receiver).__name__}",
                daemon=True)
            self._thread.start()

    def _run_receiver(self) -> None:
        try:
            self.receiver.on_start()
        except Exception:
            logger.exception("receiver failed")

    def stop_receiver(self) -> None:
        self.receiver._stopped.set()
        try:
            self.receiver.on_stop()
        except Exception:
            logger.exception("receiver on_stop failed")
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if self._wal is not None:
            self._wal.close()

    def compute_batch(self, t: int) -> None:
        with self._buf_lock:
            batch, self._buffer = self._buffer, []
            if self._wal is not None and batch:
                self._wal.sync()  # block boundary: durable before visible
        self._batches[t] = batch
        if self._wal is not None and batch:
            # consumed-marking is DEFERRED to post_interval: marking here
            # (before the interval's output actions run) would let a crash
            # mid-processing lose the records the WAL exists to protect
            self._consume_queue.append([t, len(batch), False])

    # [interval, n_records, outputs_done] in WAL order; consumption is a
    # PREFIX counter, so an interval whose outputs FAILED must block the
    # consumption of every later interval — marking out of order would
    # skip the failed interval's records and lose them on restart
    _consume_queue: List[list]

    def post_interval(self, t: int) -> None:
        for entry in self._consume_queue:
            if entry[0] == t:
                entry[2] = True
                break
        while self._consume_queue and self._consume_queue[0][2]:
            _, n, _ = self._consume_queue.pop(0)
            if self._wal is not None:
                self._wal.mark_consumed(n)


class SocketReceiver(Receiver):
    """The classic socketTextStream receiver (ref: SocketReceiver in
    SocketInputDStream.scala:58): lines from a TCP connection."""

    def __init__(self, host: str, port: int):
        super().__init__()
        self.host, self.port = host, port

    def on_start(self) -> None:
        import socket
        self._sock = socket.create_connection((self.host, self.port))
        try:
            fh = self._sock.makefile("r", encoding="utf-8", errors="replace")
            for line in fh:
                if self.is_stopped():
                    return
                line = line.rstrip("\n")
                if line:
                    self.store(line)
        except OSError:
            pass  # on_stop closed the socket to unblock this read
        finally:
            try:
                self._sock.close()
            except OSError:
                pass

    def on_stop(self) -> None:
        sock = getattr(self, "_sock", None)
        if sock is not None:
            try:
                sock.close()  # unblocks the blocking readline
            except OSError:
                pass
