"""Structured streaming: incrementalized execution of SQL plans.

TPU-native analog of the reference's structured-streaming engine
(ref: sql/core/.../execution/streaming/StreamExecution.scala:69,
MicroBatchExecution.scala:39). The engine re-executes a logical plan over
each micro-batch of source data; stateful operators (aggregation,
deduplication, stream-stream join) merge per-batch partials into a versioned
state store; offset and commit logs give exactly-once semantics across
restarts.

What deliberately does NOT port (SURVEY §2.4): continuous-processing mode
(ContinuousExecution.scala:42 — epoch-level RPC push; micro-batch covers the
semantics and the latency floor here is the Python driver, not the engine)
and the DStream WAL/receiver machinery (the sources below are pull-based and
replayable, so a write-ahead log is redundant).
"""

from cycloneml_tpu.streaming.metadata_log import MetadataLog
from cycloneml_tpu.streaming.sinks import (ConsoleSink, FileSink,
                                           ForeachBatchSink, MemorySink)
from cycloneml_tpu.streaming.sources import (FileStreamSource, MemoryStream,
                                             RateSource, StreamingScan)
from cycloneml_tpu.streaming.state import StateStoreProvider
from cycloneml_tpu.streaming.query import (DataStreamReader, DataStreamWriter,
                                           StreamingQuery)

__all__ = [
    "MetadataLog", "MemoryStream", "FileStreamSource", "RateSource",
    "StreamingScan", "MemorySink", "FileSink", "ForeachBatchSink",
    "ConsoleSink", "StateStoreProvider", "StreamingQuery", "DataStreamReader",
    "DataStreamWriter",
]
