"""Deterministic policy simulation: recorded trace in, decision log out.

The autoscaler's ``record_path`` (and the flight recorder's signal
dumps) produce a JSONL trace of :class:`~.policy.Signals` snapshots.
This harness replays such a trace through the EXACT production
:class:`~.policy.AutoscalePolicy` object — same class, same ``decide``,
no simulation-only fork to drift — and emits the decision log as
canonical JSON lines. Because the policy is pure (logical ``t_ms`` only,
no clocks, no global randomness) the output is BYTE-identical across
runs under a fixed seed: ``scripts/autoscale_sim.py`` (``make
autoscale-sim``) gates CI on drift against a committed golden log, so
every policy change shows up as a reviewable decision-log diff.

Trace grammar: one canonical-JSON object per line with at least a
``t_ms`` field (:meth:`Signals.to_json` shape). Lines without ``t_ms``
are metadata and skipped; blank and torn lines are tolerated the same
way :meth:`EventJournal.replay` tolerates a truncated tail — a trace
recorded up to a crash still replays.
"""

from __future__ import annotations

import json
from typing import Iterable, List, Optional

from cycloneml_tpu.elastic.policy import AutoscalePolicy, Signals, canonical
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class PolicySimulator:
    """Feeds trace lines to a policy; collects canonical decision lines.

    The first output line is a header pinning the policy's knobs and
    seed — two logs are only comparable when their headers match, and a
    golden-log diff that starts at line 1 says "the policy changed", not
    "the trace changed".
    """

    def __init__(self, policy: AutoscalePolicy):
        self.policy = policy

    def run(self, lines: Iterable[str]) -> List[str]:
        out = [canonical({"kind": "autoscale.decisions", "version": 1,
                          "seed": self.policy.seed,
                          "policy": self.policy.params()})]
        fed = 0
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                d = json.loads(line)
            except json.JSONDecodeError:
                continue   # torn tail / partial write: tolerated
            if not isinstance(d, dict) or "t_ms" not in d:
                continue   # metadata/header line
            fed += 1
            decision = self.policy.decide(Signals.from_json(d))
            if decision is not None:
                out.append(canonical(decision.to_json()))
        logger.info("autoscale sim: %d signal ticks -> %d decisions",
                    fed, len(out) - 1)
        return out


def replay(trace_path: str, policy: Optional[AutoscalePolicy] = None,
           conf=None, seed: int = 0) -> List[str]:
    """Replay a recorded signal trace; returns the decision-log lines
    (header first). A fresh policy is built from ``conf`` (or defaults)
    when none is given — pass an explicit policy to replay mid-life
    state."""
    if policy is None:
        policy = AutoscalePolicy.from_conf(conf, seed=seed) \
            if conf is not None else AutoscalePolicy(seed=seed)
    with open(trace_path, encoding="utf-8") as fh:
        return PolicySimulator(policy).run(fh)


def write_decision_log(lines: Iterable[str], path: str) -> None:
    """Write decision-log lines with a trailing newline each — the byte
    layout the golden comparison pins."""
    with open(path, "w", encoding="utf-8") as fh:
        for line in lines:
            fh.write(line + "\n")
