"""Elastic meshes: live scale-up/down, preemption-aware draining,
straggler re-dispatch, and the SLO control plane that drives them
(ROADMAP item 4 — elasticity as a SCHEDULING primitive, not just crash
recovery; ROADMAP item 2 — self-operating, not merely elastic-capable).

Four limbs, all seeded-deterministic under the chaos harness:

- :mod:`~cycloneml_tpu.elastic.capacity` — the :class:`CapacityEvent`
  channel. Scale decisions (API / SIGTERM / the ``elastic.capacity``
  chaos point) land at SAFE step boundaries: ``MeshSupervisor.reshape``
  migrates cached datasets off the device tier, clears the program
  cache, rebuilds the mesh at the new shape, and the loop resumes IN
  PLACE from its live (host-bounced) optimizer state — zero checkpoint
  restores on the reshape path.
- :mod:`~cycloneml_tpu.elastic.reshard` — live-state motion: one
  batched host bounce for device-resident leaves (coef/grad/S-Y rings),
  re-placed by the resumed program's sharding on the new topology.
- :mod:`~cycloneml_tpu.elastic.speculation` — Spark-style speculative
  re-dispatch consuming ``supervisor.stragglers()``: a latched lane's
  next work runs with a duplicate copy, first result wins, the
  duplicate dedups bitwise.
- :mod:`~cycloneml_tpu.elastic.autoscale` (+ :mod:`~.policy`,
  :mod:`~.simulate`) — the autoscaler closing the loop: skew/SLO/
  occupancy signals → hysteresis + cooldown + budget policy →
  bounded-deadline capacity acquisition → channel announcement. The
  policy is pure (logical time, no randomness), so
  :func:`~cycloneml_tpu.elastic.simulate.replay` re-runs any recorded
  signal trace byte-for-byte (``make autoscale-sim`` gates drift).

Preemption-aware draining (``multihost.preempt_notice`` →
:class:`~cycloneml_tpu.parallel.faults.PreemptionNotice` →
``MeshSupervisor.drain``) sits in ``parallel/resilience.py`` with the
rest of the recovery stack; the runtime stale-program guard
(``collectives.StaleProgramError`` over ``mesh.mesh_epoch``) polices
every transition. See docs/resilience.md "Elasticity".
"""

from cycloneml_tpu.elastic.autoscale import (Autoscaler, drop_decision,
                                             duplicate_decision)
from cycloneml_tpu.elastic.capacity import (CapacityChannel, CapacityEvent,
                                            channel, scale_to)
from cycloneml_tpu.elastic.policy import AutoscalePolicy, Decision, Signals
from cycloneml_tpu.elastic.reshard import host_bounce, host_bounce_state
from cycloneml_tpu.elastic.simulate import PolicySimulator, replay
from cycloneml_tpu.elastic.speculation import (Speculator, bitwise_equal,
                                               maybe_speculate)

__all__ = [
    "CapacityChannel", "CapacityEvent", "channel", "scale_to",
    "host_bounce", "host_bounce_state",
    "Speculator", "bitwise_equal", "maybe_speculate",
    "Autoscaler", "AutoscalePolicy", "Decision", "Signals",
    "PolicySimulator", "replay", "drop_decision", "duplicate_decision",
]
