"""Live-state motion for elastic reshapes.

The whole point of a reshape (vs. the crash-recovery rebuild) is that the
OLD mesh is still alive when the decision lands, so state moves through
memory instead of through a checkpoint file:

- :func:`host_bounce` pulls every device leaf of a pytree to host numpy
  in ONE batched ``jax.device_get`` (the JX001 discipline — no piecemeal
  per-leaf pulls through a TPU relay). Host leaves pass through
  untouched, so bouncing an already-host-resident L-BFGS state is free.
- :func:`host_bounce_state` is the OptimState form: coefficients,
  gradient and the S/Y curvature rings come back as host float64 —
  exactly what ``optimizer.iterations(..., resume=state)`` re-places onto
  whatever mesh is active when it restarts. GSPMD resharding (Xu et al.,
  PAPERS.md) is why the re-place needs no per-shape surgery: the resumed
  program's sharding annotations re-distribute the replicated state onto
  the new topology at dispatch.

Dataset motion rides the existing decommission hop
(``StorageManager.migrate_device_to_host`` + lazy re-place): see
``MeshSupervisor.reshape``.
"""

from __future__ import annotations

from typing import Any, Optional

import numpy as np


def _is_device_leaf(leaf: Any) -> bool:
    import jax
    return isinstance(leaf, jax.Array)


def host_bounce(tree: Any) -> Any:
    """Pytree with every ``jax.Array`` leaf replaced by its host numpy
    value; one batched transfer for all device leaves, host leaves (and
    non-array leaves) returned as-is."""
    import jax
    leaves, treedef = jax.tree_util.tree_flatten(tree)
    device_idx = [i for i, lf in enumerate(leaves) if _is_device_leaf(lf)]
    if device_idx:
        pulled = jax.device_get([leaves[i] for i in device_idx])
        for i, v in zip(device_idx, pulled):
            leaves[i] = np.asarray(v)
    return jax.tree_util.tree_unflatten(treedef, leaves)


def host_bounce_state(state: Optional[Any]) -> Optional[Any]:
    """OptimState (or None) with all device leaves bounced to host — the
    in-memory handoff captured BEFORE a reshape/drain tears the old mesh
    down. A pure-host state round-trips bitwise."""
    if state is None:
        return None
    from cycloneml_tpu.ml.optim.lbfgs import OptimState
    if isinstance(state, OptimState):
        return OptimState.from_pytree(host_bounce(state.to_pytree()))
    return host_bounce(state)
