"""Straggler mitigation: speculative re-dispatch with first-result-wins.

The DETECTION half landed in PR 12 (``observe/skew.py``): rolling
median+MAD verdicts per lane, latched as ``StragglerDetected`` and
recorded by ``MeshSupervisor.attach_skew`` — ``supervisor.stragglers()``
is the mitigation input. This module is the mitigation: when a lane with
a latched verdict comes up for more work, the work is RE-DISPATCHED —
Spark's speculation model (Zaharia et al., NSDI 2012: re-run the
straggling task elsewhere, commit whichever copy finishes first) — with
**first-result-wins** and a **bitwise dedup** of the duplicate result.

Two dispatch modes, matching where lanes physically run here:

- ``concurrent=True`` — HOST-side lane work (out-of-core shard staging:
  disk/NIC read + pad). BOTH copies run on a small worker pool and the
  caller returns with the FIRST successful result — lane latency is
  min(primary, backup), the actual Spark-speculation payoff — while the
  loser dedups bitwise OFF the critical path when it lands (identical
  by construction for deterministic lane work — a mismatch is logged
  loudly and counted). A failed first completion waits (bounded) for
  the other copy — the classic rescue: the lane's work still lands.
- ``concurrent=False`` — SPMD lane work (stacked/CV fit lanes). Two
  programs dispatched concurrently onto ONE gang-scheduled mesh would
  deadlock its collectives (mesh.safe_fit_parallelism; graftlint JX007),
  so the duplicate dispatch runs on the same thread immediately after
  the primary, in the gap where the mesh would otherwise idle between
  lanes — on a pod with a spare slice the same call is where the remote
  placement plugs in. First-result-wins degenerates to the primary
  (unless it FAILED, in which case the re-dispatch rescues the lane);
  the duplicate is still deduped bitwise, which doubles as a
  determinism check on the convicted lane.

Disabled discipline: ``maybe_speculate`` is one module-global read when
nothing is armed (the ``faults.inject`` pattern); the context arms a
:class:`Speculator` when ``cyclone.elastic.speculation`` is set.
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: speculative re-dispatches allowed per latched lane — a permanently
#: convicted lane must not double its work forever (Spark bounds
#: speculatable copies the same way)
MAX_REDISPATCH_PER_LANE = 16


def bitwise_equal(a: Any, b: Any) -> bool:
    """True when two lane results are BITWISE identical: numpy arrays
    compare by buffer bytes (NaN == NaN at the bit level, unlike ==),
    containers recurse, everything else falls back to ==."""
    if isinstance(a, (tuple, list)):
        return (isinstance(b, (tuple, list)) and len(a) == len(b)
                and all(bitwise_equal(x, y) for x, y in zip(a, b)))
    if isinstance(a, dict):
        return (isinstance(b, dict) and a.keys() == b.keys()
                and all(bitwise_equal(v, b[k]) for k, v in a.items()))
    if isinstance(a, np.ndarray) or isinstance(b, np.ndarray):
        a_arr, b_arr = np.asarray(a), np.asarray(b)
        return (a_arr.dtype == b_arr.dtype and a_arr.shape == b_arr.shape
                and a_arr.tobytes() == b_arr.tobytes())
    if isinstance(a, float) and isinstance(b, float):
        return np.float64(a).tobytes() == np.float64(b).tobytes()
    try:
        return bool(a == b)
    except Exception:
        return False


class _Attempt:
    """One copy's outcome: completion time, value or error."""

    __slots__ = ("name", "t_done", "value", "error")

    def __init__(self, name: str):
        self.name = name
        self.t_done: Optional[float] = None
        self.value: Any = None
        self.error: Optional[BaseException] = None

    def run(self, work: Callable[[], Any]) -> None:
        try:
            self.value = work()
        except BaseException as e:
            self.error = e
            self.t_done = time.perf_counter()
            if not isinstance(e, Exception):
                raise  # interrupts must never be swallowed by the arbiter
            return
        self.t_done = time.perf_counter()

    @property
    def ok(self) -> bool:
        return self.t_done is not None and self.error is None


class Speculator:
    """Re-dispatch work for lanes with latched straggler verdicts.

    ``stragglers_fn`` returns the latched lane keys — typically
    ``lambda: supervisor.stragglers()`` (keys are ``"group:position"``).
    The ledger (``stats()``) records every re-dispatch, which copy won,
    and whether the duplicate deduped bitwise.
    """

    def __init__(self, stragglers_fn: Callable[[], Any],
                 max_backups: int = 2, loser_wait_s: float = 30.0,
                 max_per_lane: int = MAX_REDISPATCH_PER_LANE):
        self._stragglers_fn = stragglers_fn
        self._loser_wait_s = float(loser_wait_s)
        self._max_per_lane = int(max_per_lane)
        # both copies of a raced lane run on this pool (the caller only
        # waits), so a single race needs 2 workers to actually overlap;
        # saturation degrades to queueing, never deadlock — the waiting
        # caller is not a pool thread
        self._pool = ThreadPoolExecutor(
            max_workers=max(int(max_backups), 2),
            thread_name_prefix="cyclone-speculate")
        self._lock = threading.Lock()
        self._per_lane: Dict[str, int] = {}
        self._ledger: List[dict] = []
        self._dedup_hits = 0
        self._mismatches = 0
        self._rescues = 0

    # -- verdict consumption ---------------------------------------------------
    def latched(self, group: str, position: str) -> bool:
        """True when the lane has a recorded straggler verdict AND its
        re-dispatch budget is not exhausted."""
        key = f"{group}:{position}"
        try:
            keys = self._stragglers_fn()
        except Exception:
            logger.exception("straggler provider failed; lane not latched")
            return False
        if key not in keys:
            return False
        with self._lock:
            return self._per_lane.get(key, 0) < self._max_per_lane

    # -- the race --------------------------------------------------------------
    def speculate(self, group: str, position: str,
                  work: Callable[[], Any], *, concurrent: bool = True,
                  eq: Callable[[Any, Any], bool] = bitwise_equal) -> Any:
        """Run ``work`` for a LATCHED lane with a speculative duplicate;
        FIRST result wins, the duplicate is deduped via ``eq``. Callers
        guard with :meth:`latched` (or go through
        :func:`maybe_speculate`, which does).

        ``concurrent=True`` submits BOTH copies to the worker pool and
        returns as soon as the FIRST succeeds — the caller's latency is
        min(primary, backup), the actual Spark-speculation payoff — with
        the loser deduped off the critical path when it lands (a loser
        that outlives ``loser_wait_s`` is left to its pool thread; it
        can no longer affect the returned result). Only when the first
        completion FAILED does the caller wait (bounded) for the other
        copy — the rescue path. ``concurrent=False`` runs both copies
        on the calling thread (SPMD lanes; see the module docstring).
        """
        import concurrent.futures as cf
        key = f"{group}:{position}"
        with self._lock:
            self._per_lane[key] = self._per_lane.get(key, 0) + 1
        primary, backup = _Attempt("primary"), _Attempt("backup")
        if not concurrent:
            # SPMD lane: serial duplicate on the idle mesh, same thread
            primary.run(work)
            backup.run(work)
            return self._arbitrate(key, primary, backup, eq)
        futs = {self._pool.submit(a.run, work): a
                for a in (primary, backup)}
        done, pending = cf.wait(futs, return_when=cf.FIRST_COMPLETED)
        finished = [futs[f] for f in done]
        if not any(a.ok for a in finished) and pending:
            # first completion FAILED: wait (bounded) for the other copy
            # — the rescue window
            done2, pending = cf.wait(pending, timeout=self._loser_wait_s)
            finished += [futs[f] for f in done2]
        winners = sorted((a for a in finished if a.ok),
                         key=lambda a: a.t_done)
        if winners and pending:
            # healthy winner, loser still running: dedup when it lands —
            # NEVER block the lane on its own straggling duplicate
            entry = self._record(key, winners[0], None)
            loser = next(futs[f] for f in pending)
            next(iter(pending)).add_done_callback(
                lambda _f, w=winners[0], l=loser, e=entry:
                    self._settle_loser(key, w, l, e, eq))
            return winners[0].value
        return self._arbitrate(key, primary, backup, eq)

    # -- arbitration + ledger --------------------------------------------------
    def _record(self, key: str, winner: Optional[_Attempt],
                dedup: Optional[bool], rescued: bool = False) -> dict:
        entry = {"lane": key,
                 "winner": winner.name if winner is not None else None,
                 "dedup": dedup, "rescued": rescued}
        with self._lock:
            if dedup is True:
                self._dedup_hits += 1
            elif dedup is False and winner is not None and not rescued:
                self._mismatches += 1
            if rescued:
                self._rescues += 1
            self._ledger.append(entry)
        return entry

    def _settle_loser(self, key: str, winner: _Attempt, loser: _Attempt,
                      entry: dict, eq) -> None:
        """Off-critical-path dedup once a late loser lands."""
        if not loser.ok:
            return  # nothing to dedup; the winner's result already won
        dedup = bool(eq(winner.value, loser.value))
        with self._lock:
            entry["dedup"] = dedup
            if dedup:
                self._dedup_hits += 1
            else:
                self._mismatches += 1
        if not dedup:
            logger.warning(
                "speculation: duplicate result for lane %s does not "
                "dedup bitwise; the first result was kept", key)

    def _arbitrate(self, key: str, primary: _Attempt, backup: _Attempt,
                   eq: Callable[[Any, Any], bool]) -> Any:
        if primary.ok and backup.ok:
            winner, loser = ((primary, backup)
                            if primary.t_done <= backup.t_done
                            else (backup, primary))
            dedup = bool(eq(winner.value, loser.value))
            self._record(key, winner, dedup)
            if not dedup:
                # first-result-wins holds, but a convicted lane whose
                # duplicate DISAGREES is nondeterministic work — loud
                logger.warning(
                    "speculation: duplicate result for lane %s does not "
                    "dedup bitwise; keeping the first result", key)
            return winner.value
        if primary.ok or backup.ok:
            winner = primary if primary.ok else backup
            self._record(key, winner, None, rescued=winner is backup)
            return winner.value
        self._record(key, None, None)
        # neither copy landed a result in time: surface the primary's
        # error when it has one (an unfinished primary means the bounded
        # rescue wait expired — a classified timeout, not a hang)
        if primary.error is not None:
            raise primary.error
        if backup.error is not None:
            raise backup.error
        raise TimeoutError(
            f"speculation: neither copy of lane {key} completed within "
            f"{self._loser_wait_s}s")

    # -- introspection / lifecycle ---------------------------------------------
    def stats(self) -> dict:
        with self._lock:
            return {"re_dispatches": [dict(e) for e in self._ledger],
                    "per_lane": dict(self._per_lane),
                    "dedup_hits": self._dedup_hits,
                    "mismatches": self._mismatches,
                    "rescues": self._rescues}

    def close(self) -> None:
        self._pool.shutdown(wait=True)


# -- process-global arming (the faults._active pattern) ------------------------
_lock = threading.Lock()
_speculator: Optional[Speculator] = None


def install(sp: Speculator) -> Optional[Speculator]:
    global _speculator
    with _lock:
        prev, _speculator = _speculator, sp
        return prev


def uninstall(sp: Optional[Speculator] = None) -> None:
    global _speculator
    with _lock:
        if sp is None or _speculator is sp:
            _speculator = None


def active() -> Optional[Speculator]:
    return _speculator


def maybe_speculate(group: str, position: str, work: Callable[[], Any],
                    *, concurrent: bool = True,
                    eq: Callable[[Any, Any], bool] = bitwise_equal) -> Any:
    """Instrumentation-site entry: plain ``work()`` (one module-global
    read) unless a speculator is armed AND the lane carries a latched
    straggler verdict."""
    sp = _speculator
    if sp is None or not sp.latched(group, position):
        return work()
    return sp.speculate(group, position, work, concurrent=concurrent, eq=eq)
