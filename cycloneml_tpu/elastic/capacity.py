"""Capacity events: the control channel that makes elasticity a
SCHEDULING primitive instead of a crash response.

``MeshSupervisor`` has always reacted to *loss* (device death, host
death). A :class:`CapacityEvent` is the planned twin: the platform (or an
operator, or an autoscaler) announces that the mesh SHOULD change shape —
a spare slice came up, a reservation is shrinking, a preempted slice's
replacement arrived — and the training loop re-shards its live state onto
the new mesh at the next safe step boundary and resumes in place. No
checkpoint round-trip: the reference's decommission block-migration
(Zaharia et al. NSDI 2012 lineage + the BlockManagerDecommissioner
follow-on; PAPER.md layer 3a) moves blocks to survivors while the old
executors still breathe, and this channel does the same for optimizer
state + cached datasets.

Delivery surfaces:

- **API**: ``channel().announce(CapacityEvent(master="local-mesh[4]"))``
  from any thread; ``train_with_checkpoints`` consumes it through
  ``MeshSupervisor.pending_capacity()`` at step boundaries only — a
  reshape never tears the mesh down under a running step.
- **Signal**: ``multihost.bootstrap.install_preemption_handler`` routes
  SIGTERM into an announcement on real pods.
- **Chaos**: the ``elastic.capacity`` fault point fires at every safe
  step boundary; schedule :func:`scale_to` as the fault action and the
  announcement lands at a seeded-deterministic invocation —
  every elastic transition is replayable under a fixed seed.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import List, Optional

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


@dataclass
class CapacityEvent:
    """One announced mesh-shape change.

    ``master`` is the target master URL (``local-mesh[4]``, ``tpu``,
    ``multihost[...]``) — the same grammar every rebuild speaks.
    ``returning`` names workers expected BACK on this event (a scale-up
    restoring a previously drained host): the supervisor re-arms their
    liveness state so they start with a fresh window instead of
    inheriting their stale expired verdicts.
    """

    master: str
    reason: str = ""
    returning: List[str] = field(default_factory=list)

    def __str__(self) -> str:  # the reshape log line / flight attr
        tail = f" (returning: {','.join(self.returning)})" \
            if self.returning else ""
        return f"capacity -> {self.master}" + \
            (f": {self.reason}" if self.reason else "") + tail


class CapacityChannel:
    """Thread-safe FIFO of pending :class:`CapacityEvent`s.

    Producers (API callers, signal handlers, chaos actions) ``announce``;
    the training loop ``peek``s at step boundaries and ``take``s the
    event it is about to apply. Coalescing is deliberate-NOT: two
    announcements apply in order (scale-down then scale-up is the
    preemption-replacement dance, and collapsing them would skip the
    intermediate mesh the test parity pins).
    """

    def __init__(self):
        # RLock, deliberately: install_preemption_handler's SIGTERM
        # handler runs ON the main thread between bytecodes — if the
        # main thread is inside announce() when the signal lands, the
        # handler's own announce() re-enters the lock on the SAME
        # thread, and a plain Lock would self-deadlock the process at
        # the exact moment it must drain. Cross-thread producers (the
        # autoscaler loop racing the handler) still serialize normally,
        # FIFO, non-coalescing.
        self._lock = threading.RLock()
        self._events: List[CapacityEvent] = []

    def announce(self, event: CapacityEvent) -> None:
        with self._lock:
            self._events.append(event)
        logger.info("capacity event announced: %s", event)

    def peek(self) -> Optional[CapacityEvent]:
        with self._lock:
            return self._events[0] if self._events else None

    def take(self) -> Optional[CapacityEvent]:
        with self._lock:
            return self._events.pop(0) if self._events else None

    def clear(self) -> None:
        with self._lock:
            self._events.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._events)


# -- process-global channel (the faults._active / skew._detector pattern) -----
_lock = threading.Lock()
_channel: Optional[CapacityChannel] = None


def channel() -> CapacityChannel:
    """The process-global channel, created on first use — supervisors
    attach it by default so ``channel().announce(...)`` reaches a live
    training loop with no handle-threading."""
    global _channel
    with _lock:
        if _channel is None:
            _channel = CapacityChannel()
        return _channel


def install(ch: CapacityChannel) -> Optional[CapacityChannel]:
    """Replace the process-global channel; returns the previous one
    (tests restore it)."""
    global _channel
    with _lock:
        prev, _channel = _channel, ch
        return prev


def uninstall(ch: Optional[CapacityChannel] = None) -> None:
    global _channel
    with _lock:
        if ch is None or _channel is ch:
            _channel = None


def scale_to(master: str, reason: str = "chaos capacity event",
             returning: Optional[List[str]] = None):
    """A ``FaultSchedule`` ACTION announcing a capacity event when fired:
    ``sched.at("elastic.capacity", 5, scale_to("local-mesh[4]"))`` makes
    the scale-down land at exactly the 5th safe step boundary — the
    seeded-deterministic chaos form of the API announcement."""

    def _announce(point: str, invocation: int, **info) -> None:
        channel().announce(CapacityEvent(
            master=master,
            reason=f"{reason} ({point}#{invocation})",
            returning=list(returning or [])))

    _announce.__name__ = f"scale_to[{master}]"
    return _announce
