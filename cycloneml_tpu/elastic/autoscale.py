"""Autoscaler: the control loop that makes elasticity SELF-OPERATING.

PR 12 built the sensors (skew/straggler verdicts, SLO latches, occupancy
gauges) and the capacity channel is the actuator (CapacityEvent →
reshape at a safe step boundary); this module closes the loop. Each tick
samples the signal plane into one :class:`~.policy.Signals` snapshot —

- **serving p99** from the ``serving.dispatch`` timer histogram, judged
  against ``cyclone.autoscale.targetP99Ms`` (the Clipper contract:
  latency SLO drives replica count);
- **straggler pressure** + **step-time SLO** from the
  :class:`~cycloneml_tpu.observe.skew.SkewDetector` latches;
- **HBM occupancy** from the :mod:`~cycloneml_tpu.observe.costs` gauges
  (−1 when the backend exposes none — CPU smoke never "looks idle") —

feeds it to the :class:`~.policy.AutoscalePolicy`, and APPLIES the
verdict: scale-up first ACQUIRES capacity through
:func:`~cycloneml_tpu.parallel.allocation.acquire_devices` with a
bounded deadline (expiry → graceful no-op + ``CapacityAcquired(ok=False)``
event, never a wedged train loop), then announces on the channel;
scale-down announces a half-size mesh directly (shrinking onto a subset
needs no new capacity).

Chaos: every policy verdict passes the seeded ``autoscale.decide`` fault
point before application. Schedule ``delay_s`` for a late decision,
:func:`drop_decision` for a dropped one, or :func:`duplicate_decision`
for a doubled one — the loop must survive its own controller
misbehaving, and test_chaos.py pins that it does.

Lifecycle: ``stop()`` latches; the apply path re-checks the latch and
announces under the SAME lock acquisition, so a concurrent shutdown can
never land a decision on a stopped supervisor (the JX022 discipline —
the graftlint fixture pair encodes exactly this idiom).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Iterable, Optional

from cycloneml_tpu.elastic import capacity as _capacity
from cycloneml_tpu.elastic.policy import AutoscalePolicy, Decision, Signals, \
    canonical
from cycloneml_tpu.observe import attribution
from cycloneml_tpu.parallel import allocation as _allocation
from cycloneml_tpu.parallel import faults as _faults
from cycloneml_tpu.util.events import AutoscaleDecision, CapacityAcquired
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: skew-detector groups whose latched stragglers count as TRAINING
#: pressure (serving.dispatch stragglers are the serving leg's business,
#: already covered by the p99 signal)
TRAIN_STRAGGLER_GROUPS = ("oocore.stage", "heartbeat.rtt", "fit.lane")


def occupancy_fraction(conf=None) -> float:
    """Peak device-memory occupancy as a fraction of the per-device
    limit, or -1.0 when the backend exposes no memory stats (CPU) or no
    limit — the scale-down signal for :class:`~.policy.AutoscalePolicy`."""
    from cycloneml_tpu.observe import costs as _costs
    try:
        if not _costs.memory_stats_available():
            return -1.0
        peak = _costs.sample_device_peak()
        limit = _costs.device_memory_limit(conf)
        if not peak or not limit:
            return -1.0
        return min(1.0, float(peak) / float(limit))
    except Exception:   # a broken gauge must not kill the control loop
        logger.exception("autoscale: occupancy sample failed")
        return -1.0


# -- fault ACTIONS for the autoscale.decide point -------------------------


def drop_decision(point: str, invocation: int, control=None, **info) -> None:
    """Chaos action: the controller's decision evaporates in flight —
    ``sched.at("autoscale.decide", 1, drop_decision)`` proves a lost
    decision degrades to "breach persists, policy re-decides after
    cooldown", never a wedged loop."""
    if control is not None:
        control["applications"] = 0


def duplicate_decision(point: str, invocation: int, control=None,
                       **info) -> None:
    """Chaos action: the decision applies TWICE (a controller retry bug).
    The second application is a same-shape reshape or a bounded acquire
    no-op — survivable either way, and the test pins the reshape count."""
    if control is not None:
        control["applications"] = 2


class Autoscaler:
    """Samples the signal plane, runs the policy, applies the verdict.

    All collaborators are injectable (the simulate/test seam); defaults
    wire the process-global capacity channel and the platform device
    count. ``start()`` runs a daemon tick loop; ``tick(now_ms=...)``
    drives one deterministic step (the chaos tests tick it from the
    ``elastic.capacity`` boundary with logical time, so the whole closed
    loop replays under a seed). ``record_path`` appends each tick's
    Signals as canonical JSONL — the trace ``simulate.replay`` consumes.
    """

    def __init__(self, policy: AutoscalePolicy, *,
                 channel: Optional[_capacity.CapacityChannel] = None,
                 detector=None, registry=None, bus=None,
                 used_fn: Optional[Callable[[], int]] = None,
                 master_for: Optional[Callable[[int], str]] = None,
                 acquire: Optional[Callable] = None,
                 acquire_timeout_s: float = 5.0,
                 interval_s: float = 1.0, min_devices: int = 1,
                 occupancy_fn: Optional[Callable[[], float]] = None,
                 record_path: Optional[str] = None,
                 straggler_groups: Iterable[str] = TRAIN_STRAGGLER_GROUPS):
        self.policy = policy
        self.acquire_timeout_s = float(acquire_timeout_s)
        self.interval_s = float(interval_s)
        self.min_devices = max(1, int(min_devices))
        self._channel = channel if channel is not None \
            else _capacity.channel()
        self._detector = detector
        self._registry = registry
        self._bus = bus
        self._used_fn = used_fn or self._default_used
        self._master_for = master_for or (lambda n: f"local-mesh[{n}]")
        self._acquire = acquire or _allocation.acquire_devices
        self._occupancy_fn = occupancy_fn or occupancy_fraction
        self._groups = tuple(straggler_groups)
        self._lock = threading.Lock()
        self._stopped = False
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # reshape actions bill the scope that OWNED the autoscaler at
        # construction (the loop thread has no scope stack of its own)
        self._scope = attribution.current_scope()
        self._record_lock = threading.Lock()
        self._record_fh = open(record_path, "a", encoding="utf-8") \
            if record_path else None

    @staticmethod
    def _default_used() -> int:
        import jax
        return len(jax.devices())

    # -- sampling ---------------------------------------------------------

    def sample(self, now_ms: Optional[int] = None) -> Signals:
        """One snapshot of the signal plane. ``now_ms`` overrides the
        wall clock with logical time (chaos/replay determinism)."""
        t_ms = int(now_ms) if now_ms is not None \
            else int(time.time() * 1000)
        p99_ms = 0.0
        if self._registry is not None:
            try:
                snap = self._registry.timer("serving.dispatch").snapshot()
                p99_ms = float(snap.get("p99", 0.0)) * 1e3
            except Exception:
                logger.exception("autoscale: serving p99 sample failed")
        pressure = 0
        step_breached = False
        if self._detector is not None:
            try:
                pressure = self._detector.straggler_pressure(self._groups)
                step_breached = bool(
                    self._detector.slo_breaches("collectives.step"))
            except Exception:
                logger.exception("autoscale: skew sample failed")
        return Signals(t_ms=t_ms, serving_p99_ms=p99_ms,
                       straggler_pressure=pressure,
                       step_slo_breached=step_breached,
                       occupancy_fraction=float(self._occupancy_fn()))

    def _record(self, signals: Signals) -> None:
        with self._record_lock:
            fh = self._record_fh
            if fh is None:
                return
            fh.write(canonical(signals.to_json()) + "\n")
            fh.flush()

    # -- the control loop -------------------------------------------------

    def tick(self, now_ms: Optional[int] = None) -> Optional[Decision]:
        """One sample → decide → apply step; returns the Decision (or
        None). Never raises on signal/apply trouble — a control plane
        that crashes the loop it supervises is worse than no control
        plane."""
        with self._lock:
            if self._stopped:
                return None
        signals = self.sample(now_ms)
        self._record(signals)
        decision = self.policy.decide(signals)
        if decision is None:
            return None
        # the controller-misbehaving fault point: actions mutate
        # control["applications"] (0 = dropped, 2 = duplicated); an
        # exception fault drops the decision too — either way the loop
        # continues and the policy re-decides after its cooldown
        control = {"applications": 1}
        try:
            _faults.inject("autoscale.decide", decision=decision.to_json(),
                           control=control)
        except _faults.FaultInjected as exc:
            logger.warning("autoscale: decision #%d lost to injected "
                           "fault: %s", decision.seq, exc)
            control["applications"] = 0
        if decision.action == "warn-hold":
            outcome = "warn-hold"
            logger.warning(
                "autoscale: decision budget exhausted (%d applied) — "
                "holding; raise cyclone.autoscale.maxDecisions or "
                "investigate the flapping signal", self.policy.max_decisions)
        elif control["applications"] <= 0:
            outcome = "dropped"
            logger.warning("autoscale: decision #%d dropped",
                           decision.seq)
        else:
            outcome = "held"
            for _ in range(int(control["applications"])):
                outcome = self._apply(decision)
        self._post(AutoscaleDecision(
            seq=decision.seq, action=decision.action,
            direction=decision.direction, reason=decision.reason,
            outcome=outcome, breach_streak=decision.breach_streak,
            idle_streak=decision.idle_streak))
        return decision

    def _apply(self, decision: Decision) -> str:
        used = max(1, int(self._used_fn()))
        if decision.direction == "up":
            start = time.monotonic()
            n = self._acquire(used + 1, self.acquire_timeout_s,
                              cancel=self._stop_event)
            waited_ms = (time.monotonic() - start) * 1e3
            if n is None:
                # acquire deadline expired: graceful no-op + event; the
                # policy's cooldown retries later if the breach persists
                logger.warning(
                    "autoscale: capacity acquire timed out after %.0fms "
                    "(decision #%d, wanted >%d devices) — holding",
                    waited_ms, decision.seq, used)
                self._post(CapacityAcquired(
                    ok=False, n_devices=0, waited_ms=waited_ms,
                    reason=decision.reason))
                return "acquire-timeout"
            target = n
            self._post(CapacityAcquired(
                ok=True, master=self._master_for(target), n_devices=target,
                waited_ms=waited_ms, reason=decision.reason))
        else:
            target = max(self.min_devices, used // 2)
            if target >= used:
                return "held"   # already at the floor: nothing to shed
        event = _capacity.CapacityEvent(
            master=self._master_for(target),
            reason=f"autoscale: {decision.reason} (#{decision.seq})")
        # latch discipline: re-check stop and announce under the SAME
        # lock hold, so a concurrent stop() can never interleave between
        # the check and the announcement (JX022)
        with self._lock:
            if self._stopped:
                logger.info("autoscale: stopped — decision #%d not "
                            "announced", decision.seq)
                return "held"
            self._channel.announce(event)
        attribution.charge(self._scope, autoscaleActions=1)
        return "announced"

    def _post(self, event) -> None:
        if self._bus is None:
            return
        try:
            self._bus.post(event)
        except Exception:
            logger.exception("autoscale: event post failed")

    # -- lifecycle --------------------------------------------------------

    def start(self) -> "Autoscaler":
        """Run the tick loop on a daemon thread. Raises once stopped —
        an autoscaler does not reincarnate (build a new one)."""
        with self._lock:
            if self._stopped:
                raise RuntimeError("autoscaler is stopped")
            if self._thread is None:
                self._thread = threading.Thread(
                    target=self._loop, name="cyclone-autoscale",
                    daemon=True)
                self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop_event.wait(self.interval_s):
            try:
                self.tick()
            except Exception:   # the loop never dies to a bad tick
                logger.exception("autoscale: tick failed")

    def stop(self) -> None:
        """Latch shutdown, wake + join the loop, close the recorder.
        Idempotent; in-flight decisions observe the latch before they
        can announce."""
        with self._lock:
            if self._stopped:
                return
            self._stopped = True
            thread, self._thread = self._thread, None
        self._stop_event.set()
        if thread is not None:
            thread.join(timeout=5)
        with self._record_lock:
            fh, self._record_fh = self._record_fh, None
        if fh is not None:
            fh.close()
