"""Autoscale policy: the pure, deterministic decision core.

The control plane splits controller from actuator the way the chaos
harness splits schedule from injector: this module is the POLICY — a
pure state machine over :class:`Signals` snapshots with no clock reads,
no global randomness and no device work — and
:mod:`~cycloneml_tpu.elastic.autoscale` is the runtime that samples the
PR-12 signal plane, feeds it, and applies what it returns. Purity is the
point: :mod:`~cycloneml_tpu.elastic.simulate` replays a recorded signal
trace through the EXACT production policy object and gets a
byte-identical decision log under a fixed seed, so every policy change
is reviewable as a decision-log diff (the Zaharia NSDI'12 lesson —
speculation/decommission policy must be budgeted and deterministic to
be trustworthy; Clipper, Crankshaw NSDI'17, supplies the SLO-driven
adaptation contract the serving leg implements).

Robustness semantics (docs/resilience.md "Autoscaling"):

- **per-direction hysteresis**: a scale-up needs ``scale_up_after``
  CONSECUTIVE breach ticks, a scale-down ``scale_down_after``
  consecutive idle ticks; any contrary sample resets the streak, so a
  flapping signal never reaches a verdict.
- **per-direction cooldowns**: after a decision, the same direction is
  suppressed for ``cooldown_ms`` of *logical* time (``Signals.t_ms`` —
  never the wall clock, or replay would diverge).
- **decision budget**: ``max_decisions`` applied decisions, SEPARATE
  from ``MeshSupervisor.max_reshapes`` — an exhausted policy degrades
  to ONE latched ``warn-hold`` decision and then holds silently; it
  never thrashes the mesh or eats the budget a real failure needs.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Any, Dict, List, Optional

#: occupancy fraction below which a tick counts as idle (the scale-down
#: signal); occupancy < 0 means "unavailable" and never counts as idle
IDLE_OCCUPANCY_FRACTION = 0.3


def canonical(obj: Any) -> str:
    """Canonical JSON line — sorted keys, no whitespace — so equal
    decisions serialize to equal BYTES (the simulation-determinism and
    golden-log contracts compare bytes, not parsed trees)."""
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


@dataclass(frozen=True)
class Signals:
    """One sampled snapshot of the PR-12 signal plane.

    ``t_ms`` is the tick's logical timestamp — supplied by the sampler
    (wall clock at record time, invocation count under chaos, trace
    field on replay); the policy itself never reads a clock.
    ``serving_p99_ms`` is 0 when nothing serves; ``occupancy_fraction``
    is -1 when the backend exposes no memory stats (CPU smoke) — an
    unavailable gauge can never vote for scale-down.
    """

    t_ms: int = 0
    serving_p99_ms: float = 0.0
    straggler_pressure: int = 0
    step_slo_breached: bool = False
    occupancy_fraction: float = -1.0

    def to_json(self) -> Dict[str, Any]:
        return {"t_ms": self.t_ms,
                "serving_p99_ms": self.serving_p99_ms,
                "straggler_pressure": self.straggler_pressure,
                "step_slo_breached": self.step_slo_breached,
                "occupancy_fraction": self.occupancy_fraction}

    @classmethod
    def from_json(cls, d: Dict[str, Any]) -> "Signals":
        return cls(
            t_ms=int(d.get("t_ms", 0)),
            serving_p99_ms=float(d.get("serving_p99_ms", 0.0)),
            straggler_pressure=int(d.get("straggler_pressure", 0)),
            step_slo_breached=bool(d.get("step_slo_breached", False)),
            occupancy_fraction=float(d.get("occupancy_fraction", -1.0)))


@dataclass(frozen=True)
class Decision:
    """One policy verdict. ``action`` is ``scale-up`` / ``scale-down``
    / ``warn-hold`` (budget exhausted — announced once, applied never);
    streak fields record the hysteresis evidence at verdict time."""

    seq: int = 0
    t_ms: int = 0
    action: str = ""
    direction: str = ""
    reason: str = ""
    breach_streak: int = 0
    idle_streak: int = 0
    budget_left: int = 0

    def to_json(self) -> Dict[str, Any]:
        return {"seq": self.seq, "t_ms": self.t_ms, "action": self.action,
                "direction": self.direction, "reason": self.reason,
                "breach_streak": self.breach_streak,
                "idle_streak": self.idle_streak,
                "budget_left": self.budget_left}


class AutoscalePolicy:
    """Hysteresis + cooldown + budget over a stream of :class:`Signals`.

    NOT thread-safe by itself — the runtime serializes ``decide`` calls
    (one tick at a time), and the simulator is single-threaded by
    construction. ``seed`` pins the replay identity: the policy draws no
    randomness, but the seed travels in the decision-log header so a log
    diff always says which replay universe produced it.
    """

    def __init__(self, *, target_p99_ms: float = 0.0,
                 scale_up_after: int = 3, scale_down_after: int = 6,
                 cooldown_ms: int = 30000, max_decisions: int = 8,
                 idle_occupancy: float = IDLE_OCCUPANCY_FRACTION,
                 seed: int = 0):
        self.target_p99_ms = float(target_p99_ms)
        self.scale_up_after = max(1, int(scale_up_after))
        self.scale_down_after = max(1, int(scale_down_after))
        self.cooldown_ms = max(0, int(cooldown_ms))
        self.max_decisions = max(0, int(max_decisions))
        self.idle_occupancy = float(idle_occupancy)
        self.seed = int(seed)
        self._up_streak = 0
        self._down_streak = 0
        self._last_ms: Dict[str, Optional[int]] = {"up": None, "down": None}
        self._decisions = 0
        self._warned = False
        self._log: List[Decision] = []

    @classmethod
    def from_conf(cls, conf, seed: int = 0) -> "AutoscalePolicy":
        from cycloneml_tpu.conf import (AUTOSCALE_COOLDOWN_MS,
                                        AUTOSCALE_MAX_DECISIONS,
                                        AUTOSCALE_SCALE_DOWN_AFTER,
                                        AUTOSCALE_SCALE_UP_AFTER,
                                        AUTOSCALE_TARGET_P99_MS)
        return cls(target_p99_ms=conf.get(AUTOSCALE_TARGET_P99_MS),
                   scale_up_after=conf.get(AUTOSCALE_SCALE_UP_AFTER),
                   scale_down_after=conf.get(AUTOSCALE_SCALE_DOWN_AFTER),
                   cooldown_ms=conf.get(AUTOSCALE_COOLDOWN_MS),
                   max_decisions=conf.get(AUTOSCALE_MAX_DECISIONS),
                   seed=seed)

    def params(self) -> Dict[str, Any]:
        """The policy's knobs, for the decision-log header — two logs
        are only comparable when their headers match."""
        return {"target_p99_ms": self.target_p99_ms,
                "scale_up_after": self.scale_up_after,
                "scale_down_after": self.scale_down_after,
                "cooldown_ms": self.cooldown_ms,
                "max_decisions": self.max_decisions,
                "idle_occupancy": self.idle_occupancy}

    @property
    def log(self) -> List[Decision]:
        """Every decision made, in order (warn-hold included)."""
        return list(self._log)

    @property
    def decisions_applied(self) -> int:
        """Applied (budget-consuming) decisions so far."""
        return self._decisions

    @property
    def budget_exhausted(self) -> bool:
        return self._decisions >= self.max_decisions

    def breach_reason(self, s: Signals) -> Optional[str]:
        """Why this tick votes scale-up, or None. Serving p99 outranks
        training pressure: a violated latency SLO is user-visible."""
        if self.target_p99_ms > 0 and s.serving_p99_ms > self.target_p99_ms:
            return "serving-p99"
        if s.straggler_pressure > 0:
            return "straggler-pressure"
        if s.step_slo_breached:
            return "step-slo"
        return None

    def decide(self, signals: Signals) -> Optional[Decision]:
        """Feed one tick; a Decision when the hysteresis window closes,
        else None. Pure in the replay sense: the same Signals sequence
        always yields the same Decision sequence."""
        reason = self.breach_reason(signals)
        idle = (reason is None and
                0.0 <= signals.occupancy_fraction < self.idle_occupancy)
        if reason is not None:
            self._up_streak += 1
            self._down_streak = 0
            direction, streak, need = "up", self._up_streak, \
                self.scale_up_after
        elif idle:
            self._down_streak += 1
            self._up_streak = 0
            direction, streak, need = "down", self._down_streak, \
                self.scale_down_after
            reason = "idle-occupancy"
        else:
            # neither breached nor idle: every streak restarts from here
            self._up_streak = 0
            self._down_streak = 0
            return None
        if streak < need:
            return None
        last = self._last_ms[direction]
        if last is not None and signals.t_ms - last < self.cooldown_ms:
            return None   # cooldown: sustained pressure re-decides later
        up, down = self._up_streak, self._down_streak
        if self._decisions >= self.max_decisions:
            if self._warned:
                return None
            # budget exhausted: degrade to ONE latched warn-hold — the
            # flapping-policy failure mode is a warning, never a thrash
            self._warned = True
            return self._record(Decision(
                seq=len(self._log) + 1, t_ms=signals.t_ms,
                action="warn-hold", direction=direction, reason=reason,
                breach_streak=up, idle_streak=down, budget_left=0))
        self._decisions += 1
        self._last_ms[direction] = signals.t_ms
        self._up_streak = 0
        self._down_streak = 0
        return self._record(Decision(
            seq=len(self._log) + 1, t_ms=signals.t_ms,
            action="scale-up" if direction == "up" else "scale-down",
            direction=direction, reason=reason,
            breach_streak=up, idle_streak=down,
            budget_left=self.max_decisions - self._decisions))

    def _record(self, d: Decision) -> Decision:
        self._log.append(d)
        return d
