"""Out-of-core streaming epoch engine: train on datasets larger than HBM.

The in-core fit paths require the full design matrix resident on the mesh;
this subsystem removes that ceiling. A dataset becomes a sequence of
bounded host shards (``shards.StreamingDataset`` — npz files at data-tier
width, with the fit statistics harvested in the same write pass); an epoch
streams them through a double-buffered host→device pipeline
(``stream.ShardStream`` — staging overlaps compute, shard operands donated
so HBM is reclaimed per dispatch); the objective folds per-shard masked
psum partials into one accumulator-tier sum
(``objective.StreamingLossFunction`` — the same aggregators, the same
normalization, seeded-parity with the in-core fit up to summation order);
and routing (``engine``) makes streaming a first-class fit mode: explicit
via ``cyclone.oocore.mode=force`` or a ``StreamingDataset`` handed to
``fit``, automatic when the memory budget guard's chunk-halving bottoms
out and the program still exceeds budget — degrade, don't OOM.

docs/out-of-core.md is the architecture document; conf keys live under
``cyclone.oocore.*``; the ``oocore.stage`` chaos point covers mid-epoch
transfer failure.
"""

from cycloneml_tpu.observe.costs import OutOfCoreRequired
from cycloneml_tpu.oocore.cache import ShardSetCache, shard_set_cache
from cycloneml_tpu.oocore.engine import (StreamingGradientDescent,
                                         degrade_allowed, shard_dataset,
                                         streaming_mode)
from cycloneml_tpu.oocore.objective import (StackedStreamingLossFunction,
                                            StreamingLossFunction)
from cycloneml_tpu.oocore.shards import StreamingDataset
from cycloneml_tpu.oocore.stream import ShardStream

__all__ = [
    "StreamingDataset", "ShardStream", "StreamingLossFunction",
    "StackedStreamingLossFunction", "StreamingGradientDescent",
    "OutOfCoreRequired", "shard_dataset", "streaming_mode",
    "degrade_allowed", "ShardSetCache", "shard_set_cache",
]
