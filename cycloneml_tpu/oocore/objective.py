"""Streamed objective: partial-sweep gradient/loss accumulation.

The out-of-core twin of ``ml/optim/loss.DistributedLossFunction``: one
loss/grad evaluation is one EPOCH — every shard staged (double-buffered),
dispatched through the SAME block aggregator the in-core fit uses, its
psummed ``{loss, grad, count}`` partial folded into a host float64
accumulator, and the total normalized by the weight sum exactly like the
in-core path. Because the per-shard math is the identical aggregator over
identically-masked padded blocks, a streamed fit's objective differs from
the in-core fit's only by floating-point summation ORDER (shard partials
vs device partials) — ~1e-15 relative under the f64 test config, the
parity envelope docs/out-of-core.md documents.

There is deliberately NO ``device_line_search`` here: the strong-Wolfe
search runs on the host with each φ(α) evaluation a full streamed epoch —
the line search over streamed objectives the out-of-core regime implies
(evaluations cost I/O, so the optimizer's eval count is the fit's epoch
count; L-BFGS' ~2-3 evals/iteration keeps that civilized).
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from cycloneml_tpu.observe import costs, tracing
from cycloneml_tpu.oocore.stream import ShardStream
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


class StreamingLossFunction:
    """Callable ``(coef) -> (loss, grad)`` in host float64 over a
    :class:`~cycloneml_tpu.oocore.shards.StreamingDataset`.

    - ``agg``: the SAME block aggregator the in-core fit would use
      (``aggregators.*`` — sums, not means; signature
      ``(x, y, w, *extras, coef)``)
    - ``extra_args``: replicated arguments before the coefficients
      (inv_std / scaled_mean / y_pars), identical to the in-core
      ``DistributedLossFunction(extra_args=...)`` contract
    - ``l2_reg_fn``: the driver-side penalty, applied once per epoch
    - the weight sum comes from the shard set's write-pass moments — no
      extra epoch is spent measuring it
    """

    def __init__(self, sds, agg: Callable,
                 l2_reg_fn: Optional[Callable] = None,
                 weight_sum: Optional[float] = None,
                 extra_args: tuple = ()):
        from cycloneml_tpu.parallel import collectives
        self._sds = sds
        self._ctx = sds.ctx
        rt = sds.ctx.mesh_runtime
        # ONE per-shard program for the whole fit: compiled before any
        # shard exists (n_sharded names the row-sharded args), with the
        # staged shard operands DONATED — they are consumed exactly once,
        # and donation frees their HBM for the next in-flight transfer
        self._sds_agg = agg  # kept so reshard() can rebind on a new mesh
        self._prog = collectives.tree_aggregate(agg, rt, n_sharded=3,
                                                donate_rows=True)
        self._extras = tuple(extra_args)
        self.l2_reg_fn = l2_reg_fn
        self.weight_sum = float(weight_sum) if weight_sum is not None \
            else float(sds.weight_sum)
        self.n_evals = 0
        self.n_dispatches = 0   # shard dispatches (n_shards per epoch)
        self.epochs = 0

    def reshard(self, runtime=None) -> "StreamingLossFunction":
        """Rebind this streamed objective to the (rebuilt) mesh — the
        out-of-core leg of an elastic reshape: the held per-shard program
        closes over the OLD mesh (the runtime StaleProgramError guard
        would refuse it), so it recompiles against the new runtime while
        every host-side position — epoch/eval/dispatch counters, the
        weight sum, the shard set itself — carries over untouched.
        Shards re-stage lazily on the new topology at the next sweep;
        the fixed ``(padRows, d)`` geometry must divide the new mesh's
        data parallelism (padRows is a multiple of 8× the SPILL-time
        parallelism, so power-of-two scale-downs and moderate scale-ups
        always fit) — an indivisible shape raises before any dispatch."""
        from cycloneml_tpu.parallel import collectives
        rt = runtime if runtime is not None else self._ctx.mesh_runtime
        dp = rt.data_parallelism
        if self._sds.pad_rows % dp:
            raise ValueError(
                f"shard geometry padRows={self._sds.pad_rows} does not "
                f"divide the reshaped mesh's data parallelism {dp}; "
                f"re-spill the shard set for this topology")
        self._prog = collectives.tree_aggregate(
            self._sds_agg, rt, n_sharded=3, donate_rows=True)
        return self

    # -- the streamed sweep ----------------------------------------------------
    def sweep(self, *call_args, per_shard=None, order=None) -> dict:
        """One epoch: stage every shard, dispatch the per-shard program,
        fold the psummed partials into host float64 sums. Returns the raw
        accumulated pytree (sums — the caller normalizes), mirroring what
        one in-core ``tree_aggregate`` dispatch returns. ``per_shard(i)``
        optionally supplies extra replicated arguments appended per shard
        dispatch (the streamed SGD's shard-index mask key — keyed on the
        TRUE shard index, so it is order-invariant). ``order`` optionally
        permutes the staging order for this epoch (streamed-SGD
        shuffling); the accumulated sums differ only by float summation
        order."""
        import jax
        acc: Optional[dict] = None
        self.epochs += 1
        with tracing.span("dispatch", "oocore.sweep",
                          shards=self._sds.n_shards) as sweep_sp:
            with ShardStream(self._sds, order=order) as stream:
                for i, xs, ys, ws in stream:
                    args = call_args if per_shard is None \
                        else (*call_args, *per_shard(i))
                    with tracing.span("dispatch", "oocore.shard", shard=i):
                        out_dev = self._prog(xs, ys, ws, *args)
                        del xs, ys, ws  # donated: dead on dispatch
                        with tracing.span("transfer",
                                          "oocore.readback") as tsp:
                            out = jax.device_get(out_dev)
                            tsp.annotate_bytes(out)
                    self.n_dispatches += 1
                    if acc is None:
                        acc = {k: np.asarray(v, dtype=np.float64)
                               for k, v in out.items()}
                    else:
                        for k, v in out.items():
                            acc[k] = acc[k] + np.asarray(v, dtype=np.float64)
            sweep_sp.annotate(bytes_staged=stream.bytes_staged)
        if acc is None:
            raise RuntimeError("streamed sweep saw zero shards")
        return acc

    def __call__(self, coef: np.ndarray) -> Tuple[float, np.ndarray]:
        self.n_evals += 1
        out = self.sweep(*self._extras, np.asarray(coef))
        loss = float(out["loss"]) / self.weight_sum
        grad = np.asarray(out["grad"], dtype=np.float64) / self.weight_sum
        if self.l2_reg_fn is not None:
            rl, rg = self.l2_reg_fn(coef)
            loss += float(rl)
            grad += np.asarray(rg, dtype=np.float64)
        if hasattr(self._ctx, "record_step"):
            # one streamed epoch ≈ one stage's TaskMetrics
            self._ctx.record_step({"loss": loss,
                                   "oocore_shards": self._sds.n_shards})
        return loss, grad

    # -- accounting ------------------------------------------------------------
    def _shard_avals(self, n_coef: int, concrete: bool = False) -> tuple:
        """Representative per-shard operands at the padded geometry.
        Abstract ``ShapeDtypeStruct``s by default — ``lower()`` only needs
        avals, and a real O(shard) allocation here would compete for the
        very HBM the streamed fit bounds; ``concrete=True`` is the
        fallback for jax versions whose structs cannot carry sharding."""
        import jax
        from cycloneml_tpu.dataset.instance import compute_dtype
        sds = self._sds
        # the ACTUAL stream dtype: fp8 shard sets stage 1-byte codes, and
        # the cost model must bill them at that width (bench-bytes gates
        # the fp8 stream at < 0.55x the bf16 stream)
        xdt = np.dtype(getattr(sds, "x_dtype", np.float64))
        adt = np.dtype(compute_dtype())
        rt = sds.ctx.mesh_runtime
        if concrete:
            x = rt.device_put_sharded_rows(
                np.zeros((sds.pad_rows, sds.n_features), dtype=xdt))
            y = rt.device_put_sharded_rows(np.zeros(sds.pad_rows, dtype=adt))
            w = rt.device_put_sharded_rows(np.zeros(sds.pad_rows, dtype=adt))
        else:
            x = jax.ShapeDtypeStruct((sds.pad_rows, sds.n_features), xdt,
                                     sharding=rt.data_sharding(1))
            y = jax.ShapeDtypeStruct((sds.pad_rows,), adt,
                                     sharding=rt.data_sharding(0))
            w = jax.ShapeDtypeStruct((sds.pad_rows,), adt,
                                     sharding=rt.data_sharding(0))
        return (x, y, w, *self._extras,
                np.zeros(n_coef, dtype=np.float64))

    def sweep_cost(self, n_coef: int) -> costs.ProgramCost:
        """:func:`observe.costs.streamed_sweep_cost` over this fit's
        per-shard program at the padded shard geometry — the whole-epoch
        bytes/FLOPs with the O(shard) per-dispatch memory footprint."""
        cost = costs.streamed_sweep_cost(
            self._prog, self._shard_avals(n_coef), self._sds.n_shards)
        if not cost.cost_available:
            # lower() rejected the abstract operands (older jax): pay the
            # one concrete staging for the measurement
            cost = costs.streamed_sweep_cost(
                self._prog, self._shard_avals(n_coef, concrete=True),
                self._sds.n_shards)
        return cost


class _StackedShardView:
    """StreamingDataset facade carrying the per-shard ``(rows, K)`` label
    stack, built host-side at stage time — the stacked streamed fit never
    materializes the whole ``(n, K)`` matrix anywhere: each shard's stack
    is O(shard · K), staged once, donated like every other shard operand.

    Two label sources, mirroring the in-core ``fit_stacked`` inputs:

    - :meth:`tiled` — the shard's own labels broadcast across K models
      (CV grids: same data, K reg strengths);
    - :meth:`from_stack` — column slices of a caller ``(K, n)`` stack in
      shard row order (OneVsRest relabelings; ``from_chunks`` preserves
      row order, so shard offsets index the stack directly).
    """

    def __init__(self, sds, n_models: int, y_fn, y_dtype):
        self._sds = sds
        self.n_models = int(n_models)
        self._y_fn = y_fn
        self.y_dtype = np.dtype(y_dtype)

    @classmethod
    def tiled(cls, sds, n_models: int, y_dtype) -> "_StackedShardView":
        ydt = np.dtype(y_dtype)

        def y_fn(i, y):
            y = np.asarray(y, dtype=ydt)
            return np.ascontiguousarray(
                np.broadcast_to(y[:, None], (len(y), n_models)))

        return cls(sds, n_models, y_fn, ydt)

    @classmethod
    def from_stack(cls, sds, y_stack: np.ndarray,
                   y_dtype) -> "_StackedShardView":
        ydt = np.dtype(y_dtype)
        offsets = np.cumsum([0] + [s.rows for s in sds._shards])
        if y_stack.shape[1] != sds.n_rows:
            raise ValueError(
                f"y_stack has {y_stack.shape[1]} rows per model; the "
                f"shard set has {sds.n_rows}")

        def y_fn(i, y):
            lo, hi = offsets[i], offsets[i + 1]
            return np.ascontiguousarray(
                np.asarray(y_stack[:, lo:hi]).T.astype(ydt))

        return cls(sds, len(y_stack), y_fn, ydt)

    # -- delegated surface (what ShardStream + the objective touch) -----------
    @property
    def ctx(self):
        return self._sds.ctx

    @property
    def n_shards(self) -> int:
        return self._sds.n_shards

    @property
    def n_rows(self) -> int:
        return self._sds.n_rows

    @property
    def n_features(self) -> int:
        return self._sds.n_features

    @property
    def pad_rows(self) -> int:
        return self._sds.pad_rows

    @property
    def weight_sum(self) -> float:
        return self._sds.weight_sum

    @property
    def x_dtype(self):
        return getattr(self._sds, "x_dtype", np.dtype(np.float64))

    @property
    def x_scale(self):
        return getattr(self._sds, "x_scale", None)

    def load_shard(self, i: int):
        x, y, w = self._sds.load_shard(i)
        return x, self._y_fn(i, y), w


class StackedStreamingLossFunction(StreamingLossFunction):
    """Model-axis twin of :class:`StreamingLossFunction` — the streamed
    analog of ``loss.StackedDistributedLossFunction``.

    Callable ``(coef_stack (K, n_coef)) -> (loss (K,), grad (K, n_coef))``
    in host float64; one evaluation is ONE double-buffered epoch whose
    per-shard program is the vmapped stacked aggregator — every staged
    shard serves all K models, so a K-model grid/OvR fit over spilled
    data reads the data once per iteration instead of K times. Per-model
    L2 is host-side runtime data (``stacked_host_l2`` — shared with the
    in-core stacked loss, so penalties are bit-identical).
    """

    def __init__(self, sds, agg, n_models: int,
                 reg: Optional[np.ndarray] = None,
                 l2_scale: Optional[np.ndarray] = None,
                 weight_sum: Optional[float] = None,
                 extra_args: tuple = (), y_stack: Optional[np.ndarray] = None,
                 y_dtype=None):
        if y_dtype is None:
            from cycloneml_tpu.dataset.instance import compute_dtype
            y_dtype = compute_dtype()
        view = (_StackedShardView.tiled(sds, n_models, y_dtype)
                if y_stack is None
                else _StackedShardView.from_stack(sds, y_stack, y_dtype))
        super().__init__(view, agg, l2_reg_fn=None, weight_sum=weight_sum,
                         extra_args=extra_args)
        self.n_models = int(n_models)
        self.reg = (np.zeros(self.n_models) if reg is None
                    else np.asarray(reg, dtype=np.float64))
        self.l2_scale = (None if l2_scale is None
                         else np.asarray(l2_scale, dtype=np.float64))

    def __call__(self, coef_stack: np.ndarray):
        from cycloneml_tpu.ml.optim.loss import stacked_host_l2
        self.n_evals += 1
        out = self.sweep(*self._extras, np.asarray(coef_stack))
        loss = np.asarray(out["loss"], dtype=np.float64) / self.weight_sum
        grad = np.asarray(out["grad"], dtype=np.float64) / self.weight_sum
        loss, grad = stacked_host_l2(loss, grad, coef_stack, self.reg,
                                     self.l2_scale)
        if hasattr(self._ctx, "record_step"):
            # one streamed epoch serves all K models
            self._ctx.record_step({"loss": float(np.mean(loss)),
                                   "n_models": self.n_models,
                                   "oocore_shards": self._sds.n_shards})
        return loss, grad

    def _shard_avals(self, n_coef: int, concrete: bool = False) -> tuple:
        import jax
        from cycloneml_tpu.dataset.instance import compute_dtype
        view = self._sds
        xdt = np.dtype(view.x_dtype)
        ydt = np.dtype(view.y_dtype)
        adt = np.dtype(compute_dtype())
        rt = view.ctx.mesh_runtime
        K = self.n_models
        if concrete:
            x = rt.device_put_sharded_rows(
                np.zeros((view.pad_rows, view.n_features), dtype=xdt))
            y = rt.device_put_sharded_rows(
                np.zeros((view.pad_rows, K), dtype=ydt))
            w = rt.device_put_sharded_rows(np.zeros(view.pad_rows, dtype=adt))
        else:
            x = jax.ShapeDtypeStruct((view.pad_rows, view.n_features), xdt,
                                     sharding=rt.data_sharding(1))
            y = jax.ShapeDtypeStruct((view.pad_rows, K), ydt,
                                     sharding=rt.data_sharding(1))
            w = jax.ShapeDtypeStruct((view.pad_rows,), adt,
                                     sharding=rt.data_sharding(0))
        return (x, y, w, *self._extras,
                np.zeros((K, n_coef), dtype=np.float64))
