"""Streaming fit routing + the streamed mini-batch SGD.

Mode selection (``cyclone.oocore.mode``):

- ``auto`` (default): in-core fits run unchanged, but when the PR-5 memory
  budget guard's chunk-halving bottoms out at deviceChunk=1 with the
  program STILL over budget, eligible estimators degrade to the streaming
  epoch engine instead of warn-proceeding (or raising under
  ``budgetAction=raise``) — graceful at any data:memory ratio, the
  capability bar of the reference's spill discipline (PAPER.md layer 3c).
- ``force``: every eligible dense fit streams (each loss/grad evaluation
  is one double-buffered epoch) — the mode for datasets ingested straight
  into a :class:`~cycloneml_tpu.oocore.shards.StreamingDataset`.
- ``off``: pre-oocore behavior everywhere.

The degradation signal is ``observe.costs.OutOfCoreRequired``: raised by
the chunk guard ONLY when the optimizer's owner declared a streaming
fallback (``DeviceLBFGS.oocore_fallback``), caught by the estimator, never
visible to user code.
"""

from __future__ import annotations

from typing import Callable, Optional, Tuple

import numpy as np

from cycloneml_tpu.observe.costs import OutOfCoreRequired  # noqa: F401  (re-export)
from cycloneml_tpu.oocore.objective import StreamingLossFunction
from cycloneml_tpu.oocore.shards import StreamingDataset
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)


def streaming_mode(conf) -> str:
    from cycloneml_tpu.conf import OOCORE_MODE
    if conf is None:
        return "auto"
    return str(conf.get(OOCORE_MODE))


def degrade_allowed(ctx) -> bool:
    """Whether the budget guard may degrade to streaming (mode=auto|force)."""
    return streaming_mode(getattr(ctx, "conf", None)) != "off"


def shard_dataset(ds, shard_rows: Optional[int] = None,
                  spill_dir: Optional[str] = None) -> StreamingDataset:
    """Spill an in-core dataset to an out-of-core shard set (the degrade
    path's bridge; bounded per-shard staging — see
    :meth:`StreamingDataset.from_dataset`). Routed through the
    content-hash shard-set cache: a CV fold or warm-start re-fit over the
    same dataset ATTACHES to the existing spill — 0 spill-write bytes —
    instead of re-blocking it (``cyclone.oocore.cacheBytes=0`` restores
    the direct build-and-own path)."""
    from cycloneml_tpu.oocore.cache import shard_set_cache
    return shard_set_cache().attach(ds, shard_rows=shard_rows,
                                    spill_dir=spill_dir)


class StreamingGradientDescent:
    """Mini-batch SGD over streamed epochs — the out-of-core twin of
    ``ml/optim/gradient_descent.GradientDescent``.

    Per step, the gradient is the PARTIAL-SWEEP ACCUMULATION: every shard's
    psummed ``{loss, grad, count}`` folded into one host-f64 sum, then one
    Updater step — identical update math to the in-core optimizer, with
    the treeAggregate dispatch replaced by an epoch. ``miniBatchFraction``
    < 1 folds a per-shard Bernoulli row mask into the weights (keyed on
    seed × step × shard × mesh position, so every row samples
    independently and a fixed seed replays exactly); shapes stay static,
    as in-core.
    """

    def __init__(self, step_size: float = 1.0, num_iterations: int = 100,
                 reg_param: float = 0.0, mini_batch_fraction: float = 1.0,
                 updater=None, convergence_tol: float = 0.001, seed: int = 0,
                 shuffle: Optional[bool] = None):
        from cycloneml_tpu.ml.optim.gradient_descent import SimpleUpdater
        self.step_size = step_size
        self.num_iterations = num_iterations
        self.reg_param = reg_param
        self.mini_batch_fraction = mini_batch_fraction
        self.updater = updater or SimpleUpdater()
        self.convergence_tol = convergence_tol
        self.seed = seed
        # per-epoch shard-order shuffling (cyclone.oocore.shuffle when
        # None): a seeded permutation keyed on seed x step — fixed seed
        # replays exactly; the epoch-accumulated gradient is
        # order-invariant up to float summation order (parity-pinned)
        self.shuffle = shuffle

    def optimize(self, sds: StreamingDataset, agg: Callable, x0: np.ndarray
                 ) -> Tuple[np.ndarray, list]:
        """Returns (weights, stochastic loss history), the in-core
        ``GradientDescent.optimize`` contract."""
        import jax
        import jax.numpy as jnp

        from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS
        from cycloneml_tpu.observe import tracing

        frac = self.mini_batch_fraction
        seed = self.seed
        shuffle = self.shuffle
        if shuffle is None:
            from cycloneml_tpu.conf import OOCORE_SHUFFLE
            conf = getattr(sds.ctx, "conf", None)
            shuffle = bool(conf.get(OOCORE_SHUFFLE)) \
                if conf is not None else False

        def epoch_order(step: int):
            if not shuffle:
                return None
            # keyed on seed x step: every epoch walks its own seeded
            # permutation, and a re-run at the same seed replays it
            return np.random.RandomState(
                (seed * 1000003 + step) % (2 ** 32)).permutation(
                    sds.n_shards)

        if frac < 1.0:
            def fn(x, y, w, coef, step, shard):
                key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
                key = jax.random.fold_in(key, shard)
                key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
                key = jax.random.fold_in(key,
                                         jax.lax.axis_index(REPLICA_AXIS))
                w = w * (jax.random.uniform(key, w.shape) < frac)
                return agg(x, y, w, coef)
            loss_fn = StreamingLossFunction(sds, fn)
        else:
            loss_fn = StreamingLossFunction(sds, agg)

        w = np.asarray(x0, dtype=np.float64).copy()
        history: list = []
        _, reg = self.updater.compute(w, np.zeros_like(w), 0.0, 1,
                                      self.reg_param)
        updates = 0
        for t in range(1, self.num_iterations + 1):
            with tracing.span("dispatch", "gd.step", evals=1, streamed=True):
                if frac < 1.0:
                    # step + shard index ride as per-dispatch arguments so
                    # each shard samples its own Bernoulli mask (keyed on
                    # the TRUE shard index — shuffle-invariant)
                    out = loss_fn.sweep(
                        jnp.asarray(w, jnp.float32),
                        jnp.asarray(t, jnp.int32),
                        per_shard=lambda i: (jnp.asarray(i, jnp.int32),),
                        order=epoch_order(t))
                else:
                    out = loss_fn.sweep(jnp.asarray(w, jnp.float32),
                                        order=epoch_order(t))
            count = float(out["count"])
            if count <= 0:
                continue  # empty mini-batch: no update, no history entry
            loss = float(out["loss"]) / count
            grad = np.asarray(out["grad"], dtype=np.float64) / count
            history.append(loss + reg)
            prev_w = w
            w, reg = self.updater.compute(w, grad, self.step_size, t,
                                          self.reg_param)
            updates += 1
            if self.convergence_tol > 0 and updates > 1:
                delta = float(np.linalg.norm(w - prev_w))
                if delta < self.convergence_tol * max(
                        float(np.linalg.norm(prev_w)), 1.0):
                    logger.info(
                        "StreamingGradientDescent converged at iteration %d",
                        t)
                    break
        return w, history

    def optimize_stacked(self, sds: StreamingDataset, agg: Callable,
                         x0: np.ndarray,
                         y_stack: Optional[np.ndarray] = None
                         ) -> Tuple[np.ndarray, list]:
        """Model-axis twin of :meth:`optimize` — the streamed analog of
        ``StackedGradientDescent``: ``x0`` is ``(K, n)``, each step is ONE
        double-buffered epoch whose per-shard program is the vmapped
        aggregator, so K models ride every staged shard. ``y_stack``
        (``(K, n)``, optional) supplies per-model labels (OvR
        relabelings); without it every model sees the shard's own labels
        (grid fits). Per-model convergence masks freeze early-converged
        models exactly where their serial streamed run would stop, while
        the epochs keep serving the rest. Returns ``(weights (K, n),
        histories)``."""
        import jax
        import jax.numpy as jnp

        from cycloneml_tpu.mesh import DATA_AXIS, REPLICA_AXIS
        from cycloneml_tpu.ml.optim import aggregators
        from cycloneml_tpu.observe import tracing
        from cycloneml_tpu.oocore.objective import \
            StackedStreamingLossFunction

        frac = self.mini_batch_fraction
        seed = self.seed
        shuffle = self.shuffle
        if shuffle is None:
            from cycloneml_tpu.conf import OOCORE_SHUFFLE
            conf = getattr(sds.ctx, "conf", None)
            shuffle = bool(conf.get(OOCORE_SHUFFLE)) \
                if conf is not None else False

        def epoch_order(step: int):
            if not shuffle:
                return None
            return np.random.RandomState(
                (seed * 1000003 + step) % (2 ** 32)).permutation(
                    sds.n_shards)

        W = np.asarray(x0, dtype=np.float64).copy()
        n_models = W.shape[0]
        stacked = aggregators.stack_aggregator(agg)

        if frac < 1.0:
            def fn(x, y, w, coef, step, shard):
                # the row mask is drawn ONCE and shared across the model
                # axis (keyed on the TRUE shard index — shuffle- and
                # stack-invariant): each model sees the same sample
                # sequence its serial streamed run would
                key = jax.random.fold_in(jax.random.PRNGKey(seed), step)
                key = jax.random.fold_in(key, shard)
                key = jax.random.fold_in(key, jax.lax.axis_index(DATA_AXIS))
                key = jax.random.fold_in(key,
                                         jax.lax.axis_index(REPLICA_AXIS))
                w = w * (jax.random.uniform(key, w.shape) < frac)
                return stacked(x, y, w, coef)
            loss_fn = StackedStreamingLossFunction(
                sds, fn, n_models, y_stack=y_stack)
        else:
            loss_fn = StackedStreamingLossFunction(
                sds, stacked, n_models, y_stack=y_stack)

        histories: list = [[] for _ in range(n_models)]
        regs = np.zeros(n_models)
        for kk in range(n_models):
            _, regs[kk] = self.updater.compute(
                W[kk], np.zeros_like(W[kk]), 0.0, 1, self.reg_param)
        live = np.ones(n_models, dtype=bool)
        updates = np.zeros(n_models, dtype=np.int64)
        for t in range(1, self.num_iterations + 1):
            if not live.any():
                break
            with tracing.span("dispatch", "gd.step", evals=1, streamed=True,
                              n_models=n_models):
                if frac < 1.0:
                    out = loss_fn.sweep(
                        jnp.asarray(W, jnp.float32),
                        jnp.asarray(t, jnp.int32),
                        per_shard=lambda i: (jnp.asarray(i, jnp.int32),),
                        order=epoch_order(t))
                else:
                    out = loss_fn.sweep(jnp.asarray(W, jnp.float32),
                                        order=epoch_order(t))
            count = np.asarray(out["count"], dtype=np.float64)
            if float(count.max()) <= 0:
                continue  # empty mini-batch: no model updates
            loss = np.asarray(out["loss"], dtype=np.float64) / count
            grad = np.asarray(out["grad"], dtype=np.float64) / count[:, None]
            for kk in np.nonzero(live)[0]:
                histories[kk].append(loss[kk] + regs[kk])
                prev = W[kk].copy()
                W[kk], regs[kk] = self.updater.compute(
                    W[kk], grad[kk], self.step_size, t, self.reg_param)
                updates[kk] += 1
                if self.convergence_tol > 0 and updates[kk] > 1:
                    delta = float(np.linalg.norm(W[kk] - prev))
                    if delta < self.convergence_tol * max(
                            float(np.linalg.norm(prev)), 1.0):
                        live[kk] = False
                        logger.info(
                            "StreamingGradientDescent: model %d converged "
                            "at iteration %d (%d/%d still live)", kk, t,
                            int(live.sum()), n_models)
        return W, histories
