"""Double-buffered host→device shard pipeline.

The staging thread walks the shard files, pads each into the fixed
``(pad_rows, d)`` geometry and places it row-sharded on the mesh while the
consumer computes over the PREVIOUS shard — the roofline rationale
(Williams et al. 2009, PAPERS.md): an out-of-core sweep whose transfer
overlaps compute is bandwidth-bound, one that alternates them is
latency-bound. The bounded queue IS the ring: ``prefetchDepth`` staged
shards in flight, so device-resident copies are bounded at depth + 1 and
host staging at O(shard).

Fault surface: every staging attempt fires the ``oocore.stage`` injection
point (parallel/faults.py). Transient failures retry with seeded backoff
mid-epoch; permanent failures (resilience classification) abort the epoch
cleanly — the error surfaces on the consumer, the queue is drained, and
the staging thread exits. Never a hang, never a leaked thread.

Observability: each staged shard records a ``transfer``-kind
``oocore.stage`` span on the staging thread's timeline and each consumed
shard a ``dispatch``-kind ``oocore.shard`` span on the consumer's — in the
Chrome trace the two rows interleave, making the transfer/compute overlap
directly visible; ``oocore.bytes_staged`` is the cumulative byte counter
track (``make bench-oocore`` computes the overlap fraction from exactly
these spans).
"""

from __future__ import annotations

import queue
import random
import threading
import time
from typing import Optional

import numpy as np

from cycloneml_tpu.observe import attribution, tracing
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

_DONE = object()


class ShardStream:
    """Iterate device-placed ``(i, x, y, w)`` shards with prefetch.

    One pass over the shard set = one epoch. The consumer owns each
    yielded shard exactly once — the per-shard aggregation program DONATES
    the arrays (collectives.tree_aggregate(donate_rows=True)), so a shard's
    HBM is reclaimed the moment its dispatch leaves the host and the next
    shard's in-flight transfer lands in freed memory.
    """

    def __init__(self, sds, depth: Optional[int] = None,
                 max_retries: Optional[int] = None, order=None):
        from cycloneml_tpu.conf import (OOCORE_MAX_RETRIES,
                                        OOCORE_PREFETCH_DEPTH)
        conf = getattr(sds.ctx, "conf", None)
        if depth is None:
            depth = int(conf.get(OOCORE_PREFETCH_DEPTH)) \
                if conf is not None else 2
        if max_retries is None:
            max_retries = int(conf.get(OOCORE_MAX_RETRIES)) \
                if conf is not None else 3
        self._sds = sds
        # staging ORDER for this epoch (seeded permutation for streamed
        # SGD shuffling); each yielded item still carries the TRUE shard
        # index, so per-shard mask keys are order-invariant
        if order is None:
            self._order = list(range(sds.n_shards))
        else:
            self._order = [int(i) for i in order]
            if sorted(self._order) != list(range(sds.n_shards)):
                raise ValueError(
                    f"order must be a permutation of range({sds.n_shards})")
        self._max_retries = max(int(max_retries), 0)
        self._q: "queue.Queue" = queue.Queue(maxsize=max(int(depth), 1))
        self._stop = threading.Event()
        self.bytes_staged = 0
        # capture the CONSTRUCTING thread's attribution scope: staging runs
        # on the background thread, which never sees the job's scope stack
        # (same cross-thread capture as Tracer.record_span)
        self._scope = attribution.current_scope()
        self._rng = random.Random(1)  # seeded: chaos replays exactly
        self._thread = threading.Thread(
            target=self._produce, name="cyclone-oocore-stage", daemon=True)
        self._thread.start()

    # -- staging thread --------------------------------------------------------
    def _produce(self) -> None:
        from cycloneml_tpu.parallel.resilience import (backoff_delay,
                                                       classify_failure)
        try:
            for i in self._order:
                attempt = 0
                while True:
                    if self._stop.is_set():
                        return
                    try:
                        item = self._stage(i)
                        break
                    except Exception as exc:
                        kind = classify_failure(exc)
                        if kind == "transient" and attempt < self._max_retries:
                            attempt += 1
                            logger.warning(
                                "oocore: transient staging failure on shard "
                                "%d (attempt %d/%d): %s — backing off",
                                i, attempt, self._max_retries, exc)
                            tracing.instant("oocore.stage_retry", shard=i,
                                            attempt=attempt)
                            self._stop.wait(
                                backoff_delay(attempt, rng=self._rng))
                            continue
                        logger.error(
                            "oocore: %s staging failure on shard %d — "
                            "aborting the epoch: %s", kind, i, exc)
                        self._put((None, exc))
                        return
                if not self._put(item):
                    return
            self._put((_DONE, None))
        except BaseException as exc:  # staging thread must never die silent
            self._put((None, exc))

    def _host_stage(self, i: int):
        """Host half of one staging attempt: shard read + pad. This is
        the per-LANE work (disk/NIC — one bad spindle makes one slow
        lane), so it is what the elastic speculation layer races: both
        copies read the same shard file, making first-result-wins
        dedup bitwise by construction."""
        sds = self._sds
        x, y, w = sds.load_shard(i)
        m = x.shape[0]
        pad = sds.pad_rows - m
        if pad:
            # fresh padded blocks per shard (zero-weight tail rows,
            # masked out of every psum) — a reused staging buffer could
            # still be read by an in-flight transfer. y may be 2-D (the
            # stacked (rows, K) label matrix): pad rows, keep the model
            # axis
            x = np.concatenate(
                [x, np.zeros((pad, x.shape[1]), dtype=x.dtype)])
            y = np.concatenate(
                [y, np.zeros((pad,) + y.shape[1:], dtype=y.dtype)])
            w = np.concatenate([w, np.zeros(pad, dtype=w.dtype)])
        return x, y, w, m

    def _stage(self, i: int):
        from cycloneml_tpu.elastic import speculation
        from cycloneml_tpu.observe import skew
        from cycloneml_tpu.parallel import faults
        # per-shard-lane staging time feeds the online straggler detector:
        # shard i revisits lane shard<i mod N> every epoch, so a lane that
        # is consistently slow (one bad disk/NIC/host in the staging path)
        # separates from the group median within a few epochs. The window
        # covers the WHOLE attempt — the chaos injection point included,
        # so an injected slow lane is observable skew, as a real one is.
        lane = f"shard{i % skew.OOCORE_SKEW_LANES}"
        t_skew = time.perf_counter()
        faults.inject("oocore.stage", shard=i)
        rt = self._sds.ctx.mesh_runtime
        with tracing.span("transfer", "oocore.stage", shard=i) as sp:
            # speculation gate (one global read when disarmed): a lane
            # with a latched straggler verdict re-dispatches its HOST
            # work concurrently — first result wins, duplicate deduped
            # bitwise (Spark speculation; elastic/speculation.py). The
            # device placement below happens ONCE, on the winner.
            x, y, w, m = speculation.maybe_speculate(
                "oocore.stage", lane, lambda: self._host_stage(i))
            xs = rt.device_put_sharded_rows(x)
            ys = rt.device_put_sharded_rows(y)
            ws = rt.device_put_sharded_rows(w)
            n_bytes = x.nbytes + y.nbytes + w.nbytes
            sp.annotate(bytes=n_bytes, rows=m)
        self.bytes_staged += n_bytes
        tracing.counter("oocore.bytes_staged", self.bytes_staged)
        attribution.charge(self._scope, h2dBytes=n_bytes)
        skew.observe("oocore.stage", lane, time.perf_counter() - t_skew)
        return (i, xs, ys, ws)

    def _put(self, item) -> bool:
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    # -- consumer --------------------------------------------------------------
    def __iter__(self):
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        item = self._q.get()
        if isinstance(item, tuple) and len(item) == 2:
            tag, err = item
            if tag is _DONE:
                self.close()
                raise StopIteration
            if tag is None:
                self.close()
                raise err
        return item

    def close(self) -> None:
        """Stop staging, drain the queue (releasing device shard refs),
        join the thread. Idempotent; safe mid-epoch (the abort path).
        Drains again AFTER the join: a put already in flight when stop was
        set can land after the first drain, and a retained tuple would
        keep one staged shard's device buffers alive past close()."""
        self._stop.set()
        self._drain()
        self._thread.join(timeout=10.0)
        self._drain()

    def _drain(self) -> None:
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break

    def __enter__(self) -> "ShardStream":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False
