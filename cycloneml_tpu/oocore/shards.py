"""Host shard store: the out-of-core dataset representation.

A :class:`StreamingDataset` is what an estimator trains on when the design
matrix must never fully materialize in device memory — the analog of the
reference's disk-backed block store feeding tasks one partition at a time
(ref BlockManager / UnifiedMemoryManager spill discipline, PAPER.md layer
3c). It is a sequence of bounded npz shard files (data-tier packed X,
accumulator-tier y/w) plus the ONE-pass statistics every fit path needs
(Summarizer moments, label histogram, label moments, weight sum) —
harvested while the shards are WRITTEN, so no extra epoch is spent on
stats and no O(n) host vector survives construction.

Geometry contract: every shard is padded — at STAGE time, not on disk —
to one fixed ``(pad_rows, d)`` block (zero-weight rows, masked out of the
psums exactly like the in-core padding), so a single compiled per-shard
aggregation program serves the whole epoch and host staging peaks at
O(pad_rows · d), never O(n · d).
"""

from __future__ import annotations

import os
import tempfile
import threading
from dataclasses import dataclass
from typing import Iterable, List, Optional

import numpy as np

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: labels above this are not class indices — histogram harvesting stops
_MAX_CLASSES = 4096


@dataclass
class _Moments:
    """f64 running sums mirroring ``ml/stat/summarizer._moments`` (same
    masking: rows with w > 0 are 'present') plus the label-side sums the
    fit paths read (histogram for classifiers, y moments for regressors)."""

    d: int
    s1: np.ndarray = None
    s2: np.ndarray = None
    l1: np.ndarray = None
    nnz: np.ndarray = None
    mx: np.ndarray = None
    mn: np.ndarray = None
    w: float = 0.0
    w2: float = 0.0
    cnt: float = 0.0
    s1y: float = 0.0
    s2y: float = 0.0
    w_max: float = 0.0
    abs_all: np.ndarray = None
    histogram: Optional[np.ndarray] = None
    integral_labels: bool = True

    def __post_init__(self):
        self.s1 = np.zeros(self.d)
        self.s2 = np.zeros(self.d)
        self.l1 = np.zeros(self.d)
        self.nnz = np.zeros(self.d)
        self.mx = np.full(self.d, -np.inf)
        self.mn = np.full(self.d, np.inf)
        self.abs_all = np.zeros(self.d)
        self.histogram = np.zeros(0)

    def update(self, x: np.ndarray, y: np.ndarray, w: np.ndarray) -> None:
        # moments are taken from the DATA-TIER view of the rows (x is
        # already cast to storage width), so streamed stats match what an
        # in-core Summarizer pass over the same stored blocks computes
        x64 = np.asarray(x, dtype=np.float64)
        y64 = np.asarray(y, dtype=np.float64)
        w64 = np.asarray(w, dtype=np.float64)
        wcol = w64[:, None]
        present = w64 > 0
        self.s1 += (wcol * x64).sum(axis=0)
        self.s2 += (wcol * x64 * x64).sum(axis=0)
        self.l1 += (wcol * np.abs(x64)).sum(axis=0)
        self.w += float(w64.sum())
        self.w2 += float((w64 * w64).sum())
        self.cnt += float(present.sum())
        if present.any():
            xp = x64[present]
            self.nnz += (xp != 0).sum(axis=0)
            self.mx = np.maximum(self.mx, xp.max(axis=0))
            self.mn = np.minimum(self.mn, xp.min(axis=0))
        if x64.shape[0]:
            # ALL-row absmax (zero-weight rows included): the fp8 set
            # scale must dominate every stored value — an out-of-range
            # code is NaN, and 0 · NaN would still poison the psum
            self.abs_all = np.maximum(self.abs_all, np.abs(x64).max(axis=0))
        self.s1y += float((w64 * y64).sum())
        self.s2y += float((w64 * y64 * y64).sum())
        if w64.size:
            # max instance weight feeds the fp8 envelope probe's
            # multiplier-overflow heuristic (instance.fp8_probe_ok)
            self.w_max = max(self.w_max, float(w64.max()))
        if self.integral_labels:
            yp = y64[present]
            if yp.size and (np.any(yp != np.round(yp)) or yp.min() < 0
                            or yp.max() >= _MAX_CLASSES):
                self.integral_labels = False
            elif yp.size:
                hist = np.bincount(yp.astype(np.int64),
                                   weights=w64[present],
                                   minlength=len(self.histogram))
                if len(hist) > len(self.histogram):
                    self.histogram = np.pad(
                        self.histogram, (0, len(hist) - len(self.histogram)))
                self.histogram = self.histogram + hist


@dataclass
class _Shard:
    path: str
    rows: int


class StreamingDataset:
    """Disk-backed shard sequence + one-pass fit statistics.

    Quacks like the corner of :class:`InstanceDataset` the dense fit paths
    touch (``n_rows`` / ``n_features`` / ``shape`` / ``ctx`` /
    ``to_instance_dataset`` returning self), so ``est.fit(streaming_ds)``
    routes through the normal estimator entry and ``_fit_dataset``
    dispatches on the type. Shard files are OWNED: removed on
    :meth:`close` or GC.
    """

    def __init__(self, ctx, shards: List[_Shard], n_features: int,
                 pad_rows: int, moments: _Moments, spill_dir: str,
                 owns_dir: bool, x_dtype=None,
                 x_scale: Optional[np.ndarray] = None):
        self.ctx = ctx
        self._shards = shards
        self.n_features = int(n_features)
        self.n_rows = int(sum(s.rows for s in shards))
        self.pad_rows = int(pad_rows)
        self._moments = moments
        self._dir = spill_dir
        self._owns_dir = owns_dir
        # the STREAM dtype: what load_shard/ShardStream stage (fp8 shard
        # sets stage 1-byte e4m3 codes); per-column dequant scale rides
        # alongside, folded into the aggregator read as in-core fp8 fits do
        self.x_dtype = np.dtype(x_dtype) if x_dtype is not None \
            else np.dtype(np.float64)
        self.x_scale: Optional[np.ndarray] = (
            np.asarray(x_scale, dtype=np.float64)
            if x_scale is not None else None)
        self._closed = False
        self._close_lock = threading.Lock()

    # -- construction ----------------------------------------------------------
    @classmethod
    def from_chunks(cls, ctx, chunks: Iterable, n_features: int,
                    shard_rows: Optional[int] = None,
                    spill_dir: Optional[str] = None,
                    stream_dtype: Optional[str] = None,
                    x_scale: Optional[np.ndarray] = None
                    ) -> "StreamingDataset":
        """Build from an iterator of ``(x, y_or_None, w_or_None)`` host
        chunks — the ``dataset/io.py`` chunked-reader contract — WITHOUT
        ever holding more than one shard of rows host-side. Chunks are
        re-blocked to ``cyclone.oocore.shardRows`` boundaries; X is cast to
        the stream tier before it is written (bf16 shards carry half the
        bytes of f32, fp8 shards half again, so the host→device stream —
        the out-of-core fit's bandwidth bill — halves per rung,
        docs/mixed-precision.md).

        ``stream_dtype`` overrides ``cyclone.oocore.streamDtype`` for this
        build. When the resolved rung is fp8, the write pass stays one
        rung wider (the set-level absmax is unknown mid-stream) and a
        FINALIZE pass requantizes every shard with ONE set-level
        per-column scale — decided by the materialization-time envelope
        probe over the write-pass moments, per shard SET, not per shard:
        one geometry, one dequant fold, one compiled program per epoch.
        A probe refusal stays at the wider rung, surfaced as a
        ``PrecisionFallback`` event — automatic and visible, never silent.

        ``x_scale`` is the PRE-QUANTIZED spill contract
        (:meth:`from_dataset` over an fp8 in-core dataset): chunks carry
        e4m3 codes whose real value is ``code * x_scale``; they are
        written through unchanged, the moments are harvested from the
        dequantized VIEW (fit statistics are about values, not codes),
        and the probe is skipped — the in-core rail already ran it."""
        from cycloneml_tpu.conf import OOCORE_DIR, OOCORE_SHARD_ROWS
        from cycloneml_tpu.dataset.instance import compute_dtype
        conf = getattr(ctx, "conf", None)
        if shard_rows is None:
            shard_rows = int(conf.get(OOCORE_SHARD_ROWS)) if conf is not None \
                else 65536
        shard_rows = max(int(shard_rows), 1)
        base = (conf.get(OOCORE_DIR) if conf is not None else "") or ""
        # only a dir we minted ourselves is removed on close; a
        # caller-provided directory is theirs
        owns_dir = spill_dir is None
        spill_dir = spill_dir or tempfile.mkdtemp(
            prefix="oocore-", dir=base or None)
        os.makedirs(spill_dir, exist_ok=True)

        if x_scale is not None:
            import ml_dtypes
            xdt = np.dtype(ml_dtypes.float8_e4m3fn)
            fp8_candidate = False
            x_scale = np.asarray(x_scale, dtype=np.float64)
        else:
            xdt, fp8_candidate = _resolve_stream_dtype(conf, stream_dtype)
        ydt = np.dtype(compute_dtype())
        moments = _Moments(int(n_features))
        shards: List[_Shard] = []
        carry: List[tuple] = []   # [(x, y, w)] pieces, < shard_rows total
        carry_rows = 0

        def flush(pieces, rows):
            xs = np.concatenate([p[0] for p in pieces]) if len(pieces) > 1 \
                else pieces[0][0]
            ys = np.concatenate([p[1] for p in pieces]) if len(pieces) > 1 \
                else pieces[0][1]
            ws = np.concatenate([p[2] for p in pieces]) if len(pieces) > 1 \
                else pieces[0][2]
            path = os.path.join(spill_dir, f"shard-{len(shards):06d}.npz")
            from cycloneml_tpu.dataset.dataset import _npz_pack
            x_packed, x_dtype = _npz_pack(xs)
            np.savez(path, x=x_packed, x_dtype=x_dtype, y=ys, w=ws)
            shards.append(_Shard(path, rows))
            if x_scale is not None:
                # codes are not values: stats come from the dequant view
                xs = np.asarray(xs, dtype=np.float64) * x_scale[None, :]
            moments.update(xs, ys, ws)

        for ci, (cx, cy, cw) in enumerate(chunks):
            cx = np.ascontiguousarray(cx, dtype=xdt)
            m = cx.shape[0]
            if cx.ndim != 2 or cx.shape[1] != n_features:
                raise ValueError(f"chunk {ci} has shape {cx.shape}, "
                                 f"expected (rows, {n_features})")
            cy = (np.zeros(m, dtype=ydt) if cy is None
                  else np.asarray(cy, dtype=ydt))
            cw = (np.ones(m, dtype=ydt) if cw is None
                  else np.asarray(cw, dtype=ydt))
            if len(cy) != m or len(cw) != m:
                raise ValueError(
                    f"chunk {ci}: y/w lengths ({len(cy)}/{len(cw)}) != "
                    f"x rows ({m})")
            lo = 0
            while lo < m:
                take = min(m - lo, shard_rows - carry_rows)
                carry.append((cx[lo:lo + take], cy[lo:lo + take],
                              cw[lo:lo + take]))
                carry_rows += take
                lo += take
                if carry_rows >= shard_rows:
                    flush(carry, carry_rows)
                    carry, carry_rows = [], 0
        if carry_rows:
            flush(carry, carry_rows)
        if not shards:
            raise ValueError("empty chunk stream: nothing to shard")

        pad_rows = _pad_geometry(ctx, max(s.rows for s in shards))
        sds = cls(ctx, shards, n_features, pad_rows, moments, spill_dir,
                  owns_dir, x_dtype=xdt, x_scale=x_scale)
        if fp8_candidate:
            _finalize_fp8(sds)
        return sds

    @classmethod
    def from_dataset(cls, ds, shard_rows: Optional[int] = None,
                     spill_dir: Optional[str] = None) -> "StreamingDataset":
        """Spill an in-core :class:`InstanceDataset` into a shard set (the
        budget-guard degradation path: the DATA already fits — it is the
        fit PROGRAM whose predicted peak HBM does not). Rows are pulled in
        bounded per-shard slices — O(shard) host staging, the graftlint
        JX018 pass idiom — with interleaved padding rows dropped via the
        dataset's own valid mask.

        An fp8 in-core dataset spills its 1-byte e4m3 CODES directly,
        carrying the per-column dequant scale onto the shard set — the
        in-core envelope probe already admitted this data to the fp8
        rung, so the stream keeps it (and keeps the halved byte bill).
        Only a ``streamDtype=bfloat16`` pin forces the codes back up,
        visibly (``PrecisionFallback``)."""
        from cycloneml_tpu.conf import OOCORE_SHARD_ROWS
        conf = getattr(ds.ctx, "conf", None)
        x_scale = getattr(ds, "x_scale", None)
        if x_scale is not None and _stream_intent(conf) == "bfloat16":
            # the stream is PINNED to the bf16 rung: the codes must leave
            # the fp8 tier before sharding — visibly, never silently
            from cycloneml_tpu.dataset.dataset import fp8_fallback
            ds = fp8_fallback(
                ds, "StreamingDataset.from_dataset",
                "cyclone.oocore.streamDtype=bfloat16 pins the stream to "
                "the bf16 rung")
            x_scale = None
        if shard_rows is None:
            shard_rows = int(conf.get(OOCORE_SHARD_ROWS)) if conf is not None \
                else 65536
        shard_rows = max(int(shard_rows), 1)

        n_pad = int(ds.x.shape[0])
        mask = ds._valid_mask
        y_host = ds.y_host()
        w_host = ds.w_host()

        def chunks():
            for lo in range(0, n_pad, shard_rows):
                hi = lo + min(shard_rows, n_pad - lo)
                xs = np.asarray(ds.x[lo:hi])
                ys = np.asarray(y_host[lo:hi], dtype=np.float64)
                ws = np.asarray(w_host[lo:hi], dtype=np.float64)
                if mask is not None:
                    keep = mask[lo:hi]
                else:
                    keep = np.zeros(hi - lo, dtype=bool)
                    keep[: max(0, min(ds.n_rows, hi) - lo)] = True
                if not keep.all():
                    xs, ys, ws = xs[keep], ys[keep], ws[keep]
                if len(ys):
                    yield xs, ys, ws

        return cls.from_chunks(ds.ctx, chunks(), ds.n_features,
                               shard_rows=shard_rows, spill_dir=spill_dir,
                               x_scale=x_scale)

    # -- InstanceDataset-shaped surface ---------------------------------------
    @property
    def shape(self):
        return (self.n_rows, self.n_features)

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    def to_instance_dataset(self, features_col=None, label_col=None,
                            weight_col=None, dtype=None,
                            fp8_capable: bool = False) -> "StreamingDataset":
        """Estimator bridge parity with :class:`InstanceDataset`: a
        StreamingDataset is already placed (on disk); column/dtype
        concepts do not apply. The fp8 opt-in DOES: an fp8 shard set
        handed to a consumer that has not declared quantized-storage
        capability re-spills at the bf16 rung (PrecisionFallback event) —
        an estimator that would read raw e4m3 codes as values must never
        see them, the same contract as ``instance.data_dtype``."""
        if self.x_scale is not None and not fp8_capable:
            _precision_fallback_event(
                self.ctx, "StreamingDataset.to_instance_dataset",
                "the consumer is not fp8-capable: e4m3 codes would be "
                "read as values", str(self.x_dtype), "bfloat16")
            scale = self.x_scale

            def chunks():
                for i in range(self.n_shards):
                    x, y, w = self.load_shard(i)
                    yield (np.asarray(x, dtype=np.float64) * scale[None, :],
                           y, w)

            return StreamingDataset.from_chunks(
                self.ctx, chunks(), self.n_features,
                shard_rows=max(s.rows for s in self._shards),
                stream_dtype="bfloat16")
        return self

    # -- one-pass statistics ---------------------------------------------------
    @property
    def weight_sum(self) -> float:
        return self._moments.w

    def summary(self):
        """Summarizer-equivalent :class:`SummaryStats` from the write-pass
        moments — the streamed fit never pays a stats epoch."""
        from cycloneml_tpu.ml.stat.summarizer import SummaryStats
        m = self._moments
        mean = m.s1 / m.w if m.w > 0 else np.zeros(self.n_features)
        denom = m.w - m.w2 / m.w if m.w > 0 else 0.0
        if denom > 0:
            variance = np.maximum((m.s2 - m.w * mean * mean) / denom, 0.0)
        else:
            variance = np.zeros_like(mean)
        return SummaryStats(
            mean=mean, variance=variance, count=int(round(m.cnt)),
            num_nonzeros=m.nnz.copy(), max=m.mx.copy(), min=m.mn.copy(),
            norm_l1=m.l1.copy(), norm_l2=np.sqrt(np.maximum(m.s2, 0.0)),
            sum=m.s1.copy(), weight_sum=m.w)

    def label_histogram(self) -> np.ndarray:
        """Weighted class histogram (f64) when labels are class indices;
        raises for non-integral labels (regression datasets)."""
        if not self._moments.integral_labels:
            raise ValueError(
                "labels are not class indices; streamed classification "
                "requires integral labels in [0, 4096)")
        return self._moments.histogram.copy()

    @property
    def num_classes(self) -> int:
        return max(len(self._moments.histogram), 2) \
            if self._moments.integral_labels else 0

    def y_moments(self):
        """``(Σwy, Σwy², Σw²)`` — what the LinearRegression label-std pass
        computes in-core with one psum."""
        m = self._moments
        return m.s1y, m.s2y, m.w2

    # -- shard access (the stream's supplier) ---------------------------------
    def load_shard(self, i: int):
        """Host arrays of shard ``i`` (unpadded; X at data-tier width)."""
        from cycloneml_tpu.dataset.dataset import _npz_unpack
        s = self._shards[i]
        z = np.load(s.path)
        x = _npz_unpack(z["x"], z.get("x_dtype", ""))
        return x, z["y"], z["w"]

    def shard_nbytes(self, i: int) -> int:
        s = self._shards[i]
        try:
            return os.path.getsize(s.path)
        except OSError:
            return 0

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        # latch under the lock: explicit close races __del__ (GC thread),
        # and both passing the check would double-unlink the spill files
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        for s in self._shards:
            try:
                os.unlink(s.path)
            except OSError:
                pass
        if self._owns_dir:
            try:
                os.rmdir(self._dir)
            except OSError:
                pass

    def __del__(self):  # dropped shard sets must not leak the spill dir
        try:
            self.close()
        except Exception:
            pass


def _pad_geometry(ctx, max_shard_rows: int) -> int:
    """Padded rows per staged shard: the max shard rounded up to a
    sublane-friendly multiple of the mesh's data parallelism, so
    ``device_put_sharded_rows`` splits every staged block evenly and one
    compiled program serves every shard."""
    rt = ctx.mesh_runtime
    unit = 8 * int(rt.data_parallelism)
    return ((max(int(max_shard_rows), 1) + unit - 1) // unit) * unit


def _stream_intent(conf, override: Optional[str] = None) -> str:
    """The configured stream rung: 'auto' | 'bfloat16' | 'float8'."""
    if override is not None:
        return str(override)
    if conf is None:
        return "auto"
    from cycloneml_tpu.conf import OOCORE_STREAM_DTYPE
    return str(conf.get(OOCORE_STREAM_DTYPE))


def _resolve_stream_dtype(conf, override: Optional[str] = None):
    """Resolve ``cyclone.oocore.streamDtype`` to ``(write_dtype,
    fp8_candidate)`` for a fresh spill. ``write_dtype`` is what the WRITE
    pass stores — one rung wider than fp8 when fp8 is the candidate,
    because the set-level scale does not exist until every row has passed
    through the moments; the finalize pass requantizes (or refuses, per
    the envelope probe). 'auto' follows ``cyclone.data.dtype`` including
    its fp8 tiers — the stream is an fp8-capable consumer: the dequant
    scale folds into the aggregator read exactly as the in-core fit's."""
    from cycloneml_tpu.dataset.instance import (compute_dtype, data_dtype,
                                                is_fp8_dtype)
    intent = _stream_intent(conf, override)
    if intent == "bfloat16":
        import ml_dtypes
        return np.dtype(ml_dtypes.bfloat16), False
    if intent == "float8":
        fp8 = True
    else:  # auto: follow the data tier, fp8-capable
        fp8 = is_fp8_dtype(data_dtype(conf, fp8_capable=True))
        if not fp8:
            return np.dtype(data_dtype(conf)), False
    # fp8 candidate: write one rung wider (f64 under the x64 parity
    # config so requantization sees pre-tier values, bf16 otherwise)
    if compute_dtype() is np.float64:
        return np.dtype(np.float64), fp8
    import ml_dtypes
    return np.dtype(ml_dtypes.bfloat16), fp8


def _finalize_fp8(sds: StreamingDataset) -> None:
    """The materialization-time envelope probe + set-level requantize.

    Decides fp8-vs-bf16 for the shard SET, not per shard: ONE per-column
    scale (``absmax / FP8_MAX`` from the write-pass moments) serves every
    shard, so one geometry and one compiled program serve the epoch and
    the dequant fold is a single replicated (d,) vector — exactly the
    in-core fp8 fit's arrangement. The probe runs on the same write-pass
    moments (``instance.fp8_probe_ok``: scale-spread + multiplier
    overflow, zero extra data passes); a refusal keeps the shards at the
    write rung and posts ``PrecisionFallback`` — automatic and visible.
    On success each shard is rewritten in place, one shard resident at a
    time (O(shard) host peak, the JX018 bound)."""
    from cycloneml_tpu.dataset.dataset import _npz_pack
    from cycloneml_tpu.dataset.instance import (FP8_MAX, fp8_probe_ok,
                                                quantize_fp8)
    m = sds._moments
    absmax = np.maximum(np.abs(m.mx), np.abs(m.mn))
    absmax = np.where(np.isfinite(absmax), absmax, 0.0)
    stats = sds.summary()
    std = np.sqrt(np.asarray(stats.variance, dtype=np.float64))
    probe_ratio = np.where(std > 0, absmax / np.where(std > 0, std, 1.0),
                           0.0)
    reason = fp8_probe_ok(stats, w_max=m.w_max or None,
                          probe_ratio=probe_ratio)
    if reason is not None:
        _precision_fallback_event(
            sds.ctx, "StreamingDataset", reason, "float8_e4m3fn",
            str(sds.x_dtype))
        return
    scale = np.where(m.abs_all > 0, m.abs_all / FP8_MAX, 1.0)
    # re-harvest the moments from the DEQUANTIZED view in the same pass:
    # fit statistics must describe the values the fit will actually read
    # (codes ∘ scale), exactly as the in-core Summarizer sees a quantized
    # dataset — write-rung stats would hand the optimizer a subtly
    # different standardization than the data it streams
    requant = _Moments(sds.n_features)
    for i, s in enumerate(sds._shards):
        x, y, w = sds.load_shard(i)
        x8, _, _ = quantize_fp8(x, scale=scale)
        x_packed, x_dtype = _npz_pack(x8)
        np.savez(s.path, x=x_packed, x_dtype=x_dtype, y=y, w=w)
        requant.update(np.asarray(x8, dtype=np.float64) * scale[None, :],
                       y, w)
    sds._moments = requant
    sds.x_scale = scale
    sds.x_dtype = np.dtype(x8.dtype)
    logger.info(
        "oocore: shard set requantized to float8_e4m3fn (%d shards, "
        "set-level per-column scale)", sds.n_shards)


def _precision_fallback_event(ctx, estimator: str, reason: str,
                              from_dtype: str, to_dtype: str) -> None:
    """Surface a streaming-tier precision decision the way the in-core
    ``dataset.fp8_fallback`` does — warning log, ``precision.fallback``
    tracing instant (the ``FitProfile.fp8_fallbacks`` counter), and a
    ``PrecisionFallback`` event on the context bus — without requiring an
    :class:`InstanceDataset` to dequantize."""
    from cycloneml_tpu.observe import tracing
    logger.warning("%s: falling back from %s to %s storage — %s",
                   estimator, from_dtype, to_dtype, reason)
    tracing.instant("precision.fallback", estimator=estimator,
                    reason=reason, from_dtype=from_dtype)
    bus = getattr(ctx, "listener_bus", None)
    if bus is not None:
        from cycloneml_tpu.util.events import PrecisionFallback
        try:
            bus.post(PrecisionFallback(estimator=estimator,
                                       from_dtype=from_dtype,
                                       to_dtype=to_dtype, reason=reason))
        except Exception:
            pass  # a stopped bus must not fail the fit
