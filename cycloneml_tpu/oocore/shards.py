"""Host shard store: the out-of-core dataset representation.

A :class:`StreamingDataset` is what an estimator trains on when the design
matrix must never fully materialize in device memory — the analog of the
reference's disk-backed block store feeding tasks one partition at a time
(ref BlockManager / UnifiedMemoryManager spill discipline, PAPER.md layer
3c). It is a sequence of bounded npz shard files (data-tier packed X,
accumulator-tier y/w) plus the ONE-pass statistics every fit path needs
(Summarizer moments, label histogram, label moments, weight sum) —
harvested while the shards are WRITTEN, so no extra epoch is spent on
stats and no O(n) host vector survives construction.

Geometry contract: every shard is padded — at STAGE time, not on disk —
to one fixed ``(pad_rows, d)`` block (zero-weight rows, masked out of the
psums exactly like the in-core padding), so a single compiled per-shard
aggregation program serves the whole epoch and host staging peaks at
O(pad_rows · d), never O(n · d).
"""

from __future__ import annotations

import os
import tempfile
import threading
from dataclasses import dataclass
from typing import Iterable, List, Optional

import numpy as np

from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: labels above this are not class indices — histogram harvesting stops
_MAX_CLASSES = 4096


@dataclass
class _Moments:
    """f64 running sums mirroring ``ml/stat/summarizer._moments`` (same
    masking: rows with w > 0 are 'present') plus the label-side sums the
    fit paths read (histogram for classifiers, y moments for regressors)."""

    d: int
    s1: np.ndarray = None
    s2: np.ndarray = None
    l1: np.ndarray = None
    nnz: np.ndarray = None
    mx: np.ndarray = None
    mn: np.ndarray = None
    w: float = 0.0
    w2: float = 0.0
    cnt: float = 0.0
    s1y: float = 0.0
    s2y: float = 0.0
    histogram: Optional[np.ndarray] = None
    integral_labels: bool = True

    def __post_init__(self):
        self.s1 = np.zeros(self.d)
        self.s2 = np.zeros(self.d)
        self.l1 = np.zeros(self.d)
        self.nnz = np.zeros(self.d)
        self.mx = np.full(self.d, -np.inf)
        self.mn = np.full(self.d, np.inf)
        self.histogram = np.zeros(0)

    def update(self, x: np.ndarray, y: np.ndarray, w: np.ndarray) -> None:
        # moments are taken from the DATA-TIER view of the rows (x is
        # already cast to storage width), so streamed stats match what an
        # in-core Summarizer pass over the same stored blocks computes
        x64 = np.asarray(x, dtype=np.float64)
        y64 = np.asarray(y, dtype=np.float64)
        w64 = np.asarray(w, dtype=np.float64)
        wcol = w64[:, None]
        present = w64 > 0
        self.s1 += (wcol * x64).sum(axis=0)
        self.s2 += (wcol * x64 * x64).sum(axis=0)
        self.l1 += (wcol * np.abs(x64)).sum(axis=0)
        self.w += float(w64.sum())
        self.w2 += float((w64 * w64).sum())
        self.cnt += float(present.sum())
        if present.any():
            xp = x64[present]
            self.nnz += (xp != 0).sum(axis=0)
            self.mx = np.maximum(self.mx, xp.max(axis=0))
            self.mn = np.minimum(self.mn, xp.min(axis=0))
        self.s1y += float((w64 * y64).sum())
        self.s2y += float((w64 * y64 * y64).sum())
        if self.integral_labels:
            yp = y64[present]
            if yp.size and (np.any(yp != np.round(yp)) or yp.min() < 0
                            or yp.max() >= _MAX_CLASSES):
                self.integral_labels = False
            elif yp.size:
                hist = np.bincount(yp.astype(np.int64),
                                   weights=w64[present],
                                   minlength=len(self.histogram))
                if len(hist) > len(self.histogram):
                    self.histogram = np.pad(
                        self.histogram, (0, len(hist) - len(self.histogram)))
                self.histogram = self.histogram + hist


@dataclass
class _Shard:
    path: str
    rows: int


class StreamingDataset:
    """Disk-backed shard sequence + one-pass fit statistics.

    Quacks like the corner of :class:`InstanceDataset` the dense fit paths
    touch (``n_rows`` / ``n_features`` / ``shape`` / ``ctx`` /
    ``to_instance_dataset`` returning self), so ``est.fit(streaming_ds)``
    routes through the normal estimator entry and ``_fit_dataset``
    dispatches on the type. Shard files are OWNED: removed on
    :meth:`close` or GC.
    """

    def __init__(self, ctx, shards: List[_Shard], n_features: int,
                 pad_rows: int, moments: _Moments, spill_dir: str,
                 owns_dir: bool):
        self.ctx = ctx
        self._shards = shards
        self.n_features = int(n_features)
        self.n_rows = int(sum(s.rows for s in shards))
        self.pad_rows = int(pad_rows)
        self._moments = moments
        self._dir = spill_dir
        self._owns_dir = owns_dir
        self._closed = False
        self._close_lock = threading.Lock()

    # -- construction ----------------------------------------------------------
    @classmethod
    def from_chunks(cls, ctx, chunks: Iterable, n_features: int,
                    shard_rows: Optional[int] = None,
                    spill_dir: Optional[str] = None) -> "StreamingDataset":
        """Build from an iterator of ``(x, y_or_None, w_or_None)`` host
        chunks — the ``dataset/io.py`` chunked-reader contract — WITHOUT
        ever holding more than one shard of rows host-side. Chunks are
        re-blocked to ``cyclone.oocore.shardRows`` boundaries; X is cast to
        the data tier before it is written (bf16 shards carry half the
        bytes of f32, so the host→device stream — the out-of-core fit's
        bandwidth bill — is halved too, docs/mixed-precision.md)."""
        from cycloneml_tpu.conf import OOCORE_DIR, OOCORE_SHARD_ROWS
        from cycloneml_tpu.dataset.instance import compute_dtype, data_dtype
        conf = getattr(ctx, "conf", None)
        if shard_rows is None:
            shard_rows = int(conf.get(OOCORE_SHARD_ROWS)) if conf is not None \
                else 65536
        shard_rows = max(int(shard_rows), 1)
        base = (conf.get(OOCORE_DIR) if conf is not None else "") or ""
        # only a dir we minted ourselves is removed on close; a
        # caller-provided directory is theirs
        owns_dir = spill_dir is None
        spill_dir = spill_dir or tempfile.mkdtemp(
            prefix="oocore-", dir=base or None)
        os.makedirs(spill_dir, exist_ok=True)

        xdt = np.dtype(data_dtype(conf))
        ydt = np.dtype(compute_dtype())
        moments = _Moments(int(n_features))
        shards: List[_Shard] = []
        carry: List[tuple] = []   # [(x, y, w)] pieces, < shard_rows total
        carry_rows = 0

        def flush(pieces, rows):
            xs = np.concatenate([p[0] for p in pieces]) if len(pieces) > 1 \
                else pieces[0][0]
            ys = np.concatenate([p[1] for p in pieces]) if len(pieces) > 1 \
                else pieces[0][1]
            ws = np.concatenate([p[2] for p in pieces]) if len(pieces) > 1 \
                else pieces[0][2]
            path = os.path.join(spill_dir, f"shard-{len(shards):06d}.npz")
            from cycloneml_tpu.dataset.dataset import _npz_pack
            x_packed, x_dtype = _npz_pack(xs)
            np.savez(path, x=x_packed, x_dtype=x_dtype, y=ys, w=ws)
            shards.append(_Shard(path, rows))
            moments.update(xs, ys, ws)

        for ci, (cx, cy, cw) in enumerate(chunks):
            cx = np.ascontiguousarray(cx, dtype=xdt)
            m = cx.shape[0]
            if cx.ndim != 2 or cx.shape[1] != n_features:
                raise ValueError(f"chunk {ci} has shape {cx.shape}, "
                                 f"expected (rows, {n_features})")
            cy = (np.zeros(m, dtype=ydt) if cy is None
                  else np.asarray(cy, dtype=ydt))
            cw = (np.ones(m, dtype=ydt) if cw is None
                  else np.asarray(cw, dtype=ydt))
            if len(cy) != m or len(cw) != m:
                raise ValueError(
                    f"chunk {ci}: y/w lengths ({len(cy)}/{len(cw)}) != "
                    f"x rows ({m})")
            lo = 0
            while lo < m:
                take = min(m - lo, shard_rows - carry_rows)
                carry.append((cx[lo:lo + take], cy[lo:lo + take],
                              cw[lo:lo + take]))
                carry_rows += take
                lo += take
                if carry_rows >= shard_rows:
                    flush(carry, carry_rows)
                    carry, carry_rows = [], 0
        if carry_rows:
            flush(carry, carry_rows)
        if not shards:
            raise ValueError("empty chunk stream: nothing to shard")

        pad_rows = _pad_geometry(ctx, max(s.rows for s in shards))
        return cls(ctx, shards, n_features, pad_rows, moments, spill_dir,
                   owns_dir)

    @classmethod
    def from_dataset(cls, ds, shard_rows: Optional[int] = None,
                     spill_dir: Optional[str] = None) -> "StreamingDataset":
        """Spill an in-core :class:`InstanceDataset` into a shard set (the
        budget-guard degradation path: the DATA already fits — it is the
        fit PROGRAM whose predicted peak HBM does not). Rows are pulled in
        bounded per-shard slices — O(shard) host staging, the graftlint
        JX018 pass idiom — with interleaved padding rows dropped via the
        dataset's own valid mask."""
        from cycloneml_tpu.conf import OOCORE_SHARD_ROWS
        conf = getattr(ds.ctx, "conf", None)
        if getattr(ds, "x_scale", None) is not None:
            # the streaming engine shards at the bf16 rung: the per-shard
            # slices below read ds.x as VALUES, and fp8 e4m3 codes are
            # not values — spilling them unscaled would train a silently
            # per-column-mis-scaled model. Leave the fp8 tier visibly
            # (PrecisionFallback event) before sharding.
            from cycloneml_tpu.dataset.dataset import fp8_fallback
            ds = fp8_fallback(
                ds, "StreamingDataset.from_dataset",
                "the streaming engine shards at the bf16 rung")
        if shard_rows is None:
            shard_rows = int(conf.get(OOCORE_SHARD_ROWS)) if conf is not None \
                else 65536
        shard_rows = max(int(shard_rows), 1)

        n_pad = int(ds.x.shape[0])
        mask = ds._valid_mask
        y_host = ds.y_host()
        w_host = ds.w_host()

        def chunks():
            for lo in range(0, n_pad, shard_rows):
                hi = lo + min(shard_rows, n_pad - lo)
                xs = np.asarray(ds.x[lo:hi])
                ys = np.asarray(y_host[lo:hi], dtype=np.float64)
                ws = np.asarray(w_host[lo:hi], dtype=np.float64)
                if mask is not None:
                    keep = mask[lo:hi]
                else:
                    keep = np.zeros(hi - lo, dtype=bool)
                    keep[: max(0, min(ds.n_rows, hi) - lo)] = True
                if not keep.all():
                    xs, ys, ws = xs[keep], ys[keep], ws[keep]
                if len(ys):
                    yield xs, ys, ws

        return cls.from_chunks(ds.ctx, chunks(), ds.n_features,
                               shard_rows=shard_rows, spill_dir=spill_dir)

    # -- InstanceDataset-shaped surface ---------------------------------------
    @property
    def shape(self):
        return (self.n_rows, self.n_features)

    @property
    def n_shards(self) -> int:
        return len(self._shards)

    def to_instance_dataset(self, features_col=None, label_col=None,
                            weight_col=None, dtype=None,
                            fp8_capable: bool = False) -> "StreamingDataset":
        """Estimator bridge parity with :class:`InstanceDataset`: a
        StreamingDataset is already placed (on disk); column/dtype
        concepts (including the fp8 opt-in — shards stay at the bf16
        rung) do not apply."""
        return self

    # -- one-pass statistics ---------------------------------------------------
    @property
    def weight_sum(self) -> float:
        return self._moments.w

    def summary(self):
        """Summarizer-equivalent :class:`SummaryStats` from the write-pass
        moments — the streamed fit never pays a stats epoch."""
        from cycloneml_tpu.ml.stat.summarizer import SummaryStats
        m = self._moments
        mean = m.s1 / m.w if m.w > 0 else np.zeros(self.n_features)
        denom = m.w - m.w2 / m.w if m.w > 0 else 0.0
        if denom > 0:
            variance = np.maximum((m.s2 - m.w * mean * mean) / denom, 0.0)
        else:
            variance = np.zeros_like(mean)
        return SummaryStats(
            mean=mean, variance=variance, count=int(round(m.cnt)),
            num_nonzeros=m.nnz.copy(), max=m.mx.copy(), min=m.mn.copy(),
            norm_l1=m.l1.copy(), norm_l2=np.sqrt(np.maximum(m.s2, 0.0)),
            sum=m.s1.copy(), weight_sum=m.w)

    def label_histogram(self) -> np.ndarray:
        """Weighted class histogram (f64) when labels are class indices;
        raises for non-integral labels (regression datasets)."""
        if not self._moments.integral_labels:
            raise ValueError(
                "labels are not class indices; streamed classification "
                "requires integral labels in [0, 4096)")
        return self._moments.histogram.copy()

    @property
    def num_classes(self) -> int:
        return max(len(self._moments.histogram), 2) \
            if self._moments.integral_labels else 0

    def y_moments(self):
        """``(Σwy, Σwy², Σw²)`` — what the LinearRegression label-std pass
        computes in-core with one psum."""
        m = self._moments
        return m.s1y, m.s2y, m.w2

    # -- shard access (the stream's supplier) ---------------------------------
    def load_shard(self, i: int):
        """Host arrays of shard ``i`` (unpadded; X at data-tier width)."""
        from cycloneml_tpu.dataset.dataset import _npz_unpack
        s = self._shards[i]
        z = np.load(s.path)
        x = _npz_unpack(z["x"], z.get("x_dtype", ""))
        return x, z["y"], z["w"]

    def shard_nbytes(self, i: int) -> int:
        s = self._shards[i]
        try:
            return os.path.getsize(s.path)
        except OSError:
            return 0

    # -- lifecycle -------------------------------------------------------------
    def close(self) -> None:
        # latch under the lock: explicit close races __del__ (GC thread),
        # and both passing the check would double-unlink the spill files
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        for s in self._shards:
            try:
                os.unlink(s.path)
            except OSError:
                pass
        if self._owns_dir:
            try:
                os.rmdir(self._dir)
            except OSError:
                pass

    def __del__(self):  # dropped shard sets must not leak the spill dir
        try:
            self.close()
        except Exception:
            pass


def _pad_geometry(ctx, max_shard_rows: int) -> int:
    """Padded rows per staged shard: the max shard rounded up to a
    sublane-friendly multiple of the mesh's data parallelism, so
    ``device_put_sharded_rows`` splits every staged block evenly and one
    compiled program serves every shard."""
    rt = ctx.mesh_runtime
    unit = 8 * int(rt.data_parallelism)
    return ((max(int(max_shard_rows), 1) + unit - 1) // unit) * unit
