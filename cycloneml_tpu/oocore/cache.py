"""Content-hash shard-set reuse: spill once, attach many.

Every CV fold, TrainValidationSplit evaluation and warm-start re-fit over
the same in-core dataset used to re-block and re-write the SAME spill —
an O(n · d) disk write per fit whose bytes the r05 bench pins as the
roofline. The cache keys a spilled :class:`~.shards.StreamingDataset` by
content hash — bounded per-shard-slice reads of the SOURCE dataset
(O(shard) host peak, the JX018 bound) plus the stream tier and the pad
geometry, so a byte-identical re-spill request ATTACHES to the existing
shard files instead: the second fit re-streams 0 spill-write bytes.

Discipline:

- **bounded**: total cached shard bytes ≤ ``cyclone.oocore.cacheBytes``,
  LRU-evicted (0 disables reuse entirely — every attach builds + owns).
- **pinned**: attached handles refcount the entry; a live
  :class:`~.stream.ShardStream` can never have its files evicted from
  under it. Eviction only claims entries with zero outstanding handles.
- **integrity-checked**: per-shard file sha256 captured at insert and
  re-verified at every attach; a mismatch (torn write, disk rot, a chaos
  fault) evicts the entry and rebuilds from source — the fit completes,
  the corruption is counted, never trained on.

Attribution: a hit charges ``cacheHits`` to the calling scope's usage
row; spill WRITE bytes accrue only on builds (the bench's
``cache_hit_restream_bytes == 0`` gate reads exactly these counters).
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from cycloneml_tpu.oocore.shards import StreamingDataset, _pad_geometry
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

#: rows per fingerprint slice — bounded host staging, never O(n · d)
_FP_SLICE_ROWS = 65536


class _Entry:
    __slots__ = ("key", "sds", "nbytes", "shard_hashes", "refs")

    def __init__(self, key: str, sds: StreamingDataset, nbytes: int,
                 shard_hashes: List[str]):
        self.key = key
        self.sds = sds
        self.nbytes = nbytes
        self.shard_hashes = shard_hashes
        self.refs = 0


class _SharedShardSet(StreamingDataset):
    """A non-owning view of a cached shard set: the full
    :class:`StreamingDataset` surface over SHARED files, with ``close()``
    releasing the cache refcount instead of unlinking — so every consumer
    keeps its spill-owns-close discipline (``finally: sds.close()``)
    unchanged while the files outlive the fit for the next attach."""

    def __init__(self, cache: "ShardSetCache", key: str,
                 base: StreamingDataset):
        self.ctx = base.ctx
        self._shards = base._shards
        self.n_features = base.n_features
        self.n_rows = base.n_rows
        self.pad_rows = base.pad_rows
        self._moments = base._moments
        self._dir = base._dir
        self._owns_dir = False
        self.x_dtype = base.x_dtype
        self.x_scale = base.x_scale
        self._closed = False
        self._close_lock = threading.Lock()
        self._cache = cache
        self._cache_key = key

    def close(self) -> None:
        with self._close_lock:
            if self._closed:
                return
            self._closed = True
        self._cache.release(self._cache_key)


def _file_sha256(path: str) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        for block in iter(lambda: f.read(1 << 20), b""):
            h.update(block)
    return h.hexdigest()


def _dataset_fingerprint(ds) -> str:
    """sha256 over the SOURCE dataset's content — x/y/w in bounded row
    slices plus the identity that changes the spilled bytes (shape,
    storage dtype, fp8 scale, valid mask). Memoized on the dataset
    object: the common reuse pattern (CV folds re-fitting one frame's
    dataset) fingerprints once and attaches for free thereafter."""
    fp = getattr(ds, "_oocore_fingerprint", None)
    if fp is not None:
        return fp
    h = hashlib.sha256()
    h.update(f"{ds.shape}|{ds.n_rows}|{np.dtype(str(ds.x.dtype))}".encode())
    n_pad = int(ds.x.shape[0])
    for lo in range(0, n_pad, _FP_SLICE_ROWS):
        hi = min(lo + _FP_SLICE_ROWS, n_pad)
        h.update(np.ascontiguousarray(np.asarray(ds.x[lo:hi])).tobytes())
    h.update(np.ascontiguousarray(
        np.asarray(ds.y_host(), dtype=np.float64)).tobytes())
    h.update(np.ascontiguousarray(
        np.asarray(ds.w_host(), dtype=np.float64)).tobytes())
    scale = getattr(ds, "x_scale", None)
    if scale is not None:
        h.update(np.ascontiguousarray(
            np.asarray(scale, dtype=np.float64)).tobytes())
    mask = getattr(ds, "_valid_mask", None)
    if mask is not None:
        h.update(np.ascontiguousarray(np.asarray(mask)).tobytes())
    fp = h.hexdigest()
    try:
        ds._oocore_fingerprint = fp
    except Exception:
        pass  # a dataset that refuses attributes just re-hashes next time
    return fp


class ShardSetCache:
    """Process-global, byte-bounded, refcounted LRU of spilled shard sets."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.evictions_lru = 0
        self.evictions_corrupt = 0
        self.spill_write_bytes = 0

    # -- the attach point ------------------------------------------------------
    def attach(self, ds, shard_rows: Optional[int] = None,
               spill_dir: Optional[str] = None) -> StreamingDataset:
        """The :func:`engine.shard_dataset` body: return a shard set for
        ``ds``, reusing a cached spill when the content key matches.
        Caller-provided ``spill_dir`` (explicitly placed files) and a
        zero byte bound bypass the cache — the handle then OWNS its
        files, exactly the pre-cache contract."""
        from cycloneml_tpu.conf import OOCORE_CACHE_BYTES
        conf = getattr(ds.ctx, "conf", None)
        bound = int(conf.get(OOCORE_CACHE_BYTES)) if conf is not None \
            else (1 << 30)
        if spill_dir is not None or bound <= 0:
            return StreamingDataset.from_dataset(ds, shard_rows=shard_rows,
                                                 spill_dir=spill_dir)
        key = self._key(ds, shard_rows)
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.refs += 1
                self._entries.move_to_end(key)
        if entry is not None:
            if self._verify(entry):
                with self._lock:
                    self.hits += 1
                from cycloneml_tpu.observe import attribution
                attribution.charge(None, cacheHits=1)
                logger.info("oocore: shard-set cache hit (%d shards, "
                            "0 spill-write bytes)", entry.sds.n_shards)
                return _SharedShardSet(self, key, entry.sds)
            # corrupt: drop our ref, evict, rebuild from source
            with self._lock:
                entry.refs -= 1
                if self._entries.get(key) is entry:
                    del self._entries[key]
                self.evictions_corrupt += 1
            logger.warning(
                "oocore: cached shard set failed its sha256 integrity "
                "check — evicting and rebuilding from source")
            if entry.refs <= 0:
                entry.sds.close()
        return self._build(ds, key, shard_rows, bound)

    def release(self, key: str) -> None:
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                entry.refs = max(entry.refs - 1, 0)

    # -- internals -------------------------------------------------------------
    def _key(self, ds, shard_rows: Optional[int]) -> str:
        from cycloneml_tpu.conf import OOCORE_SHARD_ROWS
        from cycloneml_tpu.oocore.shards import _stream_intent
        conf = getattr(ds.ctx, "conf", None)
        if shard_rows is None:
            shard_rows = int(conf.get(OOCORE_SHARD_ROWS)) \
                if conf is not None else 65536
        shard_rows = max(int(shard_rows), 1)
        # the pad geometry is part of the key: a shard set spilled for one
        # mesh's data parallelism cannot serve a mesh it doesn't divide
        pad_unit = _pad_geometry(ds.ctx, 1)
        from cycloneml_tpu.dataset.instance import data_dtype
        tier = str(np.dtype(data_dtype(conf, fp8_capable=True)))
        ident = "|".join([
            _dataset_fingerprint(ds), _stream_intent(conf), tier,
            str(shard_rows), str(pad_unit), str(ds.n_features)])
        return hashlib.sha256(ident.encode()).hexdigest()

    def _verify(self, entry: _Entry) -> bool:
        try:
            for s, want in zip(entry.sds._shards, entry.shard_hashes):
                if _file_sha256(s.path) != want:
                    return False
            return True
        except OSError:
            return False

    def _build(self, ds, key: str, shard_rows: Optional[int],
               bound: int) -> StreamingDataset:
        with self._lock:
            self.misses += 1
        sds = StreamingDataset.from_dataset(ds, shard_rows=shard_rows)
        hashes = [_file_sha256(s.path) for s in sds._shards]
        nbytes = sum(sds.shard_nbytes(i) for i in range(sds.n_shards))
        entry = _Entry(key, sds, nbytes, hashes)
        entry.refs = 1
        evicted: List[_Entry] = []
        with self._lock:
            self.spill_write_bytes += nbytes
            self._entries[key] = entry
            total = sum(e.nbytes for e in self._entries.values())
            while total > bound:
                victim_key = next(
                    (k for k, e in self._entries.items()
                     if e.refs <= 0 and k != key), None)
                if victim_key is None:
                    break  # everything live is pinned; the bound yields
                victim = self._entries.pop(victim_key)
                evicted.append(victim)
                total -= victim.nbytes
            self.evictions_lru += len(evicted)
        for victim in evicted:
            victim.sds.close()
        return _SharedShardSet(self, key, sds)

    # -- test/ops surface ------------------------------------------------------
    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"hits": self.hits, "misses": self.misses,
                    "evictionsLru": self.evictions_lru,
                    "evictionsCorrupt": self.evictions_corrupt,
                    "spillWriteBytes": self.spill_write_bytes,
                    "entries": len(self._entries),
                    "bytes": sum(e.nbytes
                                 for e in self._entries.values())}

    def clear(self) -> None:
        """Drop every entry and unlink its files (test teardown; entries
        with live handles are dropped from the index — their files die
        when the last handle's base closes via GC)."""
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
        for e in entries:
            e.sds.close()


_cache = ShardSetCache()


def shard_set_cache() -> ShardSetCache:
    return _cache
