"""Generalized linear regression via distributed IRLS.

Re-design of the reference estimator (ref: ml/regression/
GeneralizedLinearRegression.scala:246 — families/links at :557-990,
IRLS driver at ml/optim/IterativelyReweightedLeastSquares.scala): each IRLS
iteration is ONE fused device pass — eta/mu/working-response/working-weights
and the weighted Gramian are computed per block on the MXU and psum'd over
the mesh; the (d+1)×(d+1) augmented normal system is solved on the driver.
The reference instead re-runs a WeightedLeastSquares treeAggregate per
iteration over reweighted instances; collapsing reweight+Gramian into one
jit program removes a full dataset pass per iteration.

Families: gaussian, binomial, poisson, gamma, tweedie(variancePower).
Links: identity, log, logit, inverse, sqrt, probit, cloglog, power(p).
Offset support packs the offset as column 0 of the device block (sliced off
inside the aggregation program) — dense blocks stay the physical unit.
"""

from __future__ import annotations

import math
from typing import Optional

import numpy as np

from cycloneml_tpu.dataset.dataset import InstanceDataset
from cycloneml_tpu.dataset.frame import MLFrame
from cycloneml_tpu.linalg.vectors import DenseVector, Vectors
from cycloneml_tpu.ml.base import PredictionModel, Predictor
from cycloneml_tpu.ml.shared import (
    HasAggregationDepth, HasFitIntercept, HasLabelCol, HasMaxIter,
    HasRegParam, HasSolver, HasTol,
)
from cycloneml_tpu.ml.util_io import MLReadable, MLWritable, load_arrays, save_arrays
from cycloneml_tpu.util.logging import get_logger

logger = get_logger(__name__)

_EPS = 1e-16


# -- families (ref GeneralizedLinearRegression.scala:557-848) -----------------

class Family:
    """Variance/deviance structure of the response distribution.

    All callables take/return jnp arrays so the IRLS aggregation jits.
    ``unit_deviance`` is the per-instance term; ``deviance`` sums w·unit.
    """

    name = "family"
    default_link = "identity"

    def initialize(self, y, w):
        raise NotImplementedError

    def variance(self, mu):
        raise NotImplementedError

    def unit_deviance(self, y, mu):
        raise NotImplementedError

    def deviance(self, y, mu, w):
        import jax.numpy as jnp
        return jnp.sum(w * self.unit_deviance(y, mu))

    def aic(self, y, mu, w, w_sum, deviance, rank):  # driver-side, numpy
        return float("nan")

    def clean_mu(self, mu):
        return mu

    def validate_label(self, y_host: np.ndarray) -> None:
        """Driver-side label-domain check before training (no-op for
        most families; Tweedie enforces the reference's require()s)."""


class Tweedie(Family):
    def __init__(self, variance_power: float):
        self.variance_power = float(variance_power)
        self.name = "tweedie"
        self.default_link = "log" if variance_power != 0 else "identity"

    def initialize(self, y, w):
        import jax.numpy as jnp
        if self.variance_power >= 1.0:
            return jnp.maximum(y, 0.1)
        return y

    def validate_label(self, y_host: np.ndarray) -> None:
        # label-domain validation (ref Tweedie.initialize:624-632): the
        # compound-Poisson band allows y=0; p>=2 needs strictly positive
        # labels — without this, y=0 at p>2 silently NaNs the deviance.
        # Driver-side on the HOST labels (initialize runs inside jit)
        p = self.variance_power
        if 1.0 <= p < 2.0:
            if np.any(y_host < 0):
                raise ValueError(
                    f"tweedie({p}) labels must be non-negative")
        elif p >= 2.0:
            if np.any(y_host <= 0):
                raise ValueError(
                    f"tweedie({p}) labels must be positive")

    def variance(self, mu):
        import jax.numpy as jnp
        return jnp.power(jnp.maximum(mu, _EPS), self.variance_power)

    def unit_deviance(self, y, mu):
        # ref :646 — 2[y(y1^{1-p}−mu^{1-p})/(1−p) − (y^{2-p}−mu^{2-p})/(2−p)];
        # the p∈{0,1,2} limit cases are the Gaussian/Poisson/Gamma
        # subclasses. y floors to delta ONLY in the first term and only
        # for compound-Poisson 1<=p<2 (the reference's deviance:648 — the
        # second term must keep RAW y so a y=0 row contributes its full
        # mu^{2-p}/(2-p) deviance, not a delta-perturbed ~0)
        import jax.numpy as jnp
        p = self.variance_power
        y1 = jnp.maximum(y, 0.1) if 1.0 <= p < 2.0 else y
        return 2.0 * (y * (jnp.power(y1, 1 - p) - jnp.power(mu, 1 - p)) / (1 - p)
                      - (jnp.power(y, 2 - p) - jnp.power(mu, 2 - p)) / (2 - p))

    def clean_mu(self, mu):
        import jax.numpy as jnp
        return jnp.maximum(mu, _EPS) if self.variance_power >= 1 else mu


class Gaussian(Tweedie):
    def __init__(self):
        super().__init__(0.0)
        self.name = "gaussian"
        self.default_link = "identity"

    def initialize(self, y, w):
        return y

    def variance(self, mu):
        import jax.numpy as jnp
        return jnp.ones_like(mu)

    def unit_deviance(self, y, mu):
        return (y - mu) ** 2

    def aic(self, y, mu, w, w_sum, deviance, rank):
        # ref :704-711 (+ summary's 2·rank): numInstances (row COUNT, not
        # weight sum) scales the log-likelihood term, and Σlog w subtracts
        # — R's weighted-gaussian aic
        n = float(len(np.atleast_1d(y)))
        return (n * (math.log(deviance / n * 2.0 * math.pi) + 1.0) + 2.0
                - float(np.sum(np.log(np.maximum(w, _EPS))))
                + 2.0 * rank)

    def clean_mu(self, mu):
        return mu


class Binomial(Family):
    name = "binomial"
    default_link = "logit"

    def initialize(self, y, w):
        return (w * y + 0.5) / (w + 1.0)

    def variance(self, mu):
        return mu * (1.0 - mu)

    def unit_deviance(self, y, mu):
        import jax.numpy as jnp

        def ylogy(yy, m):
            return jnp.where(yy > 0, yy * jnp.log(jnp.maximum(yy / m, _EPS)), 0.0)
        return 2.0 * (ylogy(y, mu) + ylogy(1.0 - y, 1.0 - mu))

    def aic(self, y, mu, w, w_sum, deviance, rank):
        # ref :745-759 — wt=round(w) trials, but successes round y*w with
        # the RAW weight (y=0.7, w=0.7: round(0.49)=0 successes of 1
        # trial, not round(0.7·1)=1)
        from scipy import stats as sps
        # Java math.round = floor(x + 0.5) (half-UP), not numpy's
        # half-even — they diverge on exact .5 trials/successes
        wt = np.floor(w + 0.5).astype(np.int64)
        ok = wt > 0
        ll = sps.binom.logpmf(np.floor(y[ok] * w[ok] + 0.5), wt[ok],
                              np.clip(mu[ok], _EPS, 1 - _EPS))
        return -2.0 * float(ll.sum()) + 2.0 * rank

    def clean_mu(self, mu):
        import jax.numpy as jnp
        return jnp.clip(mu, _EPS, 1.0 - _EPS)


class Poisson(Tweedie):
    def __init__(self):
        super().__init__(1.0)
        self.name = "poisson"
        self.default_link = "log"

    def initialize(self, y, w):
        import jax.numpy as jnp
        return jnp.maximum(y, 0.1)

    def variance(self, mu):
        return mu

    def unit_deviance(self, y, mu):
        import jax.numpy as jnp
        t = jnp.where(y > 0, y * jnp.log(jnp.maximum(y, _EPS) / mu), 0.0)
        return 2.0 * (t - (y - mu))

    def aic(self, y, mu, w, w_sum, deviance, rank):
        from scipy import stats as sps
        ll = w * sps.poisson.logpmf(np.round(y), mu)
        return -2.0 * float(ll.sum()) + 2.0 * rank


class Gamma(Tweedie):
    def __init__(self):
        super().__init__(2.0)
        self.name = "gamma"
        self.default_link = "inverse"

    def initialize(self, y, w):
        import jax.numpy as jnp
        return jnp.maximum(y, 0.1)

    def variance(self, mu):
        return mu * mu

    def unit_deviance(self, y, mu):
        import jax.numpy as jnp
        return -2.0 * (jnp.log(jnp.maximum(y, _EPS) / mu) - (y - mu) / mu)

    def aic(self, y, mu, w, w_sum, deviance, rank):
        from scipy import stats as sps
        disp = deviance / w_sum
        ll = (w * sps.gamma.logpdf(y, 1.0 / disp, scale=mu * disp)).sum()
        return -2.0 * float(ll) + 2.0 * rank + 2.0  # +2 for estimated dispersion


def _make_family(name: str, variance_power: float) -> Family:
    name = name.lower()
    if name == "gaussian":
        return Gaussian()
    if name == "binomial":
        return Binomial()
    if name == "poisson":
        return Poisson()
    if name == "gamma":
        return Gamma()
    if name == "tweedie":
        if variance_power in (0.0, 1.0, 2.0):
            return {0.0: Gaussian(), 1.0: Poisson(), 2.0: Gamma()}[variance_power]
        if variance_power < 0 or 0 < variance_power < 1:
            raise ValueError("variancePower must be 0 or >= 1")
        return Tweedie(variance_power)
    raise ValueError(f"unknown family {name}")


# -- links (ref :850-990) -----------------------------------------------------

class Link:
    name = "link"

    def link(self, mu):
        raise NotImplementedError

    def unlink(self, eta):
        raise NotImplementedError

    def deriv(self, mu):
        """d eta / d mu."""
        raise NotImplementedError


class Identity(Link):
    name = "identity"

    def link(self, mu):
        return mu

    def unlink(self, eta):
        return eta

    def deriv(self, mu):
        import jax.numpy as jnp
        return jnp.ones_like(mu)


class Log(Link):
    name = "log"

    def link(self, mu):
        import jax.numpy as jnp
        return jnp.log(jnp.maximum(mu, _EPS))

    def unlink(self, eta):
        import jax.numpy as jnp
        return jnp.exp(eta)

    def deriv(self, mu):
        return 1.0 / _clip_pos(mu)


class Logit(Link):
    name = "logit"

    def link(self, mu):
        import jax.numpy as jnp
        return jnp.log(mu / (1.0 - mu))

    def unlink(self, eta):
        import jax

        return jax.nn.sigmoid(eta)

    def deriv(self, mu):
        return 1.0 / _clip_pos(mu * (1.0 - mu))


class Inverse(Link):
    name = "inverse"

    def link(self, mu):
        return 1.0 / _clip_pos(mu)

    def unlink(self, eta):
        return 1.0 / _clip_pos(eta)

    def deriv(self, mu):
        return -1.0 / _clip_pos(mu * mu)


class Sqrt(Link):
    name = "sqrt"

    def link(self, mu):
        import jax.numpy as jnp
        return jnp.sqrt(jnp.maximum(mu, 0.0))

    def unlink(self, eta):
        return eta * eta

    def deriv(self, mu):
        import jax.numpy as jnp
        return 0.5 / jnp.sqrt(_clip_pos(mu))


class Probit(Link):
    name = "probit"

    def link(self, mu):
        from jax.scipy.stats import norm
        import jax.scipy.special as jsp
        return jsp.ndtri(mu) if hasattr(jsp, "ndtri") else norm.ppf(mu)

    def unlink(self, eta):
        from jax.scipy.stats import norm
        return norm.cdf(eta)

    def deriv(self, mu):
        from jax.scipy.stats import norm
        import jax.numpy as jnp
        import jax.scipy.special as jsp
        q = jsp.ndtri(mu) if hasattr(jsp, "ndtri") else norm.ppf(mu)
        return 1.0 / jnp.maximum(jnp.exp(norm.logpdf(q)), _EPS)


class CLogLog(Link):
    name = "cloglog"

    def link(self, mu):
        import jax.numpy as jnp
        return jnp.log(-jnp.log(jnp.maximum(1.0 - mu, _EPS)))

    def unlink(self, eta):
        import jax.numpy as jnp
        return 1.0 - jnp.exp(-jnp.exp(eta))

    def deriv(self, mu):
        import jax.numpy as jnp
        om = _clip_pos(1.0 - mu)
        return 1.0 / _clip_pos(-om * jnp.log(om))


class Power(Link):
    def __init__(self, p: float):
        self.p = float(p)
        self.name = f"power({p})"

    def link(self, mu):
        import jax.numpy as jnp
        if self.p == 0.0:
            return jnp.log(_clip_pos(mu))
        return jnp.power(_clip_pos(mu), self.p)

    def unlink(self, eta):
        import jax.numpy as jnp
        if self.p == 0.0:
            return jnp.exp(eta)
        return jnp.power(_clip_pos(eta), 1.0 / self.p)

    def deriv(self, mu):
        import jax.numpy as jnp
        if self.p == 0.0:
            return 1.0 / _clip_pos(mu)
        return self.p * jnp.power(_clip_pos(mu), self.p - 1.0)


def _clip_pos(x):
    import jax.numpy as jnp
    return jnp.where(jnp.abs(x) > _EPS, x, jnp.sign(x) * _EPS + (x == 0) * _EPS)


def _make_link(name: str) -> Link:
    table = {"identity": Identity, "log": Log, "logit": Logit,
             "inverse": Inverse, "sqrt": Sqrt, "probit": Probit,
             "cloglog": CLogLog}
    name = name.lower()
    if name not in table:
        raise ValueError(f"unknown link {name}")
    return table[name]()


_SUPPORTED = {  # ref FamilyAndLink supported combos :532
    "gaussian": {"identity", "log", "inverse"},
    "binomial": {"logit", "probit", "cloglog"},
    "poisson": {"log", "identity", "sqrt"},
    "gamma": {"inverse", "identity", "log"},
}


class _GLRParams(HasMaxIter, HasRegParam, HasTol, HasFitIntercept,
                 HasSolver, HasAggregationDepth, HasLabelCol):
    def _declare_glr_params(self):
        self._p_label_col()
        self._p_max_iter(25)
        self._p_reg_param(0.0)
        self._p_tol(1e-6)
        self._p_fit_intercept(True)
        self._p_solver(["irls"], "irls")
        self._p_aggregation_depth(2)
        from cycloneml_tpu.ml.param import ParamValidators as V
        self._param("family", "response distribution",
                    V.in_array(["gaussian", "binomial", "poisson", "gamma",
                                "tweedie"]), default="gaussian")
        self._param("link", "link function name", default="")
        self._param("variancePower", "tweedie variance power", default=0.0)
        self._param("linkPower", "tweedie link power", default=float("nan"))
        self._param("offsetCol", "offset column", default="")
        self._param("linkPredictionCol", "eta output column", default="")


class GeneralizedLinearRegression(Predictor, _GLRParams, MLWritable, MLReadable):
    """IRLS-trained GLM (ref GeneralizedLinearRegression.scala:246)."""

    MAX_FEATURES = 4096  # ref: WeightedLeastSquares.MAX_NUM_FEATURES

    def __init__(self, uid=None, **kwargs):
        super().__init__(uid)
        self._declare_glr_params()
        for k, v in kwargs.items():
            self.set(k, v)

    def set_family(self, v):
        return self.set("family", v)

    def set_link(self, v):
        return self.set("link", v)

    def set_variance_power(self, v):
        return self.set("variancePower", v)

    def set_link_power(self, v):
        return self.set("linkPower", v)

    def set_reg_param(self, v):
        return self.set("regParam", v)

    def set_max_iter(self, v):
        return self.set("maxIter", v)

    def set_offset_col(self, v):
        return self.set("offsetCol", v)

    def _family_link(self):
        fam = _make_family(self.get("family"), self.get("variancePower"))
        link_name = self.get("link")
        if self.get("family") == "tweedie":
            lp = self.get("linkPower")
            if link_name:
                raise ValueError("use linkPower with the tweedie family")
            if lp != lp:  # nan → canonical 1 - variancePower... ref default log-ish
                lp = 1.0 - self.get("variancePower")
            link = {1.0: Identity(), 0.0: Log(), -1.0: Inverse(), 0.5: Sqrt()}.get(
                lp, Power(lp))
        elif link_name:
            if link_name not in _SUPPORTED.get(fam.name, set()):
                raise ValueError(f"link {link_name} unsupported for {fam.name}")
            link = _make_link(link_name)
        else:
            link = _make_link(fam.default_link)
        return fam, link

    def _fit(self, frame: MLFrame) -> "GeneralizedLinearRegressionModel":
        x = np.asarray(frame[self.get("featuresCol")], dtype=np.float64)
        y = np.asarray(frame[self.get("labelCol")], dtype=np.float64)
        wcol = self.get("weightCol")
        w = np.asarray(frame[wcol], dtype=np.float64) if wcol else np.ones(len(y))
        ocol = self.get("offsetCol")
        offset = np.asarray(frame[ocol], dtype=np.float64) if ocol else None
        return self._fit_arrays(x, y, w, offset)

    def _fit_arrays(self, x, y, w, offset=None) -> "GeneralizedLinearRegressionModel":
        import jax
        import jax.numpy as jnp
        from cycloneml_tpu.context import CycloneContext

        fam, link = self._family_link()
        fam.validate_label(np.asarray(y, dtype=np.float64))
        n, d = x.shape
        if d > self.MAX_FEATURES:
            raise ValueError(f"GLM supports at most {self.MAX_FEATURES} features")
        fit_icpt = self.get("fitIntercept")
        reg = self.get("regParam")
        tol = self.get("tol")
        max_iter = self.get("maxIter")

        has_offset = offset is not None
        # offset rides as column 0 of the device block (see module docstring)
        x_dev = np.concatenate([offset[:, None], x], axis=1) if has_offset else x
        ctx = CycloneContext.get_or_create()
        ds = InstanceDataset.from_numpy(ctx, x_dev, y, w)

        fam_init = fam.initialize
        fam_var = fam.variance
        link_fn, unlink_fn, deriv_fn = link.link, link.unlink, link.deriv
        clean = fam.clean_mu

        def irls_pass(x_blk, y_blk, w_blk, beta, icpt, first):
            ofs = x_blk[:, 0] if has_offset else 0.0
            xf = x_blk[:, 1:] if has_offset else x_blk
            eta_lin = jnp.dot(xf, beta, precision=jax.lax.Precision.HIGHEST) + icpt
            mu0 = clean(fam_init(y_blk, jnp.maximum(w_blk, _EPS)))
            eta = jnp.where(first > 0, link_fn(mu0), eta_lin + ofs)
            mu = clean(unlink_fn(eta))
            g = deriv_fn(mu)
            z = (eta - ofs) + (y_blk - mu) * g
            wi = w_blk / jnp.maximum(g * g * fam_var(mu), _EPS)
            xw = xf * wi[:, None]
            return {
                "xtx": jnp.dot(xw.T, xf, precision=jax.lax.Precision.HIGHEST),
                "xty": jnp.dot(xw.T, z, precision=jax.lax.Precision.HIGHEST),
                "xsum": jnp.sum(xw, axis=0),
                "xsq": jnp.sum(xw * xf, axis=0),
                "wsum": jnp.sum(wi),
                "zsum": jnp.sum(wi * z),
                "dev": fam.deviance(y_blk, mu, w_blk),
            }

        agg = ds.tree_aggregate_fn(irls_pass)

        beta = np.zeros(d)
        icpt = 0.0
        history = []
        w_sum = float(w.sum())
        for it in range(max(max_iter, 1)):
            # one transfer for the whole IRLS stat pytree — this loop was
            # paying NINE separate device->host round trips per iteration
            # (graftlint JX001)
            out = jax.device_get(agg(jnp.asarray(beta), jnp.asarray(icpt),
                                     jnp.asarray(1.0 if it == 0 else 0.0)))
            xtx = np.asarray(out["xtx"], dtype=np.float64)
            xty = np.asarray(out["xty"], dtype=np.float64)
            if fit_icpt:
                a = np.zeros((d + 1, d + 1))
                a[:d, :d] = xtx
                a[:d, d] = a[d, :d] = np.asarray(out["xsum"], dtype=np.float64)
                a[d, d] = float(out["wsum"])
                b = np.concatenate([xty, [float(out["zsum"])]])
            else:
                a, b = xtx, xty
            if reg > 0:
                # ref: each IRLS step runs WeightedLeastSquares with
                # standardizeFeatures=standardizeLabel=true, so the effective
                # original-space penalty is reg · Σwᵢ · σ_j² under the
                # CURRENT working weights (label-std factors cancel, same
                # derivation as LinearRegression._solve_normal)
                ws = float(out["wsum"])
                xm = np.asarray(out["xsum"], dtype=np.float64) / ws
                var_j = np.asarray(out["xsq"], dtype=np.float64) / ws - xm * xm
                idx = np.arange(d)
                a[idx, idx] += reg * ws * np.clip(var_j, 0.0, None)
            try:
                sol = np.linalg.solve(a, b)
            except np.linalg.LinAlgError:
                sol = np.linalg.lstsq(a, b, rcond=None)[0]
            new_beta = sol[:d]
            new_icpt = float(sol[d]) if fit_icpt else 0.0
            old = np.concatenate([beta, [icpt]])
            new = np.concatenate([new_beta, [new_icpt]])
            # ref IRLS convergence: max relative coefficient change
            delta = float(np.max(np.abs(new - old) / np.maximum(np.abs(old), 1e-6)))
            beta, icpt = new_beta, new_icpt
            history.append(float(out["dev"]))
            if it > 0 and delta < tol:
                break

        model = GeneralizedLinearRegressionModel(beta, icpt, uid=self.uid)
        self._copy_values(model)
        model._set_parent(self)
        model.summary = self._summarize(model, x, y, w, offset, fam, link,
                                        len(history))
        return model

    def _summarize(self, model, x, y, w, offset, fam: Family, link: Link,
                   n_iter: int):
        import jax.numpy as jnp

        n, d = x.shape
        fit_icpt = self.get("fitIntercept")
        eta = x @ model._coef + model._icpt + (offset if offset is not None else 0.0)
        mu = np.asarray(fam.clean_mu(link.unlink(jnp.asarray(eta))))
        w_sum = float(w.sum())
        dev = float(fam.deviance(jnp.asarray(y), jnp.asarray(mu), jnp.asarray(w)))

        # null model: intercept-only (with offset if present)
        if fit_icpt:
            null_dev = self._fit_null(y, w, offset, fam, link)
        else:
            eta0 = (offset if offset is not None else np.zeros(n))
            mu0 = np.asarray(fam.clean_mu(link.unlink(jnp.asarray(eta0))))
            null_dev = float(fam.deviance(jnp.asarray(y), jnp.asarray(mu0),
                                          jnp.asarray(w)))

        rank = d + (1 if fit_icpt else 0)
        dof_resid = n - rank
        if fam.name in ("gaussian", "gamma") or (isinstance(fam, Tweedie)
                                                 and fam.name == "tweedie"):
            g = np.asarray(link.deriv(jnp.asarray(mu)))
            var = np.asarray(fam.variance(jnp.asarray(mu)))
            pearson = float((w * (y - mu) ** 2 / np.maximum(var, _EPS)).sum())
            dispersion = pearson / max(dof_resid, 1)
        else:
            dispersion = 1.0
        aic = fam.aic(y, mu, w, w_sum, dev, rank)

        # standard errors from (XᵀWX)⁻¹·φ at the converged weights
        g = np.asarray(link.deriv(jnp.asarray(mu)))
        var = np.asarray(fam.variance(jnp.asarray(mu)))
        wi = w / np.maximum(g * g * var, _EPS)
        xa = np.concatenate([x, np.ones((n, 1))], axis=1) if fit_icpt else x
        xtwx = xa.T @ (xa * wi[:, None])
        try:
            cov = np.linalg.inv(xtwx) * dispersion
            se = np.sqrt(np.clip(np.diag(cov), 0, None))
        except np.linalg.LinAlgError:
            se = np.full(rank, float("nan"))
        coefs = np.concatenate([model._coef, [model._icpt]]) if fit_icpt \
            else model._coef
        tvals = coefs / np.maximum(se, _EPS)
        from scipy import stats as sps
        if fam.name in ("binomial", "poisson"):
            pvals = 2.0 * sps.norm.sf(np.abs(tvals))
        else:
            pvals = 2.0 * sps.t.sf(np.abs(tvals), max(dof_resid, 1))

        return GLMTrainingSummary(
            deviance=dev, null_deviance=null_dev, dispersion=dispersion,
            aic=aic, num_iterations=n_iter, rank=rank,
            degrees_of_freedom=n - 1 if fit_icpt else n,
            residual_degree_of_freedom=dof_resid,
            coefficient_standard_errors=se, t_values=tvals, p_values=pvals,
            prediction_mean=mu, label=y, weights=w, family_obj=fam,
            link_obj=link)

    def _fit_null(self, y, w, offset, fam: Family, link: Link) -> float:
        """Deviance of the intercept-only model (scalar IRLS on the driver)."""
        import jax.numpy as jnp
        mu = np.asarray(fam.initialize(jnp.asarray(y), jnp.asarray(w)))
        mu = np.asarray(fam.clean_mu(jnp.asarray(mu)))
        icpt = 0.0
        ofs = offset if offset is not None else 0.0
        eta = np.asarray(link.link(jnp.asarray(mu)))
        for _ in range(50):
            mu = np.asarray(fam.clean_mu(link.unlink(jnp.asarray(eta))))
            g = np.asarray(link.deriv(jnp.asarray(mu)))
            z = (eta - ofs) + (y - mu) * g
            wi = w / np.maximum(g * g * np.asarray(fam.variance(jnp.asarray(mu))), _EPS)
            new_icpt = float((wi * z).sum() / max(wi.sum(), _EPS))
            if abs(new_icpt - icpt) < 1e-10 * max(abs(icpt), 1.0):
                icpt = new_icpt
                break
            icpt = new_icpt
            eta = icpt + ofs
        mu = np.asarray(fam.clean_mu(link.unlink(jnp.asarray(icpt + ofs))))
        if np.isscalar(mu) or mu.ndim == 0:
            mu = np.full_like(y, float(mu))
        return float(fam.deviance(jnp.asarray(y), jnp.asarray(mu), jnp.asarray(w)))


class GeneralizedLinearRegressionModel(PredictionModel, _GLRParams,
                                       MLWritable, MLReadable):
    def __init__(self, coefficients: Optional[np.ndarray] = None,
                 intercept: float = 0.0, uid=None):
        super().__init__(uid)
        self._declare_glr_params()
        self._coef = np.asarray(coefficients) if coefficients is not None else None
        self._icpt = float(intercept)
        self.summary: Optional[GLMTrainingSummary] = None

    @property
    def coefficients(self) -> DenseVector:
        return Vectors.dense(self._coef)

    @property
    def intercept(self) -> float:
        return self._icpt

    @property
    def num_features(self) -> int:
        return self._coef.shape[0]

    def _predict_batch(self, x: np.ndarray) -> np.ndarray:
        import jax.numpy as jnp
        fam, link = GeneralizedLinearRegression._family_link(self)
        eta = x @ self._coef + self._icpt
        return np.asarray(link.unlink(jnp.asarray(eta)))

    def predict_link(self, x: np.ndarray) -> np.ndarray:
        return x @ self._coef + self._icpt

    def _transform(self, frame: MLFrame) -> MLFrame:
        # offset-trained models add the offset to eta at predict time
        # (ref GeneralizedLinearRegressionModel.predict w/ offset)
        import jax.numpy as jnp
        x = frame[self.get("featuresCol")]
        if x.ndim == 1:
            x = x[:, None]
        eta = x @ self._coef + self._icpt
        ocol = self.get("offsetCol")
        if ocol:
            eta = eta + np.asarray(frame[ocol], dtype=np.float64)
        fam, link = GeneralizedLinearRegression._family_link(self)
        out = frame.with_column(self.get("predictionCol"),
                                np.asarray(link.unlink(jnp.asarray(eta))))
        lcol = self.get("linkPredictionCol")
        if lcol:
            out = out.with_column(lcol, eta)
        return out

    def _save_data(self, path: str) -> None:
        save_arrays(path, coef=self._coef, icpt=np.array(self._icpt))

    def _load_data(self, path: str, meta) -> None:
        arrs = load_arrays(path)
        self._coef = arrs["coef"]
        self._icpt = float(arrs["icpt"])


class GLMTrainingSummary:
    """ref GeneralizedLinearRegressionTrainingSummary."""

    def __init__(self, **kw):
        self.deviance = kw["deviance"]
        self.null_deviance = kw["null_deviance"]
        self.dispersion = kw["dispersion"]
        self.aic = kw["aic"]
        self.num_iterations = kw["num_iterations"]
        self.rank = kw["rank"]
        self.degrees_of_freedom = kw["degrees_of_freedom"]
        self.residual_degree_of_freedom = kw["residual_degree_of_freedom"]
        self.coefficient_standard_errors = kw["coefficient_standard_errors"]
        self.t_values = kw["t_values"]
        self.p_values = kw["p_values"]
        self._mu = kw["prediction_mean"]
        self._y = kw["label"]
        self._w = kw["weights"]
        self._fam: Family = kw["family_obj"]
        self._link: Link = kw["link_obj"]
        self.family = self._fam.name
        self.link = self._link.name

    def residuals(self, residuals_type: str = "deviance") -> np.ndarray:
        import jax.numpy as jnp
        y, mu, w = self._y, self._mu, self._w
        if residuals_type == "response":
            return y - mu
        if residuals_type == "working":
            g = np.asarray(self._link.deriv(jnp.asarray(mu)))
            return (y - mu) * g
        if residuals_type == "pearson":
            var = np.asarray(self._fam.variance(jnp.asarray(mu)))
            return (y - mu) * np.sqrt(w) / np.sqrt(np.maximum(var, _EPS))
        if residuals_type == "deviance":
            dev_i = w * np.asarray(self._fam.unit_deviance(jnp.asarray(y),
                                                           jnp.asarray(mu)))
            return np.sign(y - mu) * np.sqrt(np.clip(dev_i, 0, None))
        raise ValueError(residuals_type)
